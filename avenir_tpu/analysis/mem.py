"""graftlint-mem: static memory-footprint analysis of the streaming layer,
plus the mechanical RSS/live-bytes auditor.

The flow tier (analysis/flow.py) proves streamed folds *deterministic*;
nothing yet proves them *admissible* — the 3,072MB RSS ceiling the scale
runs assert (tools/stream_scale_check.py) is only learned after a
100M-row scan finishes. Both framework papers this repo leans on say
memory is the product once folds are vectorized: buffer sizing dominates
on SIMD-saturated MapReduce (arXiv:1309.0215) and ingest/buffer overhead
is the Spark-vs-MPI gap (arXiv:1811.04875). A resident multi-tenant job
server (the ROADMAP tentpole) therefore needs a memory *oracle*: predict
a job's peak footprint from its block size and schema BEFORE running it.

Two layers, mirroring the ir/flow split:

- **Mem rules** — lexical/structural shapes whose cost is O(corpus)
  instead of O(block): a fold carry that grows with rows seen
  (``mem-unbounded-carry``), a temporary that materializes the whole
  stream (``mem-corpus-scaled-temporary``), an encoded-block spill with
  no byte budget (``mem-cache-spill-unbudgeted``), and a 64-bit widening
  of a block-proportional array on a hot path
  (``mem-dtype-expansion-at-parse``).
- **Analytic footprint model + mechanical audit** —
  :func:`footprint_model` composes, per registered streamed job, the
  host-side byte terms (raw blocks in flight x prefetch depth,
  parse-time dtype expansion, CSR/region-mask transients, fold buffers,
  miner replay/packing pages) into a predicted peak; ``audit_footprint``
  then runs every ``manifest.stream_entries()`` job through the REAL
  runner while a sampler thread watches ``/proc/self/statm`` (and jax
  live buffers where the backend exposes them), asserting at >= 2 block
  sizes that the measured peak sits inside the documented tolerance
  band of the prediction — ``footprint_model_validated`` per job. The
  model is an ADMISSION BOUND: measured must not exceed predicted +
  slack, and predicted must not be vacuous (bounded multiple of
  measured). The byte-accounting hook in ``core.stream`` additionally
  proves the model's effective-block term against the raw blocks that
  actually flowed.

Tolerance policy (documented in docs/graftlint.md): at auditor scale
(about a 1MB proxy corpus) the band's job is to catch order-of-magnitude
model breakage and keep the oracle's mechanics proven every round; the
true model error is recorded at real scale by the
``Mem:PredictedPeakBytes`` / ``Mem:PeakRSS`` counters every 100M-row
anchor writes (tools/stream_scale_check.py).

Findings flow through the shared engine (same ``path::rule::scope``
keys, same allowlist baseline); entry points: ``graftlint --mem``
(analysis/cli.py) or :func:`run_mem` in-process. A stream kernel that
fails to RUN (or a host without ``/proc``) raises :class:`MemAuditError`
— the CLI maps that to exit code 2; a footprint outside the band is a
finding under ``mem-footprint-model`` (exit 1): fix the model or the
job, never allowlist the drift.
"""

from __future__ import annotations

import ast
import math
import os
import shutil
import tempfile
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import (Callable, Dict, Iterator, List, Optional, Sequence, Set,
                    Tuple)

from avenir_tpu.analysis.engine import (BaselineEntry, Finding, ModuleContext,
                                        Report, apply_baseline,
                                        collect_findings)
from avenir_tpu.analysis.flow import _body_nodes, default_flow_paths

#: the audit's pseudo-rule id: a measured peak outside the model's band
#: surfaces as a finding under it (never allowlist one — a memory oracle
#: that mispredicts is worse than none: it admits jobs that OOM)
MEM_AUDIT_RULE = "mem-footprint-model"

#: allocator/compile-residue slack of the tolerance band (bytes): what a
#: warmed-up CPython+jax process may legitimately grow by during one
#: streamed job without the model being wrong (glibc arenas, numpy pool
#: growth, late XLA autotuning buffers)
AUDIT_SLACK_BYTES = 48 << 20
#: non-vacuity bound: predicted must stay within this multiple of
#: (measured + slack), or the "oracle" admits nothing useful
AUDIT_TIGHTNESS = 8.0
#: block sizes (MB) the audit measures at — two layouts whose dominant
#: model term (blocks in flight) differs 8x on the inflated proxy corpus
DEFAULT_AUDIT_BLOCKS_MB = (0.5, 0.0625)
#: the proxy corpus is byte-replicated up to this size so block-
#: proportional terms dominate schema constants at both audit layouts
AUDIT_CORPUS_BYTES = 1 << 20

#: iterator factories whose `for` loops are streamed chunk/fold loops for
#: the mem rules — wider than flow's set: the miners' per-k feeds
#: (chunks/packed_chunks/blocks) are exactly where corpus-scaled state
#: would hide
_MEM_FOLD_TAILS = {
    "double_buffered", "prefetched", "stream_job_inputs",
    "stream_job_lines", "stream_job_byte_blocks", "iter_csv_chunks",
    "iter_byte_blocks", "iter_line_blocks", "scan_encode_blocks",
    "chunks", "packed_chunks", "_dense_chunks", "_row_blocks",
    "_line_blocks", "blocks",
}

_64BIT_DTYPES = {"int64", "float64", "uint64", "complex128", "longdouble"}


class MemAuditError(RuntimeError):
    """A streamed job could not be prepared/run, or RSS is unobservable."""


# --------------------------------------------------------------------------
# shared AST helpers
# --------------------------------------------------------------------------
def _mem_fold_loops(ctx: ModuleContext) -> Iterator[ast.For]:
    """`for` statements iterating a streamed chunk source (the widened
    tail set above) — the loops whose per-iteration state must stay
    O(block)."""
    for node in ast.walk(ctx.tree):
        if not isinstance(node, (ast.For, ast.AsyncFor)):
            continue
        for sub in ast.walk(node.iter):
            if isinstance(sub, ast.Call):
                name = ctx.dotted(sub.func)
                if name is not None \
                        and name.rpartition(".")[2] in _MEM_FOLD_TAILS:
                    yield node
                    break


def _bind_key(node: ast.AST) -> Optional[str]:
    """Identifier key of a binding/receiver: plain names as ``name``,
    self-attributes as ``.attr`` (the flow tier's keying)."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return "." + node.attr
    return None


def _is_empty_container(value: ast.AST) -> bool:
    if isinstance(value, (ast.List, ast.Dict, ast.Set)) \
            and not getattr(value, "elts", getattr(value, "keys", ())):
        return True
    if isinstance(value, ast.Call) and isinstance(value.func, ast.Name) \
            and value.func.id in ("list", "dict", "set") and not value.args:
        return True
    return False


def _empty_inits_before(owner: ast.AST, loop: ast.For) -> Set[str]:
    """Names bound to an EMPTY container in `owner` (not nested defs) at a
    statement starting before `loop` — the carries the loop could grow."""
    out: Set[str] = set()
    stack = list(ast.iter_child_nodes(owner))
    while stack:
        node = stack.pop()
        if node is loop or isinstance(node, (ast.FunctionDef,
                                             ast.AsyncFunctionDef,
                                             ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))
        if getattr(node, "lineno", 10 ** 9) >= loop.lineno:
            continue
        targets: List[ast.AST] = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
        else:
            continue
        if not _is_empty_container(node.value):
            continue
        for t in targets:
            key = _bind_key(t)
            if key is not None:
                out.add(key)
    return out


_GROW_METHODS = {"append", "extend", "update", "add"}
_DRAIN_METHODS = {"clear", "pop", "popitem", "popleft"}


def _loop_growths(loop: ast.For) -> Iterator[Tuple[str, ast.AST]]:
    """(carry key, mutation node) for every growth of a name/self-attr in
    the loop body: ``X.append/extend/update/add``, ``X += ...`` and
    ``X[k] = ...`` (a dict keyed by stream values grows too).
    Subscript receivers fall through to their base name, so
    ``tids[ci].append(...)`` charges ``tids``."""
    for node in _body_nodes(loop):
        if isinstance(node, ast.Call) and isinstance(node.func,
                                                     ast.Attribute) \
                and node.func.attr in _GROW_METHODS:
            base = node.func.value
            if isinstance(base, ast.Subscript):
                base = base.value
            key = _bind_key(base)
            if key is not None:
                yield key, node
        elif isinstance(node, ast.AugAssign) and isinstance(node.op, ast.Add):
            key = _bind_key(node.target)
            if key is not None:
                yield key, node
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Subscript):
                    key = _bind_key(t.value)
                    if key is not None:
                        yield key, node


def _loop_drains(loop: ast.For) -> Set[str]:
    """Carry keys the loop body also RESETS or SHRINKS (reassignment,
    slice-reassignment, clear/pop, del): bounded buffers, not carries —
    the page buffer `buf.extend(rows); buf = buf[block_rows:]` shape."""
    out: Set[str] = set()
    for node in _body_nodes(loop):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                key = _bind_key(t)
                if key is not None:
                    out.add(key)
        elif isinstance(node, ast.Call) and isinstance(node.func,
                                                       ast.Attribute) \
                and node.func.attr in _DRAIN_METHODS:
            key = _bind_key(node.func.value)
            if key is not None:
                out.add(key)
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                base = t.value if isinstance(t, ast.Subscript) else t
                key = _bind_key(base)
                if key is not None:
                    out.add(key)
    return out


def _loop_owner(ctx: ModuleContext, loop: ast.For) -> ast.AST:
    owners = ctx.enclosing_functions(loop)
    return owners[0] if owners else ctx.tree


# --------------------------------------------------------------------------
# rules
# --------------------------------------------------------------------------
class MemRule:
    rule_id: str = ""
    description: str = ""
    hint: str = ""

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        raise NotImplementedError

    def finding(self, ctx: ModuleContext, node: ast.AST, message: str,
                hint: Optional[str] = None) -> Finding:
        return Finding(ctx.path, getattr(node, "lineno", 1), self.rule_id,
                       message, hint or self.hint, ctx.scope_of(node))


class UnboundedCarryRule(MemRule):
    """A container initialized empty BEFORE a streamed fold loop and
    grown inside it (append/extend/update/``+=``/keyed assignment)
    without ever being drained in the loop. Its size tracks rows SEEN,
    not rows per chunk — the fold's host RSS is O(corpus) and the
    O(block) contract the 1B-row path advertises is silently gone.
    Buffers the loop also reassigns/slices/clears are bounded and stay
    silent."""

    rule_id = "mem-unbounded-carry"
    description = "fold carry grows with rows seen, not with the chunk"
    hint = ("fold a fixed-size sufficient statistic instead (counts, "
            "moments — the NaiveBayesModel.accumulate algebra), write "
            "per-chunk results out as you go, or drain the buffer inside "
            "the loop; allowlist only when the corpus-sized output IS the "
            "job's contract")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for loop in _mem_fold_loops(ctx):
            owner = _loop_owner(ctx, loop)
            carries = _empty_inits_before(owner, loop)
            if not carries:
                continue
            drains = _loop_drains(loop)
            seen: Set[str] = set()
            for key, node in _loop_growths(loop):
                if key not in carries or key in drains or key in seen:
                    continue
                seen.add(key)
                yield self.finding(
                    ctx, node,
                    f"`{key.lstrip('.')}` is grown once per streamed "
                    f"chunk and never drained: the fold carry scales "
                    f"with rows seen, so host RSS is O(corpus), not "
                    f"O(block)")


class CorpusScaledTemporaryRule(MemRule):
    """``np.concatenate``/``vstack``/``hstack``/``stack`` (or
    ``np.array``/``np.asarray``) over a list that a streamed fold loop
    appends to: one expression that materializes the WHOLE stream as a
    single array — the exact shape whose deletion was PR 1's biggest RSS
    win, reintroduced one level up."""

    rule_id = "mem-corpus-scaled-temporary"
    description = "temporary proportional to the full corpus in a streamed fold"
    hint = ("reduce per chunk instead of collecting (fold the statistic, "
            "write results incrementally); if a whole-stream array is "
            "truly required, the job is not streamable — say so in its "
            "contract and allowlist with that justification")

    _MATERIALIZERS = {"concatenate", "vstack", "hstack", "stack", "array",
                      "asarray"}

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for loop in _mem_fold_loops(ctx):
            owner = _loop_owner(ctx, loop)
            grown = {key for key, _ in _loop_growths(loop)} \
                - _loop_drains(loop)
            if not grown:
                continue
            for node in ast.walk(owner):
                if not isinstance(node, ast.Call) or not node.args:
                    continue
                name = ctx.dotted(node.func)
                if name is None:
                    continue
                mod, _, func = name.rpartition(".")
                if mod not in ("numpy", "jax.numpy") \
                        or func not in self._MATERIALIZERS:
                    continue
                arg = node.args[0]
                key = _bind_key(arg)
                if key in grown:
                    yield self.finding(
                        ctx, node,
                        f"np.{func}(`{key.lstrip('.')}`) materializes "
                        f"every streamed chunk as one array — a "
                        f"corpus-proportional temporary inside a "
                        f"streamed fold")


class CacheSpillUnbudgetedRule(MemRule):
    """An ``EncodedBlockCache`` constructed without an explicit
    ``byte_budget``. The spill cache writes region-compacted codes for
    EVERY block of the corpus; unbudgeted, a 1B-row scan spills O(corpus)
    bytes to disk (and the job server's cache pool grows without bound).
    The budget is cheap to pass — the cache evicts whole
    least-recently-replayed sources atomically when it is exceeded."""

    rule_id = "mem-cache-spill-unbudgeted"
    description = "EncodedBlockCache spill with no byte budget"
    hint = ("pass byte_budget= (the stream.encoded.cache.budget.mb "
            "config key is the job surface; native.ingest."
            "DEFAULT_CACHE_BUDGET_BYTES is the generous default), so "
            "the spill is bounded and evictable")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = ctx.dotted(node.func)
            if name is None \
                    or name.rpartition(".")[2] != "EncodedBlockCache":
                continue
            if len(node.args) >= 3 or any(kw.arg == "byte_budget"
                                          for kw in node.keywords):
                continue
            yield self.finding(
                ctx, node,
                "EncodedBlockCache(...) without byte_budget: the "
                "encoded-block spill grows with the corpus, unbounded "
                "and unevictable")


class DtypeExpansionAtParseRule(MemRule):
    """A 64-bit widening of an existing array on a hot path (lexically
    inside a loop): ``x.astype(np.int64/np.float64/float/int)`` or
    ``np.asarray/np.array(x, dtype=<64-bit>)``. Between parse and device
    every element is supposed to NARROW (codes int32, measures float32);
    an 8-byte widening of a block-proportional array doubles the very
    buffers the streaming layer exists to keep small. Fresh 64-bit
    ALLOCATIONS (``np.zeros(..., np.int64)`` count tensors) are a
    deliberate exact-algebra choice and stay silent — this rule is about
    conversions."""

    rule_id = "mem-dtype-expansion-at-parse"
    description = "64-bit widening of an array on a streamed hot path"
    hint = ("keep block-proportional arrays narrow end to end (int32 "
            "codes, float32 measures — the csr_region_mask form); widen "
            "only O(model)-sized results, outside the loop, or allowlist "
            "with the bound that makes the widening noise")

    _WRAPPERS = {"numpy.asarray", "numpy.array", "jax.numpy.asarray",
                 "jax.numpy.array"}

    def _dtype_is_wide(self, ctx: ModuleContext, node: ast.AST) -> bool:
        name = ctx.dotted(node)
        if name is not None:
            tail = name.rpartition(".")[2]
            return tail in _64BIT_DTYPES or name in ("float", "int")
        return isinstance(node, ast.Constant) \
            and str(node.value) in _64BIT_DTYPES

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call) or not ctx.in_loop(node):
                continue
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "astype" and node.args:
                if self._dtype_is_wide(ctx, node.args[0]):
                    yield self.finding(
                        ctx, node,
                        ".astype(<64-bit>) inside a loop doubles a "
                        "block-proportional array on the hot path")
                continue
            name = ctx.dotted(node.func)
            if name not in self._WRAPPERS:
                continue
            dtype = next((kw.value for kw in node.keywords
                          if kw.arg == "dtype"), None)
            if dtype is None and len(node.args) > 1:
                dtype = node.args[1]
            if dtype is not None and self._dtype_is_wide(ctx, dtype):
                yield self.finding(
                    ctx, node,
                    f"{name.rpartition('.')[2]}(..., dtype=<64-bit>) "
                    f"inside a loop widens the array it wraps to 8-byte "
                    f"elements on the hot path")


ALL_MEM_RULES = [UnboundedCarryRule, CorpusScaledTemporaryRule,
                 CacheSpillUnbudgetedRule, DtypeExpansionAtParseRule]


def mem_rule_ids() -> List[str]:
    return [r.rule_id for r in ALL_MEM_RULES] + [MEM_AUDIT_RULE]


# --------------------------------------------------------------------------
# corpus statistics (what the analytic model derives its terms from)
# --------------------------------------------------------------------------
@dataclass(frozen=True)
class CorpusStats:
    """Cheap head-sample statistics of a CSV/sequence corpus: everything
    the footprint model needs, gathered without a full scan (the model
    must be usable BEFORE admission — that is its point)."""

    total_bytes: int
    rows: int                 # extrapolated from the sample's avg row
    avg_row_bytes: float
    avg_fields: float         # delimited fields per row (meta included)
    distinct_tokens: int      # non-leading-field vocab estimate (capped)

    def to_json(self) -> dict:
        return {"total_bytes": self.total_bytes, "rows": self.rows,
                "avg_row_bytes": round(self.avg_row_bytes, 2),
                "avg_fields": round(self.avg_fields, 2),
                "distinct_tokens": self.distinct_tokens}


def corpus_stats(paths: Sequence[str], delim: str = ",",
                 sample_bytes: int = 256 << 10) -> CorpusStats:
    """Sample the head of the first input (whole lines only) and
    extrapolate; token vocabulary estimate excludes each row's leading
    field (ids never dictionary-encode) and caps at 4096."""
    total = sum(os.path.getsize(p) for p in paths)
    with open(paths[0], "rb") as fh:
        head = fh.read(sample_bytes)
    cut = head.rfind(b"\n")
    if cut > 0:
        head = head[:cut + 1]
    lines = [ln for ln in head.decode("utf-8", "replace").split("\n")
             if ln.strip()]
    n = max(len(lines), 1)
    avg_row = max(len(head) / n, 1.0)
    fields = sum(ln.count(delim) + 1 for ln in lines) / n
    vocab: Set[str] = set()
    for ln in lines:
        for tok in ln.split(delim)[1:]:
            vocab.add(tok.strip(" \t\r"))
            if len(vocab) >= 4096:
                break
        if len(vocab) >= 4096:
            break
    return CorpusStats(total_bytes=total, rows=int(total / avg_row),
                       avg_row_bytes=avg_row, avg_fields=max(fields, 1.0),
                       distinct_tokens=max(len(vocab), 1))


def _unbounded_stats(avg_row_bytes: float = 40.0, avg_fields: float = 8.0,
                     distinct_tokens: int = 64) -> CorpusStats:
    """Stats for the admission manifest's nominal corpus: effectively
    unbounded size, so every block-proportional term prices a FULL block
    — the upper-bound posture an admission oracle needs."""
    return CorpusStats(total_bytes=1 << 62, rows=1 << 40,
                       avg_row_bytes=avg_row_bytes, avg_fields=avg_fields,
                       distinct_tokens=distinct_tokens)


# --------------------------------------------------------------------------
# analytic footprint model
# --------------------------------------------------------------------------
@dataclass
class FootprintEstimate:
    """One job's predicted peak incremental host bytes at one block size,
    decomposed into named terms so a drifted prediction is debuggable
    (which buffer grew?) instead of a bare number."""

    job: str
    block_bytes: int
    terms: Dict[str, int] = field(default_factory=dict)

    @property
    def total_bytes(self) -> int:
        return int(sum(self.terms.values()))

    def to_json(self) -> dict:
        return {"job": self.job, "block_bytes": self.block_bytes,
                "predicted_peak_bytes": self.total_bytes,
                "predicted_peak_mb": round(self.total_bytes / (1 << 20), 2),
                "terms": {k: int(v) for k, v in sorted(self.terms.items())}}


def _pow2ceil(x: float, lo: int) -> int:
    return max(lo, 1 << max(int(x) - 1, 0).bit_length())


def _schema_cols(schema) -> Tuple[int, int, int]:
    """(numeric, categorical, string/id) column counts of a FeatureSchema
    (defaults approximate the churn shape when no schema is known)."""
    if schema is None:
        return 1, 5, 1
    n_num = sum(1 for f in schema if f.is_numeric)
    n_cat = sum(1 for f in schema if f.is_categorical)
    return n_num, n_cat, max(len(list(schema)) - n_num - n_cat, 0)


def _eff_block(stats: CorpusStats, block_bytes: int) -> int:
    """A block never exceeds the corpus: the reader cuts at EOF."""
    return max(1, min(int(block_bytes), stats.total_bytes))


#: default depth of the outer prefetched() job feeds (the
#: `stream.prefetch.depth` conf key; core.stream.DEFAULT_PREFETCH_DEPTH)
DEFAULT_MODEL_PREFETCH_DEPTH = 2


def _dataset_ingest(stats: CorpusStats, block_bytes: int, schema,
                    prefetch_depth: int = DEFAULT_MODEL_PREFETCH_DEPTH
                    ) -> Dict[str, int]:
    """Shared-schema Dataset ingest: CsvBlockReader's inner depth-1 byte
    prefetch (producer copy + queued + parsing = 3 raw blocks), the
    native parse writing float32/int32 column outputs plus the lazy
    string-column raw bytes, and the outer depth-D Dataset prefetch of
    stream_job_inputs (D queued + producing + consuming = D+2 parsed
    chunks; D is the `stream.prefetch.depth` key, default 2)."""
    eff = _eff_block(stats, block_bytes)
    rows = eff / stats.avg_row_bytes
    n_num, n_cat, n_str = _schema_cols(schema)
    depth = max(int(prefetch_depth), 1)
    parsed = rows * 4.0 * (n_num + n_cat) + 0.3 * eff * max(n_str, 1)
    return {
        "raw_blocks_in_flight": int(3 * eff),
        "parse_transient": int(parsed),
        "parsed_chunks_in_flight": int((depth + 2) * parsed),
        # columnar-sidecar transient, one block either way: the cold
        # pass serializes the parsed block before appending it to
        # columns.bin; the warm pass materializes one block's columns
        # from the replay read
        "sidecar_pages": int(eff),
    }


def _bytes_ingest(stats: CorpusStats, block_bytes: int,
                  prefetch_depth: int = DEFAULT_MODEL_PREFETCH_DEPTH
                  ) -> Dict[str, int]:
    """Raw byte-block ingest for the sequence-shaped jobs: depth-D
    outer prefetch (D queued + producing + consuming = D+2 raw blocks
    in flight; D = `stream.prefetch.depth`, default 2) plus the CSR
    encode transients — int32 codes + int32 row_of + bool region per
    token, int64 offsets/starts per row, and one decoded copy on the
    vocabulary-extension path. Without the native encoder every token
    becomes a Python string (~64B each), and the model says so."""
    eff = _eff_block(stats, block_bytes)
    rows = eff / stats.avg_row_bytes
    toks = rows * stats.avg_fields
    depth = max(int(prefetch_depth), 1)
    terms = {
        "raw_blocks_in_flight": int((depth + 2) * eff),
        "csr_transients": int(toks * 9 + rows * 16 + eff),
        # columnar-sidecar transient (write-side serialize / read-side
        # materialize of ONE block's encoded columns)
        "sidecar_pages": int(eff),
    }
    try:
        from avenir_tpu.native.ingest import native_available
        native = native_available()
    except Exception:
        native = False
    if not native:
        terms["python_tokenize"] = int(toks * 64)
    return terms


def _model_nb(stats, block_bytes, schema,
              prefetch_depth=DEFAULT_MODEL_PREFETCH_DEPTH) -> Dict[str, int]:
    t = _dataset_ingest(stats, block_bytes, schema, prefetch_depth)
    rows = _eff_block(stats, block_bytes) / stats.avg_row_bytes
    n_num, n_cat, _ = _schema_cols(schema)
    # deferred-fold code matrix per chunk (host int32 + device copy)
    t["nb_fold_buffers"] = int(rows * 4 * (n_num + n_cat) * 2)
    t["nb_model_state"] = 1 << 20
    return t


def _model_mi(stats, block_bytes, schema,
              prefetch_depth=DEFAULT_MODEL_PREFETCH_DEPTH) -> Dict[str, int]:
    t = _dataset_ingest(stats, block_bytes, schema, prefetch_depth)
    rows = _eff_block(stats, block_bytes) / stats.avg_row_bytes
    # per-pair bincount keys (int64) and their intp cast, per chunk
    t["mi_pair_keys"] = int(rows * 8 * 2)
    t["mi_tables"] = 1 << 20
    return t


def _model_fisher(stats, block_bytes, schema,
                  prefetch_depth=DEFAULT_MODEL_PREFETCH_DEPTH
                  ) -> Dict[str, int]:
    t = _dataset_ingest(stats, block_bytes, schema, prefetch_depth)
    t["fisher_moments"] = 1 << 20
    return t


def _model_markov(stats, block_bytes, schema,
                  prefetch_depth=DEFAULT_MODEL_PREFETCH_DEPTH
                  ) -> Dict[str, int]:
    t = _bytes_ingest(stats, block_bytes, prefetch_depth)
    t["markov_counts"] = 1 << 20
    return t


def _miner_common(stats: CorpusStats, block_bytes: int,
                  prefetch_depth: int = DEFAULT_MODEL_PREFETCH_DEPTH
                  ) -> Dict[str, int]:
    """Pass-1 scan + spill write + per-k replay transients shared by both
    miners: the replay pass re-reads narrow codes + per-row counts and
    re-expands them to int32 working arrays."""
    t = _bytes_ingest(stats, block_bytes, prefetch_depth)
    eff = _eff_block(stats, block_bytes)
    rows = eff / stats.avg_row_bytes
    toks = rows * stats.avg_fields
    t["replay_transients"] = int(toks * (1 + 4 + 4) + rows * 16)
    return t


def _model_apriori(stats, block_bytes, schema,
                   prefetch_depth=DEFAULT_MODEL_PREFETCH_DEPTH
                   ) -> Dict[str, int]:
    t = _miner_common(stats, block_bytes, prefetch_depth)
    v = stats.distinct_tokens
    words = max((v + 31) // 32, 1)
    c_pad = _pow2ceil(min(v * v, 4096), 64)
    # uint8 multi-hot page + packed bitset page, double-buffered + device
    t["apriori_pages"] = int(3 * 8192 * (v + 4 * words))
    t["apriori_candidates"] = int(c_pad * (4 * words + 8))
    return t


def _model_gsp(stats, block_bytes, schema,
               prefetch_depth=DEFAULT_MODEL_PREFETCH_DEPTH) -> Dict[str, int]:
    t = _miner_common(stats, block_bytes, prefetch_depth)
    eff = _eff_block(stats, block_bytes)
    rows_page = _pow2ceil(min(eff / stats.avg_row_bytes, 65536), 1024)
    t_bucket = _pow2ceil(stats.avg_fields, 16)
    c_pad = _pow2ceil(min(stats.distinct_tokens ** 2, 4096), 16)
    # padded int32 pages (double buffer + device) and the scan kernel's
    # [rows, candidates] pointer state + hit temporaries on device
    t["gsp_pages"] = int(3 * rows_page * t_bucket * 4)
    t["gsp_scan_state"] = int(3 * rows_page * c_pad * 4)
    return t


#: canonical runner job name -> term builder(stats, block_bytes, schema)
_JOB_MODELS: Dict[str, Callable] = {
    "bayesianDistr": _model_nb,
    "mutualInformation": _model_mi,
    "fisherDiscriminant": _model_fisher,
    "markovStateTransitionModel": _model_markov,
    "frequentItemsApriori": _model_apriori,
    "candidateGenerationWithSelfJoin": _model_gsp,
}

#: the ingest terms shared by every sink of one fused scan — counted
#: once (max across jobs) when jobs fuse, exactly like the scan itself
_INGEST_TERMS = {"raw_blocks_in_flight", "parse_transient",
                 "parsed_chunks_in_flight", "csr_transients",
                 "python_tokenize", "sidecar_pages"}


def footprint_model(job: str, block_bytes: int, schema=None,
                    stats: Optional[CorpusStats] = None,
                    prefetch_depth: int = DEFAULT_MODEL_PREFETCH_DEPTH
                    ) -> FootprintEstimate:
    """Predicted peak incremental host bytes of one registered streamed
    job at `block_bytes` with `prefetch_depth` queued chunks (the
    `stream.prefetch.depth` key — the in-flight terms scale with it, so
    an autotuned depth re-prices admission honestly). With no `stats`
    the corpus is assumed unbounded (every block term prices a full
    block) — the admission-oracle posture the memory manifest exports."""
    if job not in _JOB_MODELS:
        raise ValueError(
            f"no footprint model for job {job!r}; modeled jobs: "
            f"{', '.join(sorted(_JOB_MODELS))}")
    st = stats if stats is not None else _unbounded_stats()
    terms = _JOB_MODELS[job](st, int(block_bytes), schema,
                             max(int(prefetch_depth), 1))
    return FootprintEstimate(job, int(block_bytes),
                             {k: int(v) for k, v in terms.items()})


def combined_footprint(jobs: Sequence[str], block_bytes: int, schema=None,
                       stats: Optional[CorpusStats] = None,
                       prefetch_depth: int = DEFAULT_MODEL_PREFETCH_DEPTH
                       ) -> FootprintEstimate:
    """Footprint of N jobs fused on ONE shared scan: ingest terms are
    paid once (the scan-sharing executor's whole point), per-job state
    terms sum, prefixed by job so the decomposition stays readable."""
    ests = [footprint_model(j, block_bytes, schema, stats, prefetch_depth)
            for j in jobs]
    terms: Dict[str, int] = {}
    for est in ests:
        for k, v in est.terms.items():
            if k in _INGEST_TERMS:
                terms[k] = max(terms.get(k, 0), v)
            else:
                terms[f"{est.job}:{k}" if len(ests) > 1 else k] = v
    return FootprintEstimate("+".join(jobs), int(block_bytes), terms)


# --------------------------------------------------------------------------
# device-side live bytes of the kernel manifest
# --------------------------------------------------------------------------
def _aval_bytes(v) -> int:
    aval = getattr(v, "aval", None)
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    return int(math.prod(shape)) * int(dtype.itemsize)


def _lower_compiled(fn, args):
    """Lower + compile one manifest entry for its buffer assignment —
    wrapping plain-op entries in a fresh jit (each entry is distinct and
    compiled exactly once, so the wrapper's empty cache is the point,
    not a hazard)."""
    import jax

    lowered = (fn.lower(*args) if hasattr(fn, "lower")
               else jax.jit(fn).lower(*args))
    return lowered.compile()


def kernel_device_entries(entries: Optional[Sequence] = None) -> List[dict]:
    """Per manifest kernel: argument/output/temp bytes and their peak sum
    — the device half of the memory manifest. Temp bytes come from the
    compiled HLO buffer assignment (``compiled.memory_analysis()``, the
    PR-3 lowering harness) where the backend exposes it; otherwise the
    largest single equation output of the traced jaxpr stands in, and
    the row says which source it used. Distributed families lower on the
    audit mesh and are skipped (with a note) when the device pool is too
    small — a partial manifest must say it is partial."""
    import jax

    from avenir_tpu.analysis.ir import _audit_mesh, iter_eqns
    from avenir_tpu.analysis.manifest import AUDIT_DEVICES, manifest_entries

    devices = jax.devices()
    rows: List[dict] = []
    for spec in (list(entries) if entries is not None
                 else manifest_entries()):
        if spec.is_family and len(devices) < AUDIT_DEVICES:
            rows.append({"kernel": spec.name, "path": spec.path,
                         "skipped": f"needs {AUDIT_DEVICES} devices, "
                                    f"found {len(devices)}"})
            continue
        mesh = _audit_mesh(spec, devices) if spec.is_family else None
        fn, args = spec.build(mesh)
        jaxpr = jax.make_jaxpr(fn)(*args)
        arg_b = sum(_aval_bytes(v) for v in jaxpr.jaxpr.invars)
        out_b = sum(_aval_bytes(v) for v in jaxpr.jaxpr.outvars)
        temp_b, source = None, "jaxpr"
        try:
            ma = _lower_compiled(fn, args).memory_analysis()
            if ma is not None:
                temp_b = int(getattr(ma, "temp_size_in_bytes", 0))
                arg_b = int(getattr(ma, "argument_size_in_bytes", arg_b))
                out_b = int(getattr(ma, "output_size_in_bytes", out_b))
                source = "hlo_buffer_assignment"
        except Exception:
            pass
        if temp_b is None:
            temp_b = max((sum(_aval_bytes(o) for o in eqn.outvars)
                          for eqn, _ in iter_eqns(jaxpr.jaxpr)), default=0)
        rows.append({
            "kernel": spec.name, "path": spec.path,
            "family": bool(spec.is_family),
            "argument_bytes": arg_b, "output_bytes": out_b,
            "temp_bytes": temp_b,
            "peak_live_bytes": arg_b + out_b + temp_b,
            "source": source,
        })
    return rows


def memory_manifest(block_sizes_mb: Sequence[float] = (64.0, 8.0),
                    include_kernels: bool = True) -> dict:
    """The machine-readable memory manifest — the admission oracle the
    future job server consumes: per streamed job x block size, the
    predicted peak host bytes against a nominal unbounded corpus (churn
    schema for the tabular jobs); plus the per-kernel device live bytes.
    Written next to STREAM_SCALE_*.json by bench_scaling's tripwire."""
    from avenir_tpu.data import churn_schema

    schema = churn_schema()
    tabular = {"bayesianDistr", "mutualInformation", "fisherDiscriminant"}
    jobs: Dict[str, dict] = {}
    for job in sorted(_JOB_MODELS):
        per_block = {}
        for mb in block_sizes_mb:
            est = footprint_model(job, int(mb * (1 << 20)),
                                  schema if job in tabular else None)
            per_block[f"{mb:g}MB"] = est.to_json()
        jobs[job] = per_block
    out = {
        "version": 1,
        "tolerance": {"slack_bytes": AUDIT_SLACK_BYTES,
                      "tightness": AUDIT_TIGHTNESS,
                      "policy": "measured <= predicted + slack and "
                                "predicted <= tightness * (measured + "
                                "slack), at >= 2 block sizes"},
        "jobs": jobs,
    }
    if include_kernels:
        out["kernels"] = kernel_device_entries()
    return out


# --------------------------------------------------------------------------
# mechanical audit: sampled RSS vs the model
# --------------------------------------------------------------------------
_STATM = "/proc/self/statm"


def _read_rss_bytes() -> int:
    try:
        with open(_STATM) as fh:
            return int(fh.read().split()[1]) * (os.sysconf("SC_PAGE_SIZE")
                                                or 4096)
    except (OSError, IndexError, ValueError) as e:
        raise MemAuditError(
            f"cannot sample RSS from {_STATM}: {e!r} (the footprint "
            f"auditor needs a Linux procfs)") from e


class _RssSampler:
    """Background thread sampling resident bytes (and, every few ticks,
    jax live device-buffer bytes where the backend exposes them) while
    one streamed job runs. The peaks are worker-private while sampling
    and exposed through read-only properties — the auditor reads them
    only after ``__exit__`` joined the thread, so there is no shared
    mutable surface mid-run (our own flow-shared-state-unlocked rule
    applies to this module too)."""

    def __init__(self, interval: float = 0.004):
        self.interval = interval
        self._peak_rss = 0
        self._peak_live = 0
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)

    @property
    def peak_rss(self) -> int:
        return self._peak_rss

    @property
    def peak_live(self) -> int:
        return self._peak_live

    def _loop(self) -> None:
        tick = 0
        while not self._stop.is_set():
            try:
                self._peak_rss = max(self._peak_rss, _read_rss_bytes())
            except MemAuditError:
                break
            if tick % 16 == 0:
                try:
                    import jax
                    self._peak_live = max(
                        self._peak_live,
                        sum(int(a.nbytes) for a in jax.live_arrays()))
                except Exception:
                    pass
            tick += 1
            self._stop.wait(self.interval)

    def __enter__(self) -> "_RssSampler":
        self._thread.start()
        return self

    def __exit__(self, *exc) -> None:
        self._stop.set()
        self._thread.join(5.0)


class _BlockRecorder:
    """core.stream byte-accounting consumer: the largest raw byte block
    any prefetch worker produced — the mechanical proof that the model's
    effective-block term matches the blocks that actually flowed."""

    def __init__(self):
        self.max_bytes = 0
        self._lock = threading.Lock()

    def __call__(self, n: int) -> None:
        if n:
            with self._lock:
                if n > self.max_bytes:
                    self.max_bytes = n


@contextmanager
def _bytes_hook(recorder):
    from avenir_tpu.core import stream

    prev = stream._bytes_hook
    stream._bytes_hook = recorder
    try:
        yield
    finally:
        stream._bytes_hook = prev


def _inflate_corpus(ctx: dict, target_bytes: int) -> dict:
    """Byte-replicate the spec's seeded corpus up to `target_bytes` (the
    jobs are line-streamed; replication preserves every row shape) so
    block-proportional terms dominate at audit block sizes."""
    src = ctx["csv"]
    with open(src, "rb") as fh:
        blob = fh.read()
    if not blob:
        raise MemAuditError(f"audit corpus {src!r} is empty")
    reps = max(1, -(-target_bytes // len(blob)))
    if reps == 1:
        return ctx
    big = os.path.join(ctx["dir"], "inflated.csv")
    with open(big, "wb") as fh:
        for _ in range(reps):
            fh.write(blob)
    out = dict(ctx)
    out["csv"] = big
    return out


def audit_footprint(spec, block_sizes_mb: Optional[Sequence[float]] = None,
                    model_fn: Optional[Callable] = None,
                    inflate_to: int = AUDIT_CORPUS_BYTES
                    ) -> Tuple[dict, Optional[Finding]]:
    """Run one streamed job at >= 2 block sizes on its (inflated) proxy
    corpus, sampling peak RSS, and judge the analytic prediction's band
    at every size. Each size runs TWICE: the first run absorbs jit
    compiles and allocator growth for that exact layout, the second is
    measured — the model predicts steady-state transients, not one-time
    runtime warmup. Returns (audit row, band-violation finding or None);
    a job that fails to run raises :class:`MemAuditError`."""
    sizes = [float(mb) for mb in (block_sizes_mb or DEFAULT_AUDIT_BLOCKS_MB)]
    if len(sizes) < 2:
        raise MemAuditError(
            f"{spec.name}: the footprint audit needs >= 2 block sizes, "
            f"got {sizes}")
    workdir = tempfile.mkdtemp(prefix=f"graftlint_mem_{spec.name}_")
    per_size: List[dict] = []
    try:
        ctx = spec.prepare(workdir)
        ctx = _inflate_corpus(ctx, inflate_to)
        stats = corpus_stats([ctx["csv"]])
        schema = None
        if "schema" in ctx:
            from avenir_tpu.core.schema import FeatureSchema
            schema = FeatureSchema.from_file(ctx["schema"])
        if model_fn is None:
            jobs = list(spec.jobs)
            if not jobs:
                raise MemAuditError(
                    f"{spec.name}: stream entry names no runner jobs; "
                    f"the footprint model is keyed on them")
            model_fn = lambda bb: combined_footprint(  # noqa: E731
                jobs, bb, schema, stats)
        for mb in sizes:
            bb = int(mb * (1 << 20))
            est = model_fn(bb)
            recorder = _BlockRecorder()
            with _bytes_hook(recorder):
                spec.run(ctx, mb)              # warmup: compile + arenas
                rss0 = _read_rss_bytes()
                t0 = time.perf_counter()
                with _RssSampler() as sampler:
                    spec.run(ctx, mb)
                dt = time.perf_counter() - t0
            measured = max(0, max(sampler.peak_rss, rss0) - rss0)
            predicted = est.total_bytes
            upper_ok = measured <= predicted + AUDIT_SLACK_BYTES
            lower_ok = predicted <= AUDIT_TIGHTNESS * (
                measured + AUDIT_SLACK_BYTES)
            eff = _eff_block(stats, bb)
            block_ok = (recorder.max_bytes == 0
                        or recorder.max_bytes <= eff + 65536)
            per_size.append({
                "block_mb": mb,
                "predicted_bytes": predicted,
                "predicted_mb": round(predicted / (1 << 20), 2),
                "measured_bytes": measured,
                "measured_mb": round(measured / (1 << 20), 2),
                "peak_live_device_bytes": sampler.peak_live,
                "observed_max_block_bytes": recorder.max_bytes,
                "terms": est.to_json()["terms"],
                "seconds": round(dt, 3),
                "within_band": upper_ok and lower_ok,
                "block_accounting_ok": block_ok,
            })
    except MemAuditError:
        raise
    except Exception as e:
        raise MemAuditError(
            f"{spec.name}: streamed job failed to run: {e!r}") from e
    finally:
        shutil.rmtree(workdir, ignore_errors=True)

    ok = all(s["within_band"] and s["block_accounting_ok"]
             for s in per_size)
    row = {
        "kernel": spec.name,
        "jobs": list(getattr(spec, "jobs", ()) or ()),
        "corpus": stats.to_json(),
        "block_sizes_mb": sizes,
        "tolerance": {"slack_bytes": AUDIT_SLACK_BYTES,
                      "tightness": AUDIT_TIGHTNESS},
        "runs": per_size,
        "footprint_model_validated": ok,
    }
    finding = None
    if not ok:
        bad = [s for s in per_size
               if not (s["within_band"] and s["block_accounting_ok"])]
        why = "; ".join(
            (f"{s['block_mb']:g}MB: measured {s['measured_mb']}MB vs "
             f"predicted {s['predicted_mb']}MB"
             + ("" if s["block_accounting_ok"]
                else f", observed block {s['observed_max_block_bytes']}B "
                     f"exceeds the modeled effective block"))
            for s in bad)
        finding = Finding(
            spec.path, spec.line, MEM_AUDIT_RULE,
            f"streamed job `{spec.name}` broke its footprint band: {why}",
            "re-derive the job's terms in analysis/mem.py (which buffer "
            "grew?) or fix the job if a carry went O(corpus); never "
            "allowlist a memory-oracle drift",
            spec.name)
    return row, finding


# --------------------------------------------------------------------------
# runner
# --------------------------------------------------------------------------
def run_mem(paths: Optional[Sequence[str]] = None,
            rules: Optional[Sequence[MemRule]] = None,
            baseline: Optional[Sequence[BaselineEntry]] = None,
            root: Optional[str] = None, include_md: bool = True,
            audit: bool = True, entries: Optional[Sequence] = None,
            block_sizes_mb: Optional[Sequence[float]] = None) -> Report:
    """Lint `paths` (default: the gated repo surface) with the mem rules,
    run the footprint auditor over the streamed-kernel manifest, and
    apply the allowlist baseline to both finding sets."""
    active = list(rules) if rules is not None else \
        [r() for r in ALL_MEM_RULES]
    root = os.path.abspath(root or os.getcwd())
    scan = list(paths) if paths else default_flow_paths(root)
    report, raw = collect_findings(scan, active, root, include_md)
    if audit:
        specs = list(entries) if entries is not None else None
        if specs is None:
            from avenir_tpu.analysis.manifest import stream_entries
            specs = stream_entries()
        for spec in specs:
            # NOT added to report.scanned — same reasoning as the flow
            # auditor: the audit runs the kernel, it does not lint its
            # file, and claiming a scan would falsely stale baseline
            # entries when an explicit path subset excludes it
            row, finding = audit_footprint(spec,
                                           block_sizes_mb=block_sizes_mb)
            report.footprint_audit.append(row)
            if finding is not None:
                raw.append(finding)
    active_ids = {r.rule_id for r in active}
    if audit:
        active_ids.add(MEM_AUDIT_RULE)
    apply_baseline(report, raw, baseline, active_ids)
    return report
