"""graftlint rules — each grounded in a bug class this repo already paid for.

default-int64            PR 1's biggest RSS wins were deleting accidental
                         int64/float64 temporaries from streaming folds.
host-sync-in-fold        a host transfer inside a chunk/fold loop silently
                         serializes core/stream.double_buffered.
recompile-hazard         per-iteration jit wrappers / non-static shape
                         params defeat the XLA compile cache (bench
                         watches utils.metrics.jit_cache_size at runtime).
tracer-leak              traced values stored on self/globals under jit
                         escape the trace and blow up at the next call.
unseeded-stochastic-test asserts over unpinned randomness flake — the
                         tutorial_inventory_mcmc Geweke burn-in case.

Rules are lexical (see engine.py); anything they flag is either fixed or
allowlisted with a one-line justification in graftlint_baseline.txt.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Set

from avenir_tpu.analysis.engine import Finding, ModuleContext, assigned_names

_NUMPY = "numpy"
_NP_MODS = ("numpy", "jax.numpy")


class Rule:
    rule_id: str = ""
    description: str = ""
    hint: str = ""

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        raise NotImplementedError

    def finding(self, ctx: ModuleContext, node: ast.AST, message: str,
                hint: Optional[str] = None) -> Finding:
        return Finding(ctx.path, getattr(node, "lineno", 1), self.rule_id,
                       message, hint or self.hint, ctx.scope_of(node))


class DefaultInt64Rule(Rule):
    """numpy constructors/accumulators on hot paths (lexically inside a
    loop) without an explicit narrow dtype, plus the numpy index-producing
    calls whose result is always int64.

    Scope is numpy only: jax.numpy already defaults to 32-bit unless
    jax_enable_x64 is set, and the repo never sets it. The hot-path proxy
    is lexical loop nesting — exactly where the miners' per-block folds
    live, and where a doubled temporary is paid once per block instead of
    once per process."""

    rule_id = "default-int64"
    description = ("numpy call on a hot path defaults to a 64-bit dtype "
                   "(or always returns int64 indices)")
    hint = ("pass an explicit narrow dtype (np.int32/np.float32), or use an "
            "int32 cumsum/region-mask form (see native.ingest.csr_region_mask "
            "and models/sequence.py chunks()) for index math")

    # func -> index of the positional dtype argument
    DTYPE_POS = {"zeros": 1, "ones": 1, "empty": 1, "full": 2,
                 "arange": 3, "cumsum": 2, "cumprod": 2}
    ALWAYS_INT64 = {"argsort", "flatnonzero", "nonzero", "searchsorted"}

    @staticmethod
    def _fill_sets_narrow_dtype(node: ast.Call) -> bool:
        fill = (node.args[1] if len(node.args) > 1 else
                next((kw.value for kw in node.keywords
                      if kw.arg == "fill_value"), None))
        return (isinstance(fill, ast.Constant)
                and isinstance(fill.value, (str, bool)))

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = ctx.dotted(node.func)
            if name is None or "." not in name:
                continue
            mod, _, func = name.rpartition(".")
            if mod != _NUMPY or not ctx.in_loop(node):
                continue
            if func in self.DTYPE_POS:
                has_dtype = (len(node.args) > self.DTYPE_POS[func]
                             or any(kw.arg == "dtype"
                                    for kw in node.keywords))
                if func == "full" and self._fill_sets_narrow_dtype(node):
                    continue        # dtype follows a str/bool fill value
                if not has_dtype:
                    yield self.finding(
                        ctx, node,
                        f"np.{func} inside a loop without an explicit "
                        f"dtype defaults to a 64-bit element type")
            elif func in self.ALWAYS_INT64:
                yield self.finding(
                    ctx, node,
                    f"np.{func} inside a loop materializes int64 indices "
                    f"(8 bytes/element) on a hot path")


class HostSyncInFoldRule(Rule):
    """Host transfers of device values inside chunk/fold loops: `.item()`,
    `jax.device_get`, `float()/int()` of a jitted-kernel result, and
    `np.asarray/np.array` wrapping a jitted-kernel call. Each one blocks
    until the device finishes, defeating the encode/count overlap
    core/stream.double_buffered exists to provide — unless the transfer
    IS the fold accumulation, in which case it is allowlisted with that
    justification."""

    rule_id = "host-sync-in-fold"
    description = "host sync of a device value inside a chunk/fold loop"
    hint = ("keep the accumulator on device (fold jnp arrays, transfer once "
            "after the loop), or allowlist if the once-per-block transfer is "
            "the fold itself and is overlapped by double_buffered")

    # numpy only: jnp.asarray of a device value is a no-op, not a sync
    WRAPPERS = {"numpy.asarray", "numpy.array", "numpy.copy"}

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call) or not ctx.in_loop(node):
                continue
            name = ctx.dotted(node.func)
            if name == "jax.device_get":
                yield self.finding(ctx, node,
                                   "jax.device_get inside a loop blocks on "
                                   "the device every iteration")
                continue
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "item" and not node.args \
                    and not node.keywords:
                yield self.finding(ctx, node,
                                   ".item() inside a loop is a scalar "
                                   "device->host sync per iteration")
                continue
            first_call = (node.args[0] if node.args
                          and isinstance(node.args[0], ast.Call) else None)
            if first_call is None:
                continue
            inner = ctx.dotted(first_call.func)
            inner_tail = inner.rpartition(".")[2] if inner else None
            if inner_tail not in ctx.jitted_names:
                continue
            if name in self.WRAPPERS or name in ("float", "int", "bool"):
                yield self.finding(
                    ctx, node,
                    f"{name}(...) of jitted `{inner_tail}` result inside a "
                    f"loop synchronizes host and device every iteration")


class RecompileHazardRule(Rule):
    """Compile-cache misses the type system can't see: (a) a fresh
    jax.jit wrapper built inside a loop (a new wrapper never hits the
    cache); (b) a jitted function using a plain parameter as a shape
    without marking it static; (c) a jitted closure using an enclosing
    function's local as a shape — re-traced for every distinct value.
    utils.metrics.jit_cache_size is the runtime cross-check bench_scaling
    asserts, so this rule can't silently rot."""

    rule_id = "recompile-hazard"
    description = "jit wrapper or shape argument that defeats the compile cache"
    hint = ("hoist jax.jit out of the loop / mark shape-like params "
            "static_argnames / derive shapes from operand .shape instead of "
            "closure scalars")

    SHAPE_ARG = {f"{m}.{f}": 0 for m in _NP_MODS
                 for f in ("zeros", "ones", "empty", "full")}
    SHAPE_ARG.update({f"{m}.broadcast_to": 1 for m in _NP_MODS})
    ARANGE = {f"{m}.arange" for m in _NP_MODS}

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call) \
                    and ctx.dotted(node.func) in ("jax.jit", "jit") \
                    and ctx.in_loop(node):
                yield self.finding(
                    ctx, node,
                    "jax.jit(...) inside a loop builds a fresh wrapper per "
                    "iteration; its compile cache starts empty every time",
                    "build the jitted callable once, outside the loop")
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                static = ctx.jit_static_names(node)
                if static is None:
                    continue
                yield from self._check_jitted_fn(ctx, node, static)

    def _shape_names(self, ctx: ModuleContext, call: ast.Call
                     ) -> List[ast.Name]:
        name = ctx.dotted(call.func)
        exprs: List[ast.AST] = []
        if name in self.ARANGE:
            exprs = list(call.args)
        elif name in self.SHAPE_ARG and len(call.args) > self.SHAPE_ARG[name]:
            exprs = [call.args[self.SHAPE_ARG[name]]]
        names: List[ast.Name] = []
        for e in exprs:
            for sub in ast.walk(e):
                # bare value names only: `rows.shape[0]` walks its Name
                # through an Attribute and is shape-derived, hence fine
                if isinstance(sub, ast.Name) and not isinstance(
                        ctx.parent(sub), ast.Attribute):
                    names.append(sub)
        return names

    def _check_jitted_fn(self, ctx: ModuleContext, fn: ast.FunctionDef,
                         static: Set[str]) -> Iterator[Finding]:
        params = {a.arg for a in
                  fn.args.posonlyargs + fn.args.args + fn.args.kwonlyargs}
        own = assigned_names(fn)
        enclosing: Set[str] = set()
        for outer in ctx.enclosing_functions(fn):
            enclosing |= assigned_names(outer)
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            for nm in self._shape_names(ctx, node):
                if nm.id in params and nm.id not in static:
                    yield self.finding(
                        ctx, nm,
                        f"jitted `{fn.name}` uses parameter `{nm.id}` as a "
                        f"shape; traced values cannot size arrays",
                        f"add static_argnames=('{nm.id}',) (recompiles per "
                        f"value — quantize it) or derive the size from an "
                        f"operand's .shape")
                elif nm.id in enclosing and nm.id not in own \
                        and nm.id not in ctx.module_names:
                    yield self.finding(
                        ctx, nm,
                        f"jitted `{fn.name}` closes over `{nm.id}` from an "
                        f"enclosing function and uses it as a shape: every "
                        f"distinct value re-traces and recompiles")


class TracerLeakRule(Rule):
    """Traced values escaping the trace: assignment to `self.*` or to a
    `global`-declared name anywhere inside a jit-decorated function. The
    stored tracer outlives the trace and poisons the next call (or leaks
    a stale constant)."""

    rule_id = "tracer-leak"
    description = "traced value stored on self/globals inside jit"
    hint = ("return the value from the jitted function and store it on the "
            "host side, after the call")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if ctx.jit_static_names(fn) is None:
                continue
            for node in ast.walk(fn):
                if isinstance(node, ast.Global):
                    yield self.finding(
                        ctx, node,
                        f"`global {', '.join(node.names)}` inside jitted "
                        f"`{fn.name}`: assigning it stores a tracer past "
                        f"the trace")
                elif isinstance(node, (ast.Assign, ast.AugAssign,
                                       ast.AnnAssign)):
                    targets = (node.targets
                               if isinstance(node, ast.Assign)
                               else [node.target])
                    for t in targets:
                        for leaf in ast.walk(t):
                            if isinstance(leaf, ast.Attribute) \
                                    and isinstance(leaf.value, ast.Name) \
                                    and leaf.value.id == "self":
                                yield self.finding(
                                    ctx, node,
                                    f"assignment to self.{leaf.attr} inside "
                                    f"jitted `{fn.name}` stores a traced "
                                    f"value on the instance")
                                break


class UnseededStochasticTestRule(Rule):
    """A scope that asserts AND draws unpinned randomness: global
    numpy/python RNG draws, `np.random.default_rng()` with no seed, or a
    jax PRNG key built from a non-constant. Statistical assertions are
    fine — run-to-run varying statistical assertions are flakes
    (tutorial_inventory_mcmc's Geweke burn-in was this class)."""

    rule_id = "unseeded-stochastic-test"
    description = "assert over unpinned randomness (flaky by construction)"
    hint = ("pin the seed: np.random.default_rng(<int>), jax.random.key(<int>)"
            ", or thread an explicit seeded Generator through the test")

    NP_GLOBAL_DRAWS = {"normal", "uniform", "choice", "rand", "randn",
                       "randint", "random", "permutation", "shuffle",
                       "binomial", "poisson", "standard_normal", "sample"}
    PY_DRAWS = {"random", "uniform", "randint", "choice", "shuffle",
                "sample", "gauss", "randrange", "betavariate"}

    @staticmethod
    def _walk_scope(root: ast.AST) -> Iterator[ast.AST]:
        """Nodes owned by `root`'s scope: descend everywhere except nested
        function defs (their draws/asserts attribute to the inner scope)."""
        stack = list(ast.iter_child_nodes(root))
        while stack:
            node = stack.pop()
            yield node
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                stack.extend(ast.iter_child_nodes(node))

    def _unseeded_calls(self, ctx: ModuleContext, nodes: List[ast.AST]
                        ) -> Iterator[ast.Call]:
        for node in nodes:
            if not isinstance(node, ast.Call):
                continue
            name = ctx.dotted(node.func)
            if name is None:
                continue
            if name == "numpy.random.default_rng" and not node.args \
                    and not node.keywords:
                yield node
            elif name.startswith("numpy.random.") \
                    and name.rpartition(".")[2] in self.NP_GLOBAL_DRAWS:
                yield node
            elif name.startswith("random.") \
                    and name.rpartition(".")[2] in self.PY_DRAWS:
                yield node
            elif name in ("jax.random.key", "jax.random.PRNGKey") \
                    and node.args and any(
                        isinstance(sub, ast.Call)
                        for sub in ast.walk(node.args[0])):
                # a call inside the seed expression (time.time(),
                # os.getpid(), ...) is an entropy source; arithmetic over
                # constants/loop indices is deterministic and fine
                yield node

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        scopes: List[ast.AST] = [ctx.tree]
        scopes += [n for n in ast.walk(ctx.tree)
                   if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        for scope in scopes:
            nodes = list(self._walk_scope(scope))
            if not any(isinstance(n, ast.Assert) for n in nodes):
                continue
            for call in self._unseeded_calls(ctx, nodes):
                name = ctx.dotted(call.func)
                yield self.finding(
                    ctx, call,
                    f"`{name}` draws unpinned randomness in a scope that "
                    f"asserts on the result")


class ShardedHostMaterializeRule(Rule):
    """np.asarray / np.array / np.copy applied to a device-placed array
    (a direct jax.device_put(...) result, or a name bound from
    jax.device_put / mesh.shard_rows / mesh.replicated in the same
    module). Materializing a sharded array on the host gathers EVERY
    shard through one process — the all-to-one transfer the mesh layer
    exists to avoid — and on multi-host meshes it deadlocks outright
    (non-addressable shards). Lexical, like every rule here: values that
    become sharded through a mesh kernel's return slip past, but the
    placement-then-materialize shape is the one that has actually
    appeared in review."""

    rule_id = "sharded-host-materialize"
    description = "np.asarray/np.array of a device-placed (sharded) array"
    hint = ("keep the consumer on device (jnp ops see sharded arrays "
            "natively), or jax.device_get once after the last device step "
            "— never re-wrap a device_put result with host numpy")

    WRAPPERS = {"numpy.asarray", "numpy.array", "numpy.copy"}
    PLACERS_DOTTED = {"jax.device_put"}
    # mesh-layer placement helpers, recognized by tail name so both
    # `from ..mesh import shard_rows` and `mesh.shard_rows(...)` match
    PLACER_TAILS = {"device_put", "shard_rows", "replicated"}

    def _is_placer(self, ctx: ModuleContext, call: ast.Call) -> bool:
        name = ctx.dotted(call.func)
        if name in self.PLACERS_DOTTED:
            return True
        return (name is not None
                and name.rpartition(".")[2] in self.PLACER_TAILS)

    def _placed_names(self, ctx: ModuleContext) -> Set[str]:
        """Names bound (anywhere in the module) from a placement call —
        including tuple-to-tuple unpacks like `a, b = put(x), put(y)`."""
        out: Set[str] = set()
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            pairs = []
            tgt, val = node.targets[0], node.value
            if isinstance(tgt, ast.Name):
                pairs.append((tgt, val))
            elif isinstance(tgt, ast.Tuple) and isinstance(val, ast.Tuple) \
                    and len(tgt.elts) == len(val.elts):
                pairs.extend(zip(tgt.elts, val.elts))
            for t, v in pairs:
                if isinstance(t, ast.Name) and isinstance(v, ast.Call) \
                        and self._is_placer(ctx, v):
                    out.add(t.id)
        return out

    def _feeds_placement(self, ctx: ModuleContext, node: ast.AST) -> bool:
        """True when `node` sits inside a placer call's arguments — e.g.
        ``shard_rows(mesh, np.asarray(x))``: that asarray PREPARES the
        placement (flow runs host->device), it doesn't materialize a
        placed value."""
        cur = ctx.parent(node)
        while cur is not None:
            if isinstance(cur, ast.Call) and self._is_placer(ctx, cur):
                return True
            cur = ctx.parent(cur)
        return False

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        placed = self._placed_names(ctx)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            if ctx.dotted(node.func) not in self.WRAPPERS:
                continue
            if self._feeds_placement(ctx, node):
                continue
            arg = node.args[0]
            if isinstance(arg, ast.Call) and self._is_placer(ctx, arg):
                yield self.finding(
                    ctx, node,
                    "host materialization of a jax.device_put result: "
                    "every shard transfers back through this process")
            elif isinstance(arg, ast.Name) and arg.id in placed:
                yield self.finding(
                    ctx, node,
                    f"np wrapper over `{arg.id}` (device-placed above) "
                    f"gathers all shards to host")


class Int64LiteralInJnpRule(Rule):
    """A Python int literal outside int32 range flowing into a jax.numpy
    call. With jax_enable_x64 off (this repo never sets it) such a
    literal either raises OverflowError at runtime or silently truncates
    through a weak-typed promotion — both discovered at the worst time,
    on device, mid-stream. Folds constant int arithmetic (<<, **, *, +,
    -, |) so `1 << 40` and `2**40` are caught, not just spelled-out
    literals."""

    rule_id = "int64-literal-in-jnp"
    description = "int literal beyond int32 range in a jnp call"
    hint = ("keep 64-bit id/hash math in host numpy (np.int64 arrays) and "
            "hand the device narrow codes, or split the constant into "
            "32-bit halves before it reaches jnp")

    _INT32_MAX = 2 ** 31 - 1
    _OPS = {ast.LShift: lambda a, b: a << b, ast.Pow: lambda a, b: a ** b,
            ast.Mult: lambda a, b: a * b, ast.Add: lambda a, b: a + b,
            ast.Sub: lambda a, b: a - b, ast.BitOr: lambda a, b: a | b}

    def _fold(self, node: ast.AST) -> Optional[int]:
        """Constant-fold small int expressions; None when not constant."""
        if isinstance(node, ast.Constant):
            return node.value if type(node.value) is int else None
        if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
            v = self._fold(node.operand)
            return -v if v is not None else None
        if isinstance(node, ast.BinOp):
            fn = self._OPS.get(type(node.op))
            if fn is None:
                return None
            a, b = self._fold(node.left), self._fold(node.right)
            if a is None or b is None:
                return None
            if isinstance(node.op, ast.Pow) and (abs(a) > 64 or b > 64):
                return None          # keep folding cheap and bounded
            try:
                return fn(a, b)
            except (OverflowError, ValueError):
                return None
        return None

    @staticmethod
    def _walk_pruning_calls(root: ast.AST) -> Iterator[ast.AST]:
        """Walk `root` WITHOUT descending into nested calls — a literal
        inside `np.asarray(1 << 40)` belongs to that (host) call, which
        is judged on its own if it's a jnp one."""
        stack = [root]
        while stack:
            node = stack.pop()
            yield node
            stack.extend(c for c in ast.iter_child_nodes(node)
                         if not isinstance(c, ast.Call))

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = ctx.dotted(node.func)
            if name is None or not name.startswith("jax.numpy."):
                continue
            exprs = list(node.args) + [kw.value for kw in node.keywords]
            for e in exprs:
                for sub in self._walk_pruning_calls(e):
                    v = self._fold(sub)
                    # only report the outermost folded expression: a
                    # parent BinOp that folded already covers its leaves
                    parent = ctx.parent(sub)
                    if v is not None and abs(v) > self._INT32_MAX \
                            and (not isinstance(parent, (ast.BinOp,
                                                         ast.UnaryOp))
                                 or self._fold(parent) is None):
                        yield self.finding(
                            ctx, sub if hasattr(sub, "lineno") else node,
                            f"int constant {v} exceeds int32 range inside "
                            f"`{name}`: with x64 disabled this overflows "
                            f"or silently truncates on device")


class FoldUndonatedCarryRule(Rule):
    """A jitted fold carry re-dispatched per chunk without a donated
    accumulator: ``acc = fold(acc, chunk)`` inside a lexical loop, where
    `fold` is a module-local jitted callable whose jit wrapper has no
    (non-empty) donate_argnums/donate_argnames. Every iteration then
    allocates a fresh device accumulator and keeps the previous one
    alive until the add completes — on a fan-out shared scan the per-
    chunk allocation multiplies by the sink count. The NB deferred fold
    (models/naive_bayes.py `_fold_batch_kernel`) and the miners' device
    count folds (ops/bitset.bitset_fold_counts, models/sequence.py
    `_subseq_fold_kernel`) are the donated pattern this rule enforces.
    Module-local like every rule here: an imported jitted fold is judged
    in its defining module."""

    rule_id = "fold-undonated-carry"
    description = ("jitted fold carry re-dispatched per chunk without a "
                   "donated accumulator")
    hint = ("donate the carry: @partial(jax.jit, donate_argnums=(0,)) on "
            "the fold kernel so the chunk loop reuses ONE device buffer "
            "(the models/naive_bayes.py _fold_batch_kernel pattern), or "
            "allowlist if the loop is few-iteration host-driven control")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.Assign, ast.AugAssign)) \
                    or not ctx.in_loop(node):
                continue
            value = node.value
            if not isinstance(value, ast.Call):
                continue
            fname = ctx.dotted(value.func)
            tail = fname.rpartition(".")[2] if fname else None
            if tail not in ctx.jitted_names or tail in ctx.jitted_donating:
                continue
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            tnames = {ctx.dotted(t) for t in targets} - {None}
            if not tnames:
                continue
            args = list(value.args) + [kw.value for kw in value.keywords]
            carry = next((ctx.dotted(a) for a in args
                          if ctx.dotted(a) in tnames), None)
            if carry is not None:
                yield self.finding(
                    ctx, node,
                    f"`{carry} = {tail}({carry}, ...)` in a loop: the "
                    f"jitted fold's carry is not donated, so every chunk "
                    f"allocates a fresh device accumulator")


ALL_RULES = [DefaultInt64Rule, HostSyncInFoldRule, RecompileHazardRule,
             TracerLeakRule, UnseededStochasticTestRule,
             ShardedHostMaterializeRule, Int64LiteralInJnpRule,
             FoldUndonatedCarryRule]


def rule_ids() -> List[str]:
    return [r.rule_id for r in ALL_RULES]
