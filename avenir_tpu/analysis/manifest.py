"""Traceable-kernel manifest: what graftlint-ir analyzes, and how.

The AST rules (rules.py) see code shapes; the IR rules (ir.py) see what
tracing actually produced. That needs a registry of *traceable units*:
for each hot kernel an entry point plus the abstract shapes/dtypes to
trace it with, and for each distributed family additionally the mesh to
lower on and the analytic collective-payload model
(`parallel/scaling.collective_payload_model`) its compiled HLO must
match byte-for-byte.

Shapes here are deliberately tiny — the auditor checks *structure*
(dtypes, callbacks, collective bytes), not performance, and every dim
that feeds a payload model is pinned in the entry so the analytic number
is derivable by eye. Coverage is enforced two ways: the manifest must
name every family in ``distributed.FAMILIES``
(tests/test_graftlint_ir.py), and a family without a payload model
cannot report ``payload_model_validated``.
"""

from __future__ import annotations

import inspect
import os
from dataclasses import dataclass
from typing import Callable, List, Optional, Tuple

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

#: every family entry lowers on this many virtual devices — the same
#: 8-device mesh the test harness pins (tests/conftest.py)
AUDIT_DEVICES = 8


@dataclass(frozen=True)
class KernelSpec:
    """One traceable unit.

    ``build(mesh)`` returns ``(fn, args)`` ready for ``jax.make_jaxpr``
    (and, for families, for ``fn.lower(*args).compile()`` — `fn` must be
    jitted and `args` device-placed on `mesh`). `mesh` is None for plain
    op entries. ``payload_model(mesh)`` gives the family's analytic
    collective bytes; None marks a non-distributed entry."""

    name: str                     # finding scope (rule keys use it)
    path: str                     # repo-relative module the kernel lives in
    line: int
    build: Callable
    model_parallel: int = 1       # family mesh: devices//mp x mp
    payload_model: Optional[Callable] = None

    @property
    def is_family(self) -> bool:
        return self.payload_model is not None


def _loc(obj) -> Tuple[str, int]:
    """(repo-relative posix path, first line) of a kernel's def."""
    src = inspect.getsourcefile(inspect.unwrap(obj))
    rel = os.path.relpath(os.path.abspath(src), _REPO_ROOT)
    try:
        line = inspect.getsourcelines(inspect.unwrap(obj))[1]
    except OSError:
        line = 1
    return rel.replace(os.sep, "/"), line


def _sds(shape, dtype):
    import jax

    return jax.ShapeDtypeStruct(shape, np.dtype(dtype))


# ------------------------------------------------------------- op entries
def _op_entries() -> List[KernelSpec]:
    from avenir_tpu.ops import bitset, infotheory, pallas_knn, reduce

    def spec(name, ref, build):
        path, line = _loc(ref)
        return KernelSpec(name, path, line, build)

    def bitset_counts(_mesh):
        return (bitset.bitset_contain_counts,
                (_sds((256, 4), np.uint32), _sds((64, 4), np.uint32)))

    def bitset_mask(_mesh):
        return (bitset.bitset_contain_mask,
                (_sds((256, 4), np.uint32), _sds((64, 4), np.uint32)))

    def keyed(_mesh):
        return (lambda k, v: reduce.keyed_reduce(k, v, 64),
                (_sds((1024,), np.int32), _sds((1024,), np.float32)))

    def onehot(_mesh):
        return (lambda c: reduce.one_hot_count(c, 32),
                (_sds((1024, 4), np.int32),))

    def split_score(_mesh):
        return (lambda c: infotheory.weighted_split_score(c, "entropy"),
                (_sds((16, 4, 3), np.float32),))

    def mi(_mesh):
        return (infotheory.mutual_information, (_sds((8, 4), np.float32),))

    def pallas(_mesh):
        # interpret mode: the kernel traces (and its jaxpr is lintable)
        # with no TPU attached; the compiled path is bench.py's job
        return (lambda q, t: pallas_knn.knn_topk_pallas(
                    q, t, k=5, block_q=128, block_t=256, interpret=True),
                (_sds((128, 8), np.float32), _sds((256, 8), np.float32)))

    return [
        spec("bitset_contain_counts", bitset.bitset_contain_counts,
             bitset_counts),
        spec("bitset_contain_mask", bitset.bitset_contain_mask, bitset_mask),
        spec("keyed_reduce", reduce.keyed_reduce, keyed),
        spec("one_hot_count", reduce.one_hot_count, onehot),
        spec("weighted_split_score", infotheory.weighted_split_score,
             split_score),
        spec("mutual_information", infotheory.mutual_information, mi),
        spec("knn_topk_pallas", pallas_knn.knn_topk_pallas, pallas),
    ]


# --------------------------------------------------------- family entries
def _family_entries() -> List[KernelSpec]:
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from avenir_tpu.parallel import distributed as D
    from avenir_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS
    from avenir_tpu.parallel.scaling import (_NB_BMAX, _NB_CLASSES, _NB_FEAT,
                                             collective_payload_model)

    def put(mesh, arr, *spec):
        return jax.device_put(arr, NamedSharding(mesh, P(*spec)))

    def row(mesh):
        return tuple(a for a in (DATA_AXIS, MODEL_AXIS)
                     if a in mesh.axis_names)

    ROWS = 8 * AUDIT_DEVICES

    # dims every payload model below derives from — one place, tiny values
    KNN_K, KNN_D = 5, 8
    TREE = dict(n_leaves=4, n_splits=3, smax=2, num_classes=2)
    LR_D = 8
    MARKOV = dict(n_states=3, n_classes=2)
    APRIORI_CAND, APRIORI_VOCAB, APRIORI_K = 16, 12, 2
    BANDIT_ARMS, BANDIT_BATCH = 6, 2
    CROSS = dict(bins_a=10, bins_b=2)

    def knn_build(mesh):
        data_n = mesh.shape[DATA_AXIS]
        model_n = mesh.shape.get(MODEL_AXIS, 1)
        nq, train = 8 * data_n, 16 * model_n
        fn = D.distributed_topk_fn(mesh, k=KNN_K, metric="euclidean")
        return fn, (
            put(mesh, np.zeros((nq, KNN_D), np.float32), DATA_AXIS, None),
            put(mesh, np.zeros((train, KNN_D), np.float32), MODEL_AXIS, None),
            put(mesh, np.zeros((train,), np.int32), MODEL_AXIS),
        )

    def knn_payload(mesh):
        return collective_payload_model(
            "knn_topk", dict(mesh.shape), nq=8 * mesh.shape[DATA_AXIS],
            k=KNN_K)

    def nb_build(mesh):
        r = row(mesh)
        fn = D.distributed_nb_train_fn(mesh, _NB_CLASSES, _NB_BMAX)
        return fn, (
            put(mesh, np.zeros((ROWS, _NB_FEAT), np.int32), r),
            put(mesh, np.zeros((ROWS,), np.int32), r),
            put(mesh, np.ones((ROWS,), np.float32), r),
        )

    def nb_payload(mesh):
        return collective_payload_model(
            "nb_train", dict(mesh.shape), n_feat=_NB_FEAT,
            num_classes=_NB_CLASSES, bmax=_NB_BMAX)

    def tree_build(mesh):
        r = row(mesh)
        fn = D.distributed_tree_level_fn(
            mesh, TREE["n_leaves"], TREE["n_splits"], TREE["smax"],
            TREE["num_classes"])
        return fn, (
            put(mesh, np.zeros((ROWS,), np.int32), r),
            put(mesh, np.zeros((ROWS, TREE["n_splits"]), np.int8), r),
            put(mesh, np.zeros((ROWS,), np.int32), r),
            put(mesh, np.ones((ROWS,), np.float32), r),
        )

    def tree_payload(mesh):
        return collective_payload_model("tree_level", dict(mesh.shape),
                                        **TREE)

    def lr_build(mesh):
        r = row(mesh)
        fn = D.distributed_lr_step_fn(mesh, learning_rate=0.5)
        return fn, (
            put(mesh, np.zeros((LR_D,), np.float32)),
            put(mesh, np.zeros((ROWS, LR_D), np.float32), r),
            put(mesh, np.zeros((ROWS,), np.float32), r),
            put(mesh, np.ones((ROWS,), np.float32), r),
        )

    def lr_payload(mesh):
        return collective_payload_model("lr_step", dict(mesh.shape), d=LR_D)

    def markov_build(mesh):
        r = row(mesh)
        fn = D.distributed_markov_counts_fn(
            mesh, MARKOV["n_states"], MARKOV["n_classes"])
        return fn, (
            put(mesh, np.zeros((ROWS, 6), np.int32), r),
            put(mesh, np.zeros((ROWS,), np.int32), r),
        )

    def markov_payload(mesh):
        return collective_payload_model("markov_counts", dict(mesh.shape),
                                        **MARKOV)

    def apriori_build(mesh):
        r = row(mesh)
        fn = D.distributed_apriori_support_fn(mesh, APRIORI_K)
        return fn, (
            put(mesh, np.zeros((ROWS, APRIORI_VOCAB), np.float32), r),
            put(mesh, np.zeros((APRIORI_CAND, APRIORI_VOCAB), np.float32)),
        )

    def apriori_payload(mesh):
        return collective_payload_model("apriori_support", dict(mesh.shape),
                                        n_cand=APRIORI_CAND)

    def bandit_build(mesh):
        r = row(mesh)
        fn = D.distributed_bandit_select_fn(mesh, batch_size=BANDIT_BATCH)
        return fn, (
            put(mesh, np.zeros((ROWS, BANDIT_ARMS), np.int32), r),
            put(mesh, np.zeros((ROWS, BANDIT_ARMS), np.float32), r),
            put(mesh, np.ones((ROWS, BANDIT_ARMS), bool), r),
            put(mesh, np.float32(5.0)),
        )

    def bandit_payload(mesh):
        return collective_payload_model("bandit_select", dict(mesh.shape))

    def cross_build(mesh):
        r = row(mesh)
        fn = D.distributed_crosscount_fn(mesh, CROSS["bins_a"],
                                         CROSS["bins_b"])
        return fn, (
            put(mesh, np.zeros((ROWS,), np.int32), r),
            put(mesh, np.zeros((ROWS,), np.int32), r),
            put(mesh, np.ones((ROWS,), np.float32), r),
        )

    def cross_payload(mesh):
        return collective_payload_model("crosscount", dict(mesh.shape),
                                        **CROSS)

    builders = {
        "knn_topk": (D.distributed_topk_fn, knn_build, knn_payload, 2),
        "nb_train": (D.distributed_nb_train_fn, nb_build, nb_payload, 1),
        "tree_level": (D.distributed_tree_level_fn, tree_build,
                       tree_payload, 1),
        "lr_step": (D.distributed_lr_step_fn, lr_build, lr_payload, 1),
        "markov_counts": (D.distributed_markov_counts_fn, markov_build,
                          markov_payload, 1),
        "apriori_support": (D.distributed_apriori_support_fn, apriori_build,
                            apriori_payload, 1),
        "bandit_select": (D.distributed_bandit_select_fn, bandit_build,
                          bandit_payload, 1),
        "crosscount": (D.distributed_crosscount_fn, cross_build,
                       cross_payload, 1),
    }
    out = []
    for name, (ref, build, payload, mp) in builders.items():
        path, line = _loc(ref)
        out.append(KernelSpec(name, path, line, build,
                              model_parallel=mp, payload_model=payload))
    return out


def manifest_entries() -> List[KernelSpec]:
    """The full manifest: hot ops + every distributed family."""
    return _op_entries() + _family_entries()


def family_names() -> List[str]:
    return [s.name for s in _family_entries()]


# ------------------------------------------------------ streamed fold kernels
@dataclass(frozen=True)
class StreamKernelSpec:
    """One streamed fold kernel for the chunk-invariance auditor
    (analysis/flow.py).

    ``prepare(workdir)`` writes the kernel's corpus (deterministic,
    seeded) and returns a context dict; ``run(ctx, block_mb)`` executes
    the REAL streamed job over that corpus with the given stream block
    size and returns the output artifact's bytes. `layouts` holds >= 3
    block sizes chosen so the corpus chunks into visibly different
    layouts (single block / a dozen / dozens) — the auditor verifies the
    chunk counts actually differ, then asserts the bytes don't.

    ``jobs`` names the registered runner job(s) the spec drives (several
    for the fused shared-scan entries): the memory auditor
    (analysis/mem.py) keys its per-job analytic footprint model on
    them, so every stream entry is memory-auditable by construction.

    ``fold_specs`` carries the same jobs as ``(job, prefix, conf)``
    triples (conf values may hold ``{schema}``-style ctx placeholders,
    formatted exactly like ``_job_runner`` does): the shard-merge/
    resume auditor (analysis/merge.py) drives each job's REGISTERED
    fold sink (runner.stream_fold_ops) directly with them, so every
    stream entry is merge-auditable by construction too."""

    name: str
    path: str                     # repo-relative module of the fold kernel
    line: int
    prepare: Callable             # workdir -> ctx dict
    run: Callable                 # (ctx, block_mb) -> bytes
    layouts: Tuple[float, ...] = (64.0, 0.002, 0.0005)
    jobs: Tuple[str, ...] = ()
    fold_specs: Tuple[Tuple[str, str, dict], ...] = ()


def _job_runner(job: str, prefix: str, conf: dict, inputs_key: str = "csv"):
    """run(ctx, block_mb) driving a registered runner job with the
    kernel's corpus and `<prefix>.stream.block.size.mb` pinned to the
    layout under test — the full streamed path (prefetched block reads,
    shared-schema chunk parses, double-buffered device folds, output
    writer), not a unit-sized re-implementation of it."""

    def run(ctx: dict, block_mb: float) -> bytes:
        from avenir_tpu.runner import run_job

        ctx["runs"] = ctx.get("runs", 0) + 1
        out = os.path.join(ctx["dir"], f"out_{ctx['runs']}.txt")
        props = dict(conf)
        for key, val in list(props.items()):
            props[key] = val.format(**ctx) if isinstance(val, str) else val
        props[f"{prefix}.stream.block.size.mb"] = repr(float(block_mb))
        res = run_job(job, props, [ctx[inputs_key]], out)
        # the artifact is every output file the job wrote (the miners
        # emit one per itemset length), name-tagged so a missing per-k
        # file can't alias a reordered one
        blobs = []
        for p in sorted(res.outputs):
            rel = os.path.relpath(p, out)   # run-invariant name ('.'
            with open(p, "rb") as fh:       # for single-file outputs)
                blobs.append(rel.encode() + b"\0" + fh.read())
        return b"\n".join(blobs)

    return run


def _shared_runner(specs):
    """run(ctx, block_mb) driving N registered jobs through runner.
    run_shared — the REAL scan-sharing executor (one SharedScan read +
    parse, N fold sinks) — with every job's stream block size pinned to
    the layout under test. The artifact is every output file of every
    fused job, name-tagged, so a drift in ANY sink's fold fails the
    byte-identity assertion. `specs` is [(job, prefix, conf)]."""

    def run(ctx: dict, block_mb: float) -> bytes:
        from avenir_tpu.runner import run_shared

        ctx["runs"] = ctx.get("runs", 0) + 1
        blobs = []
        shared_specs = []
        outs = []
        for job, prefix, conf in specs:
            out = os.path.join(ctx["dir"], f"out_{ctx['runs']}_{job}")
            props = {k: (v.format(**ctx) if isinstance(v, str) else v)
                     for k, v in conf.items()}
            props[f"{prefix}.stream.block.size.mb"] = repr(float(block_mb))
            shared_specs.append((job, props, out))
            outs.append(out)
        results = run_shared(shared_specs, [ctx["csv"]])
        for (job, _prefix, _conf), out in zip(specs, outs):
            res = results[job]
            for p in sorted(res.outputs):
                rel = os.path.relpath(p, out)
                with open(p, "rb") as fh:
                    blobs.append(f"{job}:{rel}".encode() + b"\0" + fh.read())
        return b"\n".join(blobs)

    return run


def _churn_corpus(workdir: str) -> dict:
    from avenir_tpu.data import churn_schema, generate_churn

    csv = os.path.join(workdir, "churn.csv")
    with open(csv, "w") as fh:
        fh.write(generate_churn(600, seed=11, as_csv=True))
    schema = os.path.join(workdir, "churn.json")
    churn_schema().save(schema)
    return {"dir": workdir, "csv": csv, "schema": schema}


def _seq_corpus(workdir: str) -> dict:
    """Markov/miner corpus: 3-state token sequences with a class column,
    the bench_scaling.miner_tripwire shape at auditor size."""
    rng = np.random.default_rng(12)
    states = ["L", "M", "H"]
    csv = os.path.join(workdir, "seq.csv")
    with open(csv, "w") as fh:
        for i in range(400):
            up = i % 2 == 0
            s, toks = 1, []
            for _ in range(6):
                p = [0.1, 0.3, 0.6] if up else [0.6, 0.3, 0.1]
                s = int(np.clip(s + rng.choice([-1, 0, 1], p=p), 0, 2))
                toks.append(states[s])
            fh.write(f"c{i},{'T' if up else 'F'}," + ",".join(toks) + "\n")
    return {"dir": workdir, "csv": csv}


def stream_entries() -> List[StreamKernelSpec]:
    """The streamed fold kernels the chunk-invariance auditor proves
    deterministic every run: NB, MI, Markov, Apriori, GSP, discriminant
    — every additive-count fold the 1B-row path is built on. Each
    `path:line` points at the fold kernel itself (the accumulate /
    mine_stream the job drives), so findings land on the code that owns
    the invariant."""
    from avenir_tpu.core.stream import SharedScan
    from avenir_tpu.models.association import FrequentItemsApriori
    from avenir_tpu.models.discriminant import FisherDiscriminant
    from avenir_tpu.models.explore import MutualInformationAnalyzer
    from avenir_tpu.models.markov import MarkovStateTransitionModel
    from avenir_tpu.models.naive_bayes import NaiveBayesModel
    from avenir_tpu.models.sequence import GSPMiner

    def spec(name, ref, prepare, run, fold_specs):
        path, line = _loc(ref)
        return StreamKernelSpec(
            name, path, line, prepare, run,
            jobs=tuple(job for job, _prefix, _conf in fold_specs),
            fold_specs=tuple((job, prefix, dict(conf))
                            for job, prefix, conf in fold_specs))

    schema_conf = lambda prefix: {
        f"{prefix}.feature.schema.file.path": "{schema}"}
    # ONE definition of each job's audit config, shared by the runner
    # closures (chunk-invariance / footprint audits) and the fold_specs
    # (shard-merge/resume audit) so the tiers can never drift apart
    nb_spec = ("bayesianDistr", "bad", schema_conf("bad"))
    mi_spec = ("mutualInformation", "mut", {
        **schema_conf("mut"),
        "mut.mutual.info.score.algorithms":
            "mutual.info.maximization,min.redundancy.max.relevance",
    })
    fid_spec = ("fisherDiscriminant", "fid", schema_conf("fid"))
    mst_spec = ("markovStateTransitionModel", "mst", {
        "mst.model.states": "L,M,H",
        "mst.class.label.field.ord": "1",
        "mst.skip.field.count": "2",
        "mst.class.labels": "T,F",
    })
    fia_spec = ("frequentItemsApriori", "fia", {
        "fia.support.threshold": "0.3",
        "fia.item.set.length": "2",
        "fia.skip.field.count": "2",
    })
    cgs_spec = ("candidateGenerationWithSelfJoin", "cgs", {
        "cgs.support.threshold": "0.3",
        "cgs.item.set.length": "2",
        "cgs.skip.field.count": "2",
    })

    def solo(name, ref, prepare, job_spec):
        job, prefix, conf = job_spec
        return spec(name, ref, prepare, _job_runner(job, prefix, conf),
                    [job_spec])

    return [
        solo("nb_stream", NaiveBayesModel.accumulate, _churn_corpus,
             nb_spec),
        solo("mi_stream", MutualInformationAnalyzer.add, _churn_corpus,
             mi_spec),
        solo("discriminant_stream", FisherDiscriminant.accumulate,
             _churn_corpus, fid_spec),
        solo("markov_stream", MarkovStateTransitionModel.fit_csr,
             _seq_corpus, mst_spec),
        solo("apriori_stream", FrequentItemsApriori.mine_stream,
             _seq_corpus, fia_spec),
        solo("gsp_stream", GSPMiner.mine_stream, _seq_corpus, cgs_spec),
        # fused shared-scan entries: the SAME jobs through the
        # scan-sharing executor (ONE read + parse, N fold sinks). The
        # auditor re-proves every round that fan-out changes nothing —
        # fused outputs must be byte-identical under all chunk layouts
        # and the adversarial prefetch scheduler, exactly like the
        # one-job-one-scan entries above.
        spec("shared_churn_stream", SharedScan.run, _churn_corpus,
             _shared_runner([nb_spec, mi_spec, fid_spec]),
             [nb_spec, mi_spec, fid_spec]),
        spec("shared_seq_stream", SharedScan.run, _seq_corpus,
             _shared_runner([mst_spec, fia_spec]),
             [mst_spec, fia_spec]),
    ]


def stream_kernel_names() -> List[str]:
    return [s.name for s in stream_entries()]
