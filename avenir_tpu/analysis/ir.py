"""graftlint-ir: jaxpr/HLO-level hazard analysis over the kernel manifest.

The AST rules (rules.py) stop at what the source *says*; the regressions
that cost this repo real scale-run hours live one layer down, in what
tracing *produced*: dtype widenings the source never spelled, callbacks
smuggled into scan bodies by a helper, host transfers inside fold loops,
and collectives whose payloads drift from the analytic traffic model in
`parallel/scaling.py`. This module walks the traced jaxpr of every
manifest entry (analysis/manifest.py) for the first three, and — the
headline — lowers every distributed family on the virtual 8-device mesh,
parses the compiled HLO's collective instructions
(`scaling.hlo_collective_payloads`) and asserts the summed payload bytes
equal `scaling.collective_payload_model` per family. The same move XLA's
own HLO verifier makes: pin the invariant at the IR, where no amount of
source-level cleverness can hide a violation.

Findings flow through the shared engine: keyed
``path::rule::kernel-name`` against the same allowlist baseline, merged
into a :class:`~avenir_tpu.analysis.engine.Report` whose
``payload_audit`` lists each family's verdict. Entry point:
``graftlint --ir`` (analysis/cli.py) or :func:`run_ir` in-process.

A manifest entry that fails to trace/lower raises :class:`IRTraceError`
— the CLI maps that to exit code 2 (usage-or-trace-error), distinct from
exit 1 (findings): a broken trace means the *auditor* is broken, not
that a hazard was found.
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Sequence, Set, Tuple

from avenir_tpu.analysis.engine import (BaselineEntry, Finding, Report,
                                        apply_baseline)
from avenir_tpu.analysis.manifest import (AUDIT_DEVICES, KernelSpec,
                                          manifest_entries)

#: the audit's pseudo-rule id: payload mismatches surface as findings
#: under it (allowlistable like any other, though the right fix is to
#: correct the model or the kernel, never to excuse the drift)
PAYLOAD_RULE = "ir-collective-payload"

_CALLBACK_PRIMS = ("pure_callback", "io_callback", "debug_callback")
_LOOP_PRIMS = ("scan", "while")


class IRTraceError(RuntimeError):
    """A manifest entry could not be traced or lowered."""


# ----------------------------------------------------------- jaxpr walking
def _jaxprs_in(value) -> Iterator:
    """Jaxprs reachable from one eqn param value (ClosedJaxpr, raw Jaxpr,
    or containers of either — scan's `jaxpr`, cond's `branches`, ...)."""
    if hasattr(value, "eqns"):
        yield value
    elif hasattr(value, "jaxpr") and hasattr(value.jaxpr, "eqns"):
        yield value.jaxpr
    elif isinstance(value, (tuple, list)):
        for v in value:
            yield from _jaxprs_in(v)


def iter_eqns(jaxpr, in_loop: bool = False) -> Iterator[Tuple[object, bool]]:
    """Yield (eqn, in_loop) over `jaxpr` and every sub-jaxpr. `in_loop`
    is True for eqns whose enclosing sub-jaxpr executes per-iteration of
    a lax.scan / lax.while_loop (body AND cond: a cond-side callback
    fires every trip too)."""
    for eqn in jaxpr.eqns:
        yield eqn, in_loop
        loop = in_loop or eqn.primitive.name in _LOOP_PRIMS
        for v in eqn.params.values():
            for sub in _jaxprs_in(v):
                yield from iter_eqns(sub, loop)


# ------------------------------------------------------------------ rules
class IRRule:
    rule_id: str = ""
    description: str = ""
    hint: str = ""

    def check(self, spec: KernelSpec, jaxpr) -> Iterator[Finding]:
        raise NotImplementedError

    def finding(self, spec: KernelSpec, message: str) -> Finding:
        return Finding(spec.path, spec.line, self.rule_id, message,
                       self.hint, spec.name)


class Widen64BitRule(IRRule):
    """64-bit element types anywhere in the traced program. The source
    rules catch *lexical* int64 producers; this catches the ones tracing
    introduces — x64 mode flipped on, a weak-typed Python scalar
    promoting an op, a library helper converting under the covers. With
    jax_enable_x64 off this should be structurally impossible, which is
    exactly why it's worth pinning: a hit means the config or an
    extension leaked wide dtypes into a hot kernel."""

    rule_id = "ir-widen-64bit"
    description = "64-bit dtype in a traced kernel (absent from the source)"
    hint = ("trace with jax_enable_x64 off; narrow the producing operand "
            "(int32/float32) or cast at the host boundary, not in-kernel")

    def check(self, spec: KernelSpec, jaxpr) -> Iterator[Finding]:
        seen: Set[Tuple[str, str]] = set()
        for eqn, _ in iter_eqns(jaxpr):
            wide = []
            if eqn.primitive.name == "convert_element_type":
                dt = eqn.params.get("new_dtype")
                if dt is not None and getattr(dt, "itemsize", 0) == 8:
                    wide.append(str(dt))
            for o in eqn.outvars:
                dt = getattr(getattr(o, "aval", None), "dtype", None)
                if dt is not None and dt.itemsize == 8:
                    wide.append(str(dt))
            for dt in wide:
                key = (eqn.primitive.name, dt)
                if key in seen:
                    continue
                seen.add(key)
                yield self.finding(
                    spec,
                    f"traced `{spec.name}` materializes {dt} through "
                    f"`{eqn.primitive.name}` — a 64-bit temporary the "
                    f"source never spelled")


class CallbackInLoopRule(IRRule):
    """pure_callback / io_callback / debug_callback inside a scan or
    while body. Each fires a host round-trip per iteration — inside the
    miners' folds that is the double-buffered overlap silently gone, and
    on TPU a per-step infeed/outfeed stall."""

    rule_id = "ir-callback-in-loop"
    description = "host callback inside a scan/while body"
    hint = ("hoist the callback out of the loop (accumulate on device, "
            "call once after), or make it a post-hoc pass over the "
            "stacked scan outputs")

    def check(self, spec: KernelSpec, jaxpr) -> Iterator[Finding]:
        for eqn, in_loop in iter_eqns(jaxpr):
            if in_loop and eqn.primitive.name in _CALLBACK_PRIMS:
                yield self.finding(
                    spec,
                    f"`{eqn.primitive.name}` inside a scan/while body of "
                    f"traced `{spec.name}`: one host round-trip per "
                    f"iteration")


class HostTransferInLoopRule(IRRule):
    """device_put inside a scan/while body: a per-iteration placement/
    transfer op in the fold path (jax.device_get cannot appear in a
    jaxpr — it forces concretization at trace time and the tracer-leak
    AST rule owns that shape)."""

    rule_id = "ir-host-transfer-in-loop"
    description = "device_put inside a scan/while body"
    hint = ("place operands before the loop (device_put once, scan over "
            "device-resident arrays); inside the trace jnp.asarray is "
            "free and sufficient")

    def check(self, spec: KernelSpec, jaxpr) -> Iterator[Finding]:
        for eqn, in_loop in iter_eqns(jaxpr):
            if in_loop and eqn.primitive.name == "device_put":
                yield self.finding(
                    spec,
                    f"`device_put` inside a scan/while body of traced "
                    f"`{spec.name}`: per-iteration transfer in a fold path")


ALL_IR_RULES = [Widen64BitRule, CallbackInLoopRule, HostTransferInLoopRule]


def ir_rule_ids() -> List[str]:
    return [r.rule_id for r in ALL_IR_RULES] + [PAYLOAD_RULE]


# -------------------------------------------------------------- execution
def check_jaxpr(spec: KernelSpec, jaxpr,
                rules: Optional[Sequence[IRRule]] = None) -> List[Finding]:
    """Run the jaxpr rules over one traced kernel (fixture corpus entry
    point: tests hand-trace bad/good snippets and feed them here)."""
    active = list(rules) if rules is not None else [r() for r in ALL_IR_RULES]
    out: List[Finding] = []
    for rule in active:
        out.extend(rule.check(spec, jaxpr))
    return out


def _audit_mesh(spec: KernelSpec, devices):
    from avenir_tpu.parallel.mesh import data_mesh

    return data_mesh(devices[:AUDIT_DEVICES],
                     model_parallel=spec.model_parallel)


def audit_family(spec: KernelSpec, devices) -> Tuple[dict, Optional[Finding]]:
    """Lower one distributed family on the audit mesh, extract its
    collective payload bytes from compiled HLO, and compare against the
    analytic model. Returns (audit row, mismatch finding or None)."""
    mesh = _audit_mesh(spec, devices)
    fn, args = spec.build(mesh)
    return _audit_built(spec, mesh, fn, args)


def _audit_built(spec: KernelSpec, mesh, fn, args
                 ) -> Tuple[dict, Optional[Finding]]:
    """Audit body over an already-built (fn, args) — run_ir reuses the
    pair it traced so each family is constructed exactly once."""
    from avenir_tpu.parallel.scaling import hlo_collective_payloads

    try:
        compiled = fn.lower(*args).compile()
    except Exception as e:
        raise IRTraceError(
            f"{spec.name}: could not lower on the "
            f"{dict(mesh.shape)} mesh: {e!r}") from e
    ops = hlo_collective_payloads(compiled.as_text())
    got = sum(o["payload_bytes"] for o in ops)
    want = int(spec.payload_model(mesh))
    audit = {
        "family": spec.name,
        "mesh": {k: int(v) for k, v in mesh.shape.items()},
        "collectives": ops,
        "hlo_payload_bytes": got,
        "analytic_payload_bytes": want,
        "payload_model_validated": got == want,
    }
    finding = None
    if got != want:
        finding = Finding(
            spec.path, spec.line, PAYLOAD_RULE,
            f"family `{spec.name}` ships {got} collective bytes on the "
            f"{dict(mesh.shape)} mesh; the scaling.py model says {want} — "
            f"the traffic model (and every projection built on it) is "
            f"stale",
            "re-derive scaling.collective_payload_model for this family "
            "(or fix the kernel if XLA is reducing more than intended)",
            spec.name)
    return audit, finding


def run_ir(rules: Optional[Sequence[IRRule]] = None,
           baseline: Optional[Sequence[BaselineEntry]] = None,
           entries: Optional[Sequence[KernelSpec]] = None,
           audit: bool = True) -> Report:
    """Trace every manifest entry, run the jaxpr rules, audit every
    family's collective payload, and apply the allowlist baseline.

    Needs >= AUDIT_DEVICES jax devices (the test harness and the CLI both
    pin an 8-device virtual CPU pool); raises IRTraceError otherwise so
    the CLI can exit 2 instead of reporting a half-audited manifest as
    clean."""
    import jax

    devices = jax.devices()
    if len(devices) < AUDIT_DEVICES:
        raise IRTraceError(
            f"the collective-payload audit needs {AUDIT_DEVICES} devices, "
            f"found {len(devices)}; run under JAX_PLATFORMS=cpu with "
            f"XLA_FLAGS=--xla_force_host_platform_device_count="
            f"{AUDIT_DEVICES} (the graftlint CLI sets this up when jax "
            f"is not yet initialized)")
    specs = list(entries) if entries is not None else manifest_entries()
    active = list(rules) if rules is not None else [r() for r in ALL_IR_RULES]
    report = Report()
    raw: List[Finding] = []
    for spec in specs:
        if spec.path not in report.scanned:
            report.scanned.append(spec.path)
        mesh = _audit_mesh(spec, devices) if spec.is_family else None
        try:
            fn, args = spec.build(mesh)
            jaxpr = jax.make_jaxpr(fn)(*args)
        except IRTraceError:
            raise
        except Exception as e:
            raise IRTraceError(f"{spec.name}: could not trace: {e!r}") from e
        raw.extend(check_jaxpr(spec, jaxpr, active))
        if audit and spec.is_family:
            row, finding = _audit_built(spec, mesh, fn, args)
            report.payload_audit.append(row)
            if finding is not None:
                raw.append(finding)
    active_ids = {r.rule_id for r in active}
    if audit:
        active_ids.add(PAYLOAD_RULE)
    apply_baseline(report, raw, baseline, active_ids)
    return report
