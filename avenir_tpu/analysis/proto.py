"""graftlint --proto: the shared-filesystem protocol-discipline tier.

The repo's distributed substrate is files on one filesystem — spool
requests and results, leases, ledger claims and block states, shard
plans, checkpoints, sidecar manifests, tune profiles. Every one of them
is only correct under ONE discipline (docs/DESIGN.md "Publish is an
atomic commit"): write the complete payload to a uniquely-named SIBLING
tmp file, commit with a single atomic rename (``os.replace``, or
``os.link`` for first-commit-wins), clean the tmp on every exit path,
guard every shared read against torn/absent files, bound every poll,
and keep in-process deadline arithmetic on the monotonic clock. The
fabric-unification work (ROADMAP top item) merges two independently-
evolved protocol families — this tier is the gate that proves they
already speak the same discipline, in the established graftlint shape:

**Static rules** (AST, interprocedural within a module like flow.py)
over the protocol surface (``net/``, ``dist/``, ``server/spool.py`` +
jobserver snapshots, ``native/sidecar.py``, ``core/incremental.py``,
``core/atomic.py``, ``tune/store.py``):

- ``proto-nonatomic-publish`` — a write-mode open of a non-tmp path in
  a function with no atomic commit (replace/rename/link) and no
  publish helper: a reader can observe the torn intermediate.
- ``proto-tmp-not-sibling`` — the rename source lives in a different
  directory tree (tempfile.*, a ``/tmp`` literal) than its target:
  a cross-filesystem rename silently becomes copy+delete, not atomic.
- ``proto-shared-tmp-name`` — a FIXED tmp name (``path + ".tmp"``)
  committed by rename: two racing writers collide on the tmp and one
  publishes the other's half-written bytes.
- ``proto-torn-read-unguarded`` — ``json.load``/``loads`` of a shared
  file with no enclosing guard for torn/absent content.
- ``proto-unbounded-poll`` — a sleep-poll loop with no deadline,
  patience bound, stop predicate or raise: it hangs forever when the
  awaited file never appears.
- ``proto-wall-clock-deadline`` — ``time.time()`` arithmetic driving
  an in-process deadline/backoff comparison: an NTP step makes the
  bound fire instantly or never (``time.monotonic()`` is required;
  wall time stays only in persisted cross-process records).
- ``proto-tmp-leak-on-raise`` — a tmp written and renamed with no
  cleanup on the exception path: crashed writers strand tmps forever.

**Mechanical auditor** (:func:`audit_commit_points`): every publish
function registers its commit point in :data:`COMMIT_SITES`, and the
``AVENIR_PROTO_CRASH`` hook (core/atomic.py) lets the auditor run a
real small job per site in a subprocess and hard-kill it (``os._exit``)
at *after-tmp-write/before-rename* and at *after-rename*. Recovery —
re-running the same publish plus the startup stale-tmp sweep — must
leave the artifact BYTE-IDENTICAL to an uncrashed run (volatile wall
timestamps canonicalized away) with no stranded tmp and no
double-folded state. ``commit_point_validated`` is gated N/N like the
invariance/merge/footprint audits; the audit pseudo-rule
``proto-commit-point`` is never allowlisted. A registry cross-check
(:func:`check_site_registry`) greps the protocol surface for
``crash_point("<site>", ...)`` / ``site="<site>"`` annotations and
fails loudly when the code and :data:`COMMIT_SITES` disagree in either
direction — an unregistered publish is exactly the bug this tier
exists to catch.
"""

from __future__ import annotations

import ast
import json
import os
import re
import shutil
import subprocess
import sys
import tempfile
from dataclasses import dataclass
from typing import (Callable, Dict, Iterator, List, Optional, Sequence,
                    Set, Tuple)

from avenir_tpu.analysis.engine import (BaselineEntry, Finding,
                                        ModuleContext, Report,
                                        apply_baseline, collect_findings)
from avenir_tpu.core.atomic import (AFTER_RENAME, BEFORE_RENAME,
                                    CRASH_ENV, CRASH_EXIT, is_tmp_name,
                                    sweep_stale_tmps)

#: the audit pseudo-rule: commit-site kill-injection verdicts surface
#: under this id and are NEVER allowlisted
PROTO_AUDIT_RULE = "proto-commit-point"


class ProtoAuditError(RuntimeError):
    """The commit-point auditor could not run (driver crash, child
    failure, registry mismatch) — an environment/registry error, never
    a lint finding."""


def default_proto_paths(root: str) -> List[str]:
    """The protocol surface this tier lints: every module that reads or
    writes shared-filesystem protocol state."""
    names = [os.path.join("avenir_tpu", "net"),
             os.path.join("avenir_tpu", "dist"),
             os.path.join("avenir_tpu", "server", "spool.py"),
             os.path.join("avenir_tpu", "server", "jobserver.py"),
             os.path.join("avenir_tpu", "native", "sidecar.py"),
             os.path.join("avenir_tpu", "core", "incremental.py"),
             os.path.join("avenir_tpu", "core", "atomic.py"),
             os.path.join("avenir_tpu", "tune", "store.py"),
             os.path.join("avenir_tpu", "server", "score.py")]
    return [p for p in (os.path.join(root, n) for n in names)
            if os.path.exists(p)]


# --------------------------------------------------------------------------
# shared AST helpers
# --------------------------------------------------------------------------
_WRITE_MODES = {"w", "wb", "x", "xb", "w+", "wb+", "w+b", "x+b", "xb+"}
_COMMIT_CALLS = {"os.replace", "os.rename", "os.link"}
_REMOVE_CALLS = {"os.remove", "os.unlink"}
#: a call whose terminal name contains one of these delegates the
#: commit to the core.atomic discipline — the function under it is a
#: publish wrapper, not a hand-rolled protocol
_PUBLISH_MARKERS = ("publish", "write_json_atomic", "_write_atomic")
#: naming evidence that a tmp path carries a per-writer uniquifier
_UNIQUE_MARKERS = ("uuid", "getpid", "mkstemp", "namedtemporary",
                   "nonce", "unique")
_GUARD_EXCEPTIONS = {"ValueError", "JSONDecodeError", "KeyError",
                     "Exception", "BaseException"}
#: evidence that a sleep-poll loop is bounded (deadline/patience
#: arithmetic, a stop predicate, liveness checks)
_POLL_BOUND_MARKERS = ("deadline", "monotonic", "perf_counter",
                       "patience", "stop", "done", "alive", "is_set",
                       "expired", "timeout", "until", "attempts",
                       "retries", "bound", "remaining")
#: deadline-flavored target names for wall-clock deadline construction
_DEADLINE_NAMES = ("deadline", "backoff", "restart_at", "retry_at",
                   "expires", "until", "_at")

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)


def _functions(ctx: ModuleContext) -> Iterator[ast.FunctionDef]:
    for node in ast.walk(ctx.tree):
        if isinstance(node, _FUNC_NODES):
            yield node


def _calls(node: ast.AST) -> Iterator[ast.Call]:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            yield sub


def _terminal_name(ctx: ModuleContext, call: ast.Call) -> str:
    """The last dotted segment of the callee (``fh.write`` -> `write`),
    lower-cased; empty for non-name callees."""
    dotted = ctx.dotted(call.func)
    if dotted:
        return dotted.rsplit(".", 1)[-1].lower()
    if isinstance(call.func, ast.Attribute):
        return call.func.attr.lower()
    return ""


def _write_open_path(ctx: ModuleContext, call: ast.Call
                     ) -> Optional[ast.AST]:
    """The path expression of an ``open(path, "w"/"wb"/...)`` call, or
    None when the call is not a literal write-mode open."""
    if ctx.dotted(call.func) not in ("open", "io.open") or not call.args:
        return None
    mode = None
    if len(call.args) >= 2:
        mode = call.args[1]
    for kw in call.keywords:
        if kw.arg == "mode":
            mode = kw.value
    if not (isinstance(mode, ast.Constant)
            and isinstance(mode.value, str)):
        return None
    if mode.value not in _WRITE_MODES and "a" not in mode.value:
        return None
    if "a" in mode.value:
        return None                 # append is its own (log) discipline
    return call.args[0]


def _resolve_map(ctx: ModuleContext,
                 fn: ast.FunctionDef) -> Dict[str, List[ast.AST]]:
    """Name -> assigned value expressions, for soup resolution: the
    function's simple local assigns plus the enclosing class's
    ``self.x = ...`` assigns across all its methods (a tmp path is
    often built in ``__init__`` and renamed in ``commit``)."""
    out: Dict[str, List[ast.AST]] = {}

    def note(target: ast.AST, value: ast.AST) -> None:
        if isinstance(target, ast.Name):
            out.setdefault(target.id, []).append(value)
        elif isinstance(target, ast.Attribute) \
                and isinstance(target.value, ast.Name) \
                and target.value.id == "self":
            out.setdefault(f"self.{target.attr}", []).append(value)

    def harvest(scope: ast.AST) -> None:
        for node in ast.walk(scope):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    note(t, node.value)
            elif isinstance(node, ast.AnnAssign) and node.value:
                note(node.target, node.value)

    harvest(fn)
    cur = ctx.parent(fn)
    while cur is not None and not isinstance(cur, ast.ClassDef):
        cur = ctx.parent(cur)
    if cur is not None:
        harvest(cur)
    return out


def _soup(ctx: ModuleContext, expr: ast.AST,
          resolve: Optional[Dict[str, List[ast.AST]]] = None,
          depth: int = 2) -> str:
    """A lower-cased bag of the names, attributes, string constants and
    callee names an expression (and, up to `depth` levels, the local
    assignments it references) is built from — the naming-evidence
    substrate the tmp-likeness and uniquifier checks read."""
    parts: List[str] = []
    stack: List[Tuple[ast.AST, int]] = [(expr, depth)]
    while stack:
        node, d = stack.pop()
        for sub in ast.walk(node):
            if isinstance(sub, ast.Constant) and isinstance(sub.value,
                                                            str):
                parts.append(sub.value.lower())
            elif isinstance(sub, ast.Name):
                parts.append(sub.id.lower())
                if resolve and d > 0:
                    for v in resolve.get(sub.id, ()):
                        stack.append((v, d - 1))
            elif isinstance(sub, ast.Attribute):
                parts.append(sub.attr.lower())
                if resolve and d > 0 \
                        and isinstance(sub.value, ast.Name) \
                        and sub.value.id == "self":
                    for v in resolve.get(f"self.{sub.attr}", ()):
                        stack.append((v, d - 1))
    return " ".join(parts)


def _tmp_like(soup: str) -> bool:
    return "tmp" in soup or "temp" in soup


def _has_unique_marker(soup: str) -> bool:
    return any(m in soup for m in _UNIQUE_MARKERS)


def _foreign_tmp_root(ctx: ModuleContext, expr: ast.AST,
                      resolve: Dict[str, List[ast.AST]]) -> bool:
    """True when the expression (shallow-resolved) is derived from a
    tempfile.* directory or a ``/tmp`` literal — a root with no
    same-filesystem guarantee relative to the rename target."""
    stack: List[Tuple[ast.AST, int]] = [(expr, 2)]
    while stack:
        node, d = stack.pop()
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                dotted = ctx.dotted(sub.func) or ""
                if dotted.startswith("tempfile."):
                    return True
            elif isinstance(sub, ast.Constant) \
                    and isinstance(sub.value, str) \
                    and sub.value.startswith("/tmp"):
                return True
            elif isinstance(sub, ast.Name) and d > 0:
                for v in resolve.get(sub.id, ()):
                    stack.append((v, d - 1))
    return False


# --------------------------------------------------------------------------
# rules
# --------------------------------------------------------------------------
class ProtoRule:
    rule_id: str = ""
    description: str = ""
    hint: str = ""

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        raise NotImplementedError

    def finding(self, ctx: ModuleContext, node: ast.AST, message: str,
                hint: Optional[str] = None) -> Finding:
        return Finding(ctx.path, getattr(node, "lineno", 1),
                       self.rule_id, message, hint or self.hint,
                       ctx.scope_of(node))


class NonatomicPublishRule(ProtoRule):
    """A function write-opens a non-tmp path and never commits anything
    atomically (no replace/rename/link, no publish helper): whatever it
    writes is observable half-written by any concurrent reader — the
    exact torn state every protocol reader in this repo is specified
    never to see."""

    rule_id = "proto-nonatomic-publish"
    description = "shared-file write without tmp + atomic rename commit"
    hint = ("publish through core.atomic.publish_bytes/publish_json "
            "(unique sibling tmp + os.replace), or os.link for "
            "first-commit-wins records")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for fn in _functions(ctx):
            commits = False
            for call in _calls(fn):
                dotted = ctx.dotted(call.func) or ""
                term = _terminal_name(ctx, call)
                if dotted in _COMMIT_CALLS \
                        or any(m in term for m in _PUBLISH_MARKERS):
                    commits = True
                    break
            if commits:
                continue
            resolve = _resolve_map(ctx, fn)
            for call in _calls(fn):
                path_expr = _write_open_path(ctx, call)
                if path_expr is None:
                    continue
                if _tmp_like(_soup(ctx, path_expr, resolve)):
                    continue        # a staged tmp: the commit is elsewhere
                yield self.finding(
                    ctx, call,
                    f"`{fn.name}` write-opens a shared path with no "
                    f"atomic commit in sight: a concurrent reader can "
                    f"observe the half-written file")


class TmpNotSiblingRule(ProtoRule):
    """An atomic-looking rename whose source was staged under a
    DIFFERENT directory tree (tempfile.*, a /tmp literal): when the
    stage and the target sit on different filesystems, os.replace
    degrades to EXDEV failure and the usual fallback (copy+delete) is
    not atomic — the tmp must be a sibling of its target."""

    rule_id = "proto-tmp-not-sibling"
    description = "rename source staged outside the target's directory"
    hint = ("stage with core.atomic.unique_tmp(path) — the tmp is a "
            "same-directory sibling by construction, so the commit "
            "rename is same-filesystem and atomic")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for fn in _functions(ctx):
            resolve = _resolve_map(ctx, fn)
            for call in _calls(fn):
                if ctx.dotted(call.func) not in _COMMIT_CALLS \
                        or len(call.args) < 2:
                    continue
                src, dst = call.args[0], call.args[1]
                if _foreign_tmp_root(ctx, src, resolve) \
                        and not _foreign_tmp_root(ctx, dst, resolve):
                    yield self.finding(
                        ctx, call,
                        f"`{fn.name}` renames from a tempfile/tmpdir "
                        f"stage into a different tree: a cross-"
                        f"filesystem rename is not atomic")


class SharedTmpNameRule(ProtoRule):
    """A rename-committed tmp path with a FIXED name (``path + '.tmp'``
    and friends, no uuid/pid/mkstemp uniquifier): two racing writers
    collide on the tmp — the slower one overwrites the faster one's
    bytes mid-publish and the rename commits a torn hybrid."""

    rule_id = "proto-shared-tmp-name"
    description = "fixed-name tmp two racing writers collide on"
    hint = ("uniquify the stage per writer: core.atomic.unique_tmp "
            "(uuid sibling), or a pid/uuid suffix when hand-rolling "
            "a first-commit-wins link")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for fn in _functions(ctx):
            resolve = _resolve_map(ctx, fn)
            for call in _calls(fn):
                if ctx.dotted(call.func) not in _COMMIT_CALLS \
                        or not call.args:
                    continue
                soup = _soup(ctx, call.args[0], resolve)
                if _tmp_like(soup) and not _has_unique_marker(soup):
                    yield self.finding(
                        ctx, call,
                        f"`{fn.name}` commits a fixed-name tmp: two "
                        f"racing writers share one stage path and one "
                        f"publishes the other's half-written bytes")


class TornReadUnguardedRule(ProtoRule):
    """A ``json.load``/``json.loads`` of shared state with no enclosing
    try guarding torn/absent content (ValueError/JSONDecodeError/
    KeyError): writers are atomic, but a reader still races deletion
    and external truncation — every protocol reader in this repo
    treats an unparsable record as absent, never as a crash."""

    rule_id = "proto-torn-read-unguarded"
    description = "shared-file json.load without torn/absent guard"
    hint = ("wrap in try/except (OSError, ValueError, KeyError) and "
            "treat the torn record as absent (the claim_info / "
            "load_plan / load_claimed policy)")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call) \
                    or ctx.dotted(node.func) not in ("json.load",
                                                     "json.loads"):
                continue
            if self._guarded(ctx, node):
                continue
            yield self.finding(
                ctx, node,
                "json.load of a shared file with no torn/absent guard: "
                "a reader racing deletion or truncation crashes instead "
                "of treating the record as absent")

    @staticmethod
    def _guarded(ctx: ModuleContext, node: ast.AST) -> bool:
        cur = ctx.parent(node)
        while cur is not None:
            if isinstance(cur, _FUNC_NODES):
                return False
            if isinstance(cur, ast.Try):
                for handler in cur.handlers:
                    if handler.type is None:
                        return True
                    names = {n.rsplit(".", 1)[-1]
                             for n in (ctx.dotted(t) or ""
                                       for t in ast.walk(handler.type))
                             if n}
                    if names & _GUARD_EXCEPTIONS:
                        return True
            cur = ctx.parent(cur)
        return False


class UnboundedPollRule(ProtoRule):
    """A sleep-poll while-loop with no deadline, patience bound, stop
    predicate, liveness check or in-loop raise: when the awaited file
    never appears (its writer died), the loop spins to the caller's
    outermost timeout — or forever."""

    rule_id = "proto-unbounded-poll"
    description = "sleep-poll loop with no deadline or stop predicate"
    hint = ("bound the loop: a time.monotonic()/perf_counter deadline "
            "that raises, a should_stop()/patience predicate, or a "
            "liveness check on the awaited writer")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.While):
                continue
            sleeps = any(
                _terminal_name(ctx, c) in ("sleep", "wait")
                for c in _calls(node))
            if not sleeps:
                continue
            if any(isinstance(sub, ast.Raise) for sub in ast.walk(node)):
                continue
            soup = _soup(ctx, node)
            if any(m in soup for m in _POLL_BOUND_MARKERS):
                continue
            yield self.finding(
                ctx, node,
                "sleep-poll loop with no deadline, stop predicate or "
                "liveness bound: it hangs forever when the awaited "
                "writer is gone")


class WallClockDeadlineRule(ProtoRule):
    """``time.time()`` arithmetic driving an in-process deadline or
    duration comparison (both compared values wall-derived locals):
    an NTP step stretches or collapses the bound — leases expire
    instantly or never. ``time.monotonic()`` is required for every
    in-process duration; wall time belongs only in persisted records
    compared across processes (attribute/subscript loads are exempt
    for exactly that reason). Wall taint propagates through same-module
    call sites into callee parameters, like flow.py's interprocedural
    passes."""

    rule_id = "proto-wall-clock-deadline"
    description = "wall-clock arithmetic driving an in-process deadline"
    hint = ("use time.monotonic() for in-process backoff/patience/"
            "deadline arithmetic; keep time.time() only for persisted "
            "cross-process record timestamps")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        taint = self._module_taint(ctx)
        for fn in _functions(ctx):
            tainted = taint.get(fn, set())
            seen: Set[int] = set()
            for node in ast.walk(fn):
                sides: List[ast.AST] = []
                if isinstance(node, ast.Compare):
                    sides = [node.left] + list(node.comparators)
                elif isinstance(node, ast.BinOp) \
                        and isinstance(node.op, ast.Sub):
                    sides = [node.left, node.right]
                if len(sides) < 2:
                    continue
                wall = [s for s in sides
                        if self._pure_wall(ctx, s, tainted)]
                if len(wall) < 2 or node.lineno in seen:
                    continue
                seen.add(node.lineno)
                yield self.finding(
                    ctx, node,
                    f"`{fn.name}` compares/differences two wall-clock "
                    f"(time.time-derived) values in-process: an NTP "
                    f"step makes this bound fire instantly or never")

    # -------------------------------------------------- wall taint
    @staticmethod
    def _is_wall_call(ctx: ModuleContext, node: ast.AST) -> bool:
        return isinstance(node, ast.Call) \
            and ctx.dotted(node.func) == "time.time"

    def _expr_tainted(self, ctx: ModuleContext, expr: ast.AST,
                      tainted: Set[str]) -> bool:
        for sub in ast.walk(expr):
            if self._is_wall_call(ctx, sub):
                return True
            if isinstance(sub, ast.Name) and sub.id in tainted:
                return True
        return False

    def _pure_wall(self, ctx: ModuleContext, expr: ast.AST,
                   tainted: Set[str]) -> bool:
        """Wall-derived AND built only from locals/constants — an
        attribute or subscript load means a persisted cross-process
        record is involved, which is the legitimate use of wall time."""
        wall = False
        for sub in ast.walk(expr):
            if self._is_wall_call(ctx, sub):
                wall = True
            elif isinstance(sub, ast.Call):
                return False
            elif isinstance(sub, ast.Attribute):
                if (ctx.dotted(sub) or "") != "time.time":
                    return False
            elif isinstance(sub, ast.Subscript):
                return False
            elif isinstance(sub, ast.Name):
                if sub.id in tainted:
                    wall = True
        return wall

    def _module_taint(self, ctx: ModuleContext
                      ) -> Dict[ast.FunctionDef, Set[str]]:
        fns = list(_functions(ctx))
        by_name: Dict[str, List[ast.FunctionDef]] = {}
        for fn in fns:
            by_name.setdefault(fn.name, []).append(fn)
        taint: Dict[ast.FunctionDef, Set[str]] = {fn: set() for fn in fns}
        for _ in range(3):
            changed = False
            for fn in fns:
                tainted = taint[fn]
                # local propagation: assignments from wall expressions
                for _pass in range(2):
                    for node in ast.walk(fn):
                        if not isinstance(node, ast.Assign):
                            continue
                        if not self._expr_tainted(ctx, node.value,
                                                  tainted):
                            continue
                        for t in node.targets:
                            if isinstance(t, ast.Name) \
                                    and t.id not in tainted:
                                tainted.add(t.id)
                                changed = True
                # call-site propagation into same-module callees
                for call in _calls(fn):
                    name = _terminal_name(ctx, call)
                    targets = by_name.get(name)
                    if not targets or len(targets) != 1:
                        continue
                    callee = targets[0]
                    params = [a.arg for a in callee.args.args]
                    offset = 1 if params[:1] == ["self"] else 0
                    for i, arg in enumerate(call.args):
                        if not self._expr_tainted(ctx, arg, taint[fn]):
                            continue
                        idx = i + offset
                        if idx < len(params) \
                                and params[idx] not in taint[callee]:
                            taint[callee].add(params[idx])
                            changed = True
                    for kw in call.keywords:
                        if kw.arg and kw.arg in params \
                                and self._expr_tainted(ctx, kw.value,
                                                       taint[fn]) \
                                and kw.arg not in taint[callee]:
                            taint[callee].add(kw.arg)
                            changed = True
            if not changed:
                break
        return taint


class TmpLeakOnRaiseRule(ProtoRule):
    """A function stages a tmp and commits by rename but never removes
    the tmp on the exception path (no remove/unlink in any except
    handler or finally): every crash between stage and commit strands
    a tmp file in the shared root forever."""

    rule_id = "proto-tmp-leak-on-raise"
    description = "staged tmp not cleaned on the exception path"
    hint = ("wrap stage+commit so the tmp is removed on failure "
            "(try/finally os.remove, or the core.atomic.publish_* "
            "helpers which do it for you)")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        for fn in _functions(ctx):
            resolve = _resolve_map(ctx, fn)
            staged = None
            commits = False
            for call in _calls(fn):
                path_expr = _write_open_path(ctx, call)
                if path_expr is not None \
                        and _tmp_like(_soup(ctx, path_expr, resolve)):
                    staged = staged or call
                if ctx.dotted(call.func) in _COMMIT_CALLS:
                    commits = True
            if staged is None or not commits:
                continue
            if self._cleans_on_failure(ctx, fn):
                continue
            yield self.finding(
                ctx, staged,
                f"`{fn.name}` stages a tmp and renames it but never "
                f"removes the tmp on the exception path: a crash "
                f"between stage and commit strands it forever")

    @staticmethod
    def _cleans_on_failure(ctx: ModuleContext,
                           fn: ast.FunctionDef) -> bool:
        for node in ast.walk(fn):
            if not isinstance(node, ast.Try):
                continue
            failure_bodies = list(node.finalbody)
            for handler in node.handlers:
                failure_bodies.extend(handler.body)
            for stmt in failure_bodies:
                for call in _calls(stmt):
                    if ctx.dotted(call.func) in _REMOVE_CALLS:
                        return True
        return False


ALL_PROTO_RULES = [NonatomicPublishRule, TmpNotSiblingRule,
                   SharedTmpNameRule, TornReadUnguardedRule,
                   UnboundedPollRule, WallClockDeadlineRule,
                   TmpLeakOnRaiseRule]


def proto_rule_ids() -> List[str]:
    return [r.rule_id for r in ALL_PROTO_RULES] + [PROTO_AUDIT_RULE]


# --------------------------------------------------------------------------
# commit-site registry
# --------------------------------------------------------------------------
@dataclass
class CommitSite:
    """One registered commit point: a name (matching the site string
    its publish function passes to ``crash_point``/``site=``), the
    module that implements it, and a driver that runs ONE real small
    publish of that site rooted at a given directory. The driver must
    be deterministic (volatile wall timestamps excepted — the audit
    canonicalizes those) and IDEMPOTENT under re-run: recovery after a
    crash is literally running it again, exactly like the restarted
    writer would."""

    name: str
    path: str
    run: Callable[[str], None]
    #: override the crash child's ``python -c`` source (tests inject
    #: deliberately-broken sites the package does not export);
    #: ``__ROOT__`` is substituted with the crash root
    child_source: Optional[str] = None


def _run_ledger_claim(root: str) -> None:
    from avenir_tpu.dist.ledger import BlockLedger
    BlockLedger(root).claim(1, 0)


def _run_ledger_commit(root: str) -> None:
    from avenir_tpu.dist.ledger import BlockLedger
    led = BlockLedger(root)
    if 2 not in led.committed():      # the restarted worker's recovery
        led.commit(2, 0, b"block-2-state")


def _run_ledger_dup(root: str) -> None:
    from avenir_tpu.dist.ledger import BlockLedger
    led = BlockLedger(root)
    if 3 not in led.committed():
        led.commit(3, 0, b"block-3-state")
    led.commit(3, 1, b"block-3-dup")  # rejected: records the dup marker


def _run_ledger_format(root: str) -> None:
    from avenir_tpu.dist.ledger import BlockLedger
    BlockLedger(root)      # construction stamps states/FORMAT.json


def _run_plan_manifest(root: str) -> None:
    from avenir_tpu.dist.plan import write_json_atomic
    write_json_atomic({"procs": 1, "factor": 1, "blocks": []},
                      os.path.join(root, "plan.json"))


def _run_lease_write(root: str) -> None:
    from avenir_tpu.net.fault import Lease, LeaseStore
    LeaseStore(root).write(Lease(name="r000001.json", host=0,
                                 claimed_at=1000.0, ttl_s=5.0))


def _run_spool_result(root: str) -> None:
    from avenir_tpu.server.spool import publish_result
    out_dir = os.path.join(root, "out")
    os.makedirs(out_dir, exist_ok=True)
    publish_result(out_dir, "r1.json", {"ok": True, "name": "audit"})


def _run_spool_dead_letter(root: str) -> None:
    from avenir_tpu.server.spool import dead_letter
    work_dir = os.path.join(root, "work")
    os.makedirs(work_dir, exist_ok=True)
    work_path = os.path.join(work_dir, "q.json")
    with open(work_path, "w") as fh:   # the torn request being buried
        fh.write("{not json")
    dead_letter(root, "q.json", work_path, "ValueError: torn request")


def _run_spool_port(root: str) -> None:
    from avenir_tpu.server.spool import write_port_file
    write_port_file(os.path.join(root, "port"), 43210)


def _run_checkpoint_save(root: str) -> None:
    from avenir_tpu.core.incremental import CheckpointStore
    CheckpointStore(os.path.join(root, "state")).save(
        {"seq": 1, "job": "audit"}, b"carry-bytes")


def _run_profile_save(root: str) -> None:
    from avenir_tpu.tune.store import ProfileStore
    ProfileStore(os.path.join(root, "tune")).set_knobs(
        "audit", "deadbeef", {}, ["proto audit"])


def _run_score_reward(root: str) -> None:
    from avenir_tpu.server.score import append_reward
    artifact = os.path.join(root, "bandit_stats.csv")
    try:
        with open(artifact, "x") as fh:     # EAFP: re-run keeps the file
            fh.write("g1,i1,5,2.0\ng1,i2,3,4.0\n")
    except FileExistsError:
        pass
    # the nonce makes the recovery (re-running the append) idempotent:
    # an entry that already committed dedupes instead of doubling
    append_reward(artifact, "g1", "i2", 7.0, count=1,
                  nonce="proto-audit-reward")


def _run_sidecar_manifest(root: str) -> None:
    from avenir_tpu.native.sidecar import FORMAT, _write_manifest
    dirpath = os.path.join(root, "sc")
    os.makedirs(dirpath, exist_ok=True)
    _write_manifest(dirpath, {"format": FORMAT, "blocks": []})


#: every registered commit point — each publish function on the
#: protocol surface annotates its commit (``crash_point(name, ...)``
#: directly or ``site=name`` through the atomic helpers) and registers
#: a driver here; check_site_registry fails loudly on a mismatch in
#: either direction
COMMIT_SITES: List[CommitSite] = [
    CommitSite("ledger.claim", "avenir_tpu/dist/ledger.py",
               _run_ledger_claim),
    CommitSite("ledger.commit", "avenir_tpu/dist/ledger.py",
               _run_ledger_commit),
    CommitSite("ledger.dup", "avenir_tpu/dist/ledger.py",
               _run_ledger_dup),
    CommitSite("ledger.format", "avenir_tpu/dist/ledger.py",
               _run_ledger_format),
    CommitSite("plan.manifest", "avenir_tpu/dist/plan.py",
               _run_plan_manifest),
    CommitSite("lease.write", "avenir_tpu/net/fault.py",
               _run_lease_write),
    CommitSite("spool.result", "avenir_tpu/server/spool.py",
               _run_spool_result),
    CommitSite("spool.dead_letter", "avenir_tpu/server/spool.py",
               _run_spool_dead_letter),
    CommitSite("spool.port", "avenir_tpu/server/spool.py",
               _run_spool_port),
    CommitSite("checkpoint.save", "avenir_tpu/core/incremental.py",
               _run_checkpoint_save),
    CommitSite("profile.save", "avenir_tpu/tune/store.py",
               _run_profile_save),
    CommitSite("sidecar.manifest", "avenir_tpu/native/sidecar.py",
               _run_sidecar_manifest),
    CommitSite("score.reward", "avenir_tpu/server/score.py",
               _run_score_reward),
]


def commit_sites() -> List[CommitSite]:
    return list(COMMIT_SITES)


def _drive_site(name: str, root: str) -> None:
    """The crash child's entry point: run one registered site's driver
    with the ``AVENIR_PROTO_CRASH`` hook armed by the parent."""
    for site in COMMIT_SITES:
        if site.name == name:
            site.run(root)
            return
    raise SystemExit(f"unknown commit site {name!r}")


# --------------------------------------------------------------------------
# registry cross-check
# --------------------------------------------------------------------------
#: a site annotation in protocol code: crash_point("name", ...) or a
#: site="name" keyword into the atomic publish helpers
_SITE_REF_RE = re.compile(r'(?:crash_point\(\s*|site\s*=\s*)"([a-z_.]+)"')


def _pkg_root() -> str:
    """The repo root the avenir_tpu package under audit lives in."""
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def site_annotations(root: Optional[str] = None
                     ) -> Dict[str, Tuple[str, int]]:
    """Every site name annotated on the protocol surface, mapped to
    the (repo-relative path, line) of its first annotation."""
    root = root or _pkg_root()
    refs: Dict[str, Tuple[str, int]] = {}
    files: List[str] = []
    for p in default_proto_paths(root):
        if os.path.isdir(p):
            for dirpath, dirnames, names in os.walk(p):
                dirnames.sort()
                files.extend(os.path.join(dirpath, n)
                             for n in sorted(names)
                             if n.endswith(".py"))
        else:
            files.append(p)
    for path in files:
        try:
            with open(path, encoding="utf-8") as fh:
                text = fh.read()
        except OSError:
            continue
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        for i, line in enumerate(text.splitlines(), 1):
            for m in _SITE_REF_RE.finditer(line):
                refs.setdefault(m.group(1), (rel, i))
    return refs


def check_site_registry(root: Optional[str] = None
                        ) -> Dict[str, Tuple[str, int]]:
    """Fail loudly when the code annotations and COMMIT_SITES disagree:
    an annotated-but-unregistered site escapes the crash audit, a
    registered-but-unannotated site means the registry points at a
    publish that no longer exists. Returns the annotation locations
    (the audit rows' path/line source)."""
    refs = site_annotations(root)
    names = {s.name for s in COMMIT_SITES}
    unregistered = sorted(set(refs) - names)
    unannotated = sorted(names - set(refs))
    problems = []
    if unregistered:
        problems.append(
            f"annotated in code but not in COMMIT_SITES (no crash "
            f"audit covers them): {unregistered}")
    if unannotated:
        problems.append(
            f"registered in COMMIT_SITES but never annotated in code "
            f"(dangling registry entries): {unannotated}")
    if problems:
        raise ProtoAuditError(
            "commit-site registry mismatch: " + "; ".join(problems))
    return refs


# --------------------------------------------------------------------------
# crash-point auditor
# --------------------------------------------------------------------------
#: wall-clock fields protocol records legitimately persist — stripped
#: before byte comparison (two correct runs stamp different times)
_VOLATILE_KEYS = ("claimed_at", "rejected_at", "ts_unix")


def _canon(rel: str, data: bytes) -> bytes:
    """Canonical bytes of one artifact: JSON files are re-serialized
    with volatile wall-timestamp fields dropped and keys sorted, so
    byte comparison proves structural identity; everything else
    compares raw."""
    if not rel.endswith(".json"):
        return data
    try:
        obj = json.loads(data.decode("utf-8"))
    except (ValueError, UnicodeDecodeError):
        return data                 # torn JSON: compare (and fail) raw
    if isinstance(obj, dict):
        for key in _VOLATILE_KEYS:
            obj.pop(key, None)
    return json.dumps(obj, sort_keys=True).encode("utf-8")


def _snapshot(root: str) -> Dict[str, bytes]:
    out: Dict[str, bytes] = {}
    for dirpath, dirnames, names in os.walk(root):
        dirnames.sort()
        for n in sorted(names):
            if is_tmp_name(n):
                continue
            path = os.path.join(dirpath, n)
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            try:
                with open(path, "rb") as fh:
                    out[rel] = _canon(rel, fh.read())
            except OSError:
                out[rel] = b"<unreadable>"
    return out


def _tmp_leftovers(root: str) -> List[str]:
    out = []
    for dirpath, _dirnames, names in os.walk(root):
        out.extend(os.path.relpath(os.path.join(dirpath, n), root)
                   for n in names if is_tmp_name(n))
    return sorted(out)


def _spawn_crash_child(site: CommitSite, root: str,
                       stage: str) -> subprocess.CompletedProcess:
    env = dict(os.environ)
    env[CRASH_ENV] = f"{site.name}:{stage}"
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (_pkg_root(), env.get("PYTHONPATH")) if p)
    if site.child_source is not None:
        code = site.child_source.replace("__ROOT__", root)
    else:
        code = ("from avenir_tpu.analysis.proto import _drive_site; "
                f"_drive_site({site.name!r}, {root!r})")
    try:
        return subprocess.run([sys.executable, "-c", code], env=env,
                              capture_output=True, text=True,
                              timeout=120)
    except subprocess.TimeoutExpired as e:
        raise ProtoAuditError(
            f"commit site {site.name} [{stage}]: crash child timed "
            f"out after 120s") from e


def audit_commit_points(sites: Optional[Sequence[CommitSite]] = None,
                        locations: Optional[
                            Dict[str, Tuple[str, int]]] = None
                        ) -> Tuple[List[dict], List[Finding]]:
    """Kill-injection audit of every registered commit site: per site,
    run the publish uncrashed (the reference artifact), then twice in a
    subprocess hard-killed at *before-rename* and *after-rename*, then
    recover (re-run the publish + the startup stale-tmp sweep) and
    assert the recovered artifact is byte-identical to the reference
    with no stranded tmp. Returns (rows, findings) — one row per site,
    one ``proto-commit-point`` finding per failed site. Driver/child
    infrastructure failures raise :class:`ProtoAuditError`."""
    sites = list(sites) if sites is not None else list(COMMIT_SITES)
    locations = locations or {}
    rows: List[dict] = []
    findings: List[Finding] = []
    base = tempfile.mkdtemp(prefix="graftlint_proto_")
    try:
        for site in sites:
            loc = locations.get(site.name)
            site_dir = os.path.join(base, site.name.replace(".", "_"))
            clean_root = os.path.join(site_dir, "clean")
            os.makedirs(clean_root, exist_ok=True)
            try:
                site.run(clean_root)
            except Exception as e:
                raise ProtoAuditError(
                    f"commit site {site.name}: clean driver failed: "
                    f"{type(e).__name__}: {e}") from e
            want = _snapshot(clean_root)
            if not want:
                raise ProtoAuditError(
                    f"commit site {site.name}: clean driver published "
                    f"no artifact — nothing to validate")
            problems: List[str] = []
            stage_rows: List[dict] = []
            for stage in (BEFORE_RENAME, AFTER_RENAME):
                crash_root = os.path.join(site_dir, stage)
                os.makedirs(crash_root, exist_ok=True)
                proc = _spawn_crash_child(site, crash_root, stage)
                crashed = proc.returncode == CRASH_EXIT
                if not crashed and proc.returncode != 0:
                    raise ProtoAuditError(
                        f"commit site {site.name} [{stage}]: crash "
                        f"child failed rc={proc.returncode}: "
                        f"{(proc.stderr or '').strip()[-400:]}")
                # recovery = what the next writer does: re-run the
                # publish, then the startup sweep (age-forced — the
                # audit plays the 'later' startup)
                try:
                    site.run(crash_root)
                    recovered = True
                except Exception as e:  # noqa: BLE001 — verdict, not crash
                    recovered = False
                    problems.append(
                        f"{stage}: recovery raised "
                        f"{type(e).__name__}: {e}")
                sweep_stale_tmps(crash_root, min_age_s=0.0)
                got = _snapshot(crash_root)
                identical = got == want
                leftovers = _tmp_leftovers(crash_root)
                stage_rows.append({"stage": stage, "crashed": crashed,
                                   "recovered": recovered,
                                   "byte_identical": identical,
                                   "tmp_clean": not leftovers})
                if not crashed:
                    problems.append(
                        f"{stage}: crash hook never reached (the "
                        f"publish does not pass this site to "
                        f"crash_point)")
                if not identical:
                    drift = sorted(set(want) ^ set(got)) or \
                        sorted(k for k in want
                               if got.get(k) != want[k])
                    problems.append(
                        f"{stage}: recovered artifact differs from the "
                        f"uncrashed run (drifting: {drift[:4]})")
                if leftovers:
                    problems.append(
                        f"{stage}: stranded tmp files survive recovery "
                        f"+ sweep: {leftovers[:4]}")
            validated = not problems
            rows.append({"site": site.name,
                         "path": loc[0] if loc else site.path,
                         "line": loc[1] if loc else 1,
                         "stages": stage_rows,
                         "commit_point_validated": validated})
            if not validated:
                findings.append(Finding(
                    loc[0] if loc else site.path,
                    loc[1] if loc else 1,
                    PROTO_AUDIT_RULE,
                    f"commit site `{site.name}` failed crash-point "
                    f"validation: {'; '.join(problems)}",
                    "publish through core.atomic (unique sibling tmp, "
                    "atomic rename, tmp cleaned on every path) and "
                    "keep the recovery re-run idempotent; never "
                    "allowlist a commit-point failure",
                    site.name))
    finally:
        shutil.rmtree(base, ignore_errors=True)
    return rows, findings


# --------------------------------------------------------------------------
# runner
# --------------------------------------------------------------------------
def run_proto(paths: Optional[Sequence[str]] = None,
              rules: Optional[Sequence[ProtoRule]] = None,
              baseline: Optional[Sequence[BaselineEntry]] = None,
              root: Optional[str] = None, include_md: bool = True,
              audit: bool = True,
              sites: Optional[Sequence[CommitSite]] = None) -> Report:
    """Lint `paths` (default: the protocol surface) with the proto
    rules, run the commit-point crash auditor over the registered
    sites (default: COMMIT_SITES, after the registry cross-check), and
    apply the allowlist baseline to the rule findings — audit findings
    are never baselined away."""
    active = list(rules) if rules is not None else \
        [r() for r in ALL_PROTO_RULES]
    root = os.path.abspath(root or os.getcwd())
    scan = list(paths) if paths else default_proto_paths(root)
    report, raw = collect_findings(scan, active, root, include_md)
    if audit:
        locations: Dict[str, Tuple[str, int]] = {}
        if sites is None:
            # default registry: prove code annotations and registry
            # agree before trusting either, and source row locations
            # from the real annotation lines
            locations = check_site_registry()
        rows, audit_findings = audit_commit_points(
            sites=sites, locations=locations)
        # audit drivers are NOT added to report.scanned — the audit
        # drives the publish functions, it does not lint their files
        report.proto_audit.extend(rows)
        raw.extend(audit_findings)
    active_ids = {r.rule_id for r in active}
    if audit:
        active_ids.add(PROTO_AUDIT_RULE)
    apply_baseline(report, raw, baseline, active_ids)
    return report
