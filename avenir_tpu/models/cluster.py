"""Clustering: agglomerative (graph distance based), k-means, DBSCAN.

Reference surface:
- cluster/AgglomerativeGraphical.java:43 — greedy agglomerative clustering
  over precomputed pairwise distances (EntityDistanceMapFileAccessor);
  cluster membership by average edge weight (EdgeWeightedCluster.java:32).
- python/unsupv/cluster.py — scikit KMeans / AgglomerativeClustering /
  DBSCAN with model selection by cohesion + inter-cluster distance.

TPU design: k-means is the device-native one — Lloyd iterations are one
distance matmul + segment_sum per step under jit. Agglomerative and DBSCAN
operate on a (device-computed) distance matrix with host merge loops, like
the reference's file-of-distances design.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Optional

import numpy as np
import jax
import jax.numpy as jnp

from avenir_tpu.core.dataset import Dataset
from avenir_tpu.ops.distance import pairwise_distance

_EPS = 1e-9


# ---------------------------------------------------------------------------
# k-means (Lloyd under jit)
# ---------------------------------------------------------------------------


@partial(jax.jit, static_argnames=("k",))
def _kmeans_step(x, centers, k: int):
    d2 = (
        jnp.sum(x * x, axis=1)[:, None]
        + jnp.sum(centers * centers, axis=1)[None, :]
        - 2.0 * x @ centers.T
    )
    assign = jnp.argmin(d2, axis=1)
    sums = jax.ops.segment_sum(x, assign, num_segments=k)
    cnts = jax.ops.segment_sum(jnp.ones(x.shape[0]), assign, num_segments=k)
    new_centers = sums / jnp.maximum(cnts[:, None], 1.0)
    # keep empty clusters where they were
    new_centers = jnp.where(cnts[:, None] > 0, new_centers, centers)
    inertia = jnp.sum(jnp.min(d2, axis=1))
    return new_centers, assign, inertia


class KMeans:
    def __init__(self, k: int, iters: int = 50, seed: int = 0, tol: float = 1e-5):
        self.k = k
        self.iters = iters
        self.seed = seed
        self.tol = tol

    def fit(self, x: np.ndarray) -> "KMeans":
        rng = np.random.default_rng(self.seed)
        x = np.asarray(x, np.float32)
        init = x[rng.choice(len(x), self.k, replace=False)]
        centers = jnp.asarray(init)
        xd = jnp.asarray(x)
        prev_inertia = np.inf
        for _ in range(self.iters):
            centers, assign, inertia = _kmeans_step(xd, centers, self.k)
            if abs(prev_inertia - float(inertia)) < self.tol * max(float(inertia), 1.0):
                break
            prev_inertia = float(inertia)
        self.centers = np.asarray(centers)
        self.labels_ = np.asarray(assign)
        self.inertia_ = float(inertia)
        return self

    def predict(self, x: np.ndarray) -> np.ndarray:
        d2 = ((np.asarray(x)[:, None, :] - self.centers[None]) ** 2).sum(-1)
        return d2.argmin(axis=1)


# ---------------------------------------------------------------------------
# agglomerative (average linkage over a distance matrix)
# ---------------------------------------------------------------------------


class AgglomerativeGraphical:
    """Greedy agglomerative merging over pairwise distances with an
    average-edge-weight membership criterion (AgglomerativeGraphical.java:43,
    EdgeWeightedCluster.java:32): merge the closest pair of clusters while
    the resulting cluster's average intra-edge distance stays below
    `max_avg_distance`, up to `num_clusters`."""

    def __init__(self, num_clusters: int = 2,
                 max_avg_distance: Optional[float] = None):
        self.num_clusters = num_clusters
        self.max_avg_distance = max_avg_distance

    def fit(self, dist: np.ndarray) -> "AgglomerativeGraphical":
        n = dist.shape[0]
        clusters: Dict[int, List[int]] = {i: [i] for i in range(n)}
        d = dist.astype(np.float64).copy()
        np.fill_diagonal(d, np.inf)
        cd = {(i, j): d[i, j] for i in range(n) for j in range(i + 1, n)}

        def avg_intra(members: List[int]) -> float:
            if len(members) < 2:
                return 0.0
            s = cnt = 0
            for a in range(len(members)):
                for b in range(a + 1, len(members)):
                    s += dist[members[a], members[b]]
                    cnt += 1
            return s / cnt

        while len(clusters) > self.num_clusters:
            (i, j), _ = min(
                ((pair, val) for pair, val in cd.items()
                 if pair[0] in clusters and pair[1] in clusters),
                key=lambda kv: kv[1],
            )
            merged = clusters[i] + clusters[j]
            if (self.max_avg_distance is not None
                    and avg_intra(merged) > self.max_avg_distance):
                break
            del clusters[j]
            clusters[i] = merged
            # average linkage update
            for k in list(clusters):
                if k == i:
                    continue
                a, b = min(i, k), max(i, k)
                pairs = [(x, y) for x in clusters[i] for y in clusters[k]]
                cd[(a, b)] = float(np.mean([dist[x, y] for x, y in pairs]))

        self.labels_ = np.zeros(n, np.int32)
        for li, members in enumerate(clusters.values()):
            for m in members:
                self.labels_[m] = li
        return self


# ---------------------------------------------------------------------------
# DBSCAN
# ---------------------------------------------------------------------------


class DBSCAN:
    """Density clustering over a distance matrix (python/unsupv/cluster.py
    parity). Noise points get label -1."""

    def __init__(self, eps: float, min_samples: int = 4):
        self.eps = eps
        self.min_samples = min_samples

    def fit(self, dist: np.ndarray) -> "DBSCAN":
        n = dist.shape[0]
        neigh = [np.flatnonzero(dist[i] <= self.eps) for i in range(n)]
        core = np.array([len(nb) >= self.min_samples for nb in neigh])
        labels = np.full(n, -1, np.int32)
        cid = 0
        for i in range(n):
            if labels[i] != -1 or not core[i]:
                continue
            stack = [i]
            labels[i] = cid
            while stack:
                p = stack.pop()
                for q in neigh[p]:
                    if labels[q] == -1:
                        labels[q] = cid
                        if core[q]:
                            stack.append(q)
            cid += 1
        self.labels_ = labels
        return self


# ---------------------------------------------------------------------------
# model selection metrics (python/unsupv/cluster.py cohesion / separation)
# ---------------------------------------------------------------------------


def cohesion(x: np.ndarray, labels: np.ndarray) -> float:
    """Mean distance to own-cluster centroid (lower = tighter)."""
    total = 0.0
    for c in np.unique(labels[labels >= 0]):
        members = x[labels == c]
        centroid = members.mean(axis=0)
        total += np.linalg.norm(members - centroid, axis=1).sum()
    valid = (labels >= 0).sum()
    return total / max(valid, 1)


def inter_cluster_distance(x: np.ndarray, labels: np.ndarray) -> float:
    """Mean pairwise centroid distance (higher = better separated)."""
    cents = [x[labels == c].mean(axis=0) for c in np.unique(labels[labels >= 0])]
    if len(cents) < 2:
        return 0.0
    tot = cnt = 0
    for a in range(len(cents)):
        for b in range(a + 1, len(cents)):
            tot += np.linalg.norm(cents[a] - cents[b])
            cnt += 1
    return tot / cnt


def dataset_distance_matrix(ds: Dataset, metric: str = "euclidean") -> np.ndarray:
    """Device-computed mixed-attribute distance matrix for the host
    clustering algorithms (the EntityDistanceMapFileAccessor role)."""
    from avenir_tpu.core.dataset import extract_mixed_features

    x_num, ranges, x_cat, bins = extract_mixed_features(ds)
    d = pairwise_distance(
        jnp.asarray(x_num),
        jnp.asarray(x_num),
        jnp.asarray(x_cat) if x_cat is not None else None,
        jnp.asarray(x_cat) if x_cat is not None else None,
        cat_bins=bins,
        num_ranges=jnp.asarray(ranges) if ranges.size else None,
        metric=metric,
    )
    return np.asarray(d)


# ---------------------------------------------------------------------------
# cluster-tendency exploration (python/unsupv/cluster.py expl_* functions)
# ---------------------------------------------------------------------------


def _min_cross_distances(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """Distance from each row of `a` to its nearest row of `b`
    (lib/support.py find_min_distances), as one device matmul-distance."""
    sq_a = jnp.sum(a * a, axis=1)[:, None]
    sq_b = jnp.sum(b * b, axis=1)[None, :]
    d2 = jnp.maximum(sq_a + sq_b - 2.0 * (a @ b.T), 0.0)
    return jnp.sqrt(jnp.min(d2, axis=1))


def hopkins_statistic(x: np.ndarray, x_random: np.ndarray,
                      sample_size: int, num_iters: int = 1,
                      seed: int = 0) -> float:
    """Hopkins cluster-tendency statistic (expl_hopkins,
    unsupv/cluster.py:104-134): ~0.5 means no cluster structure, near 0
    means clustered. Each iteration splits off `sample_size` real points
    and `sample_size` uniform-random points, sums nearest-neighbor
    distances to the remaining data, and averages
    spl_sum / (ran_sum + spl_sum) over iterations."""
    if sample_size >= len(x):
        raise ValueError(f"sample_size {sample_size} must be < len(x) {len(x)}")
    if sample_size > len(x_random):
        raise ValueError(
            f"sample_size {sample_size} exceeds len(x_random) {len(x_random)}")
    rng = np.random.default_rng(seed)
    xj = jnp.asarray(x, jnp.float32)
    xr = jnp.asarray(x_random, jnp.float32)
    stats = []
    for _ in range(num_iters):
        perm = rng.permutation(len(x))
        spl, tra = xj[perm[:sample_size]], xj[perm[sample_size:]]
        ran = xr[rng.permutation(len(x_random))[:sample_size]]
        ran_sum = float(jnp.sum(_min_cross_distances(ran, tra)))
        spl_sum = float(jnp.sum(_min_cross_distances(spl, tra)))
        stats.append(spl_sum / max(ran_sum + spl_sum, 1e-30))
    return float(np.mean(stats))


def k_dist(x: np.ndarray, neighbor_index: int,
           first_order_diff: bool = False) -> np.ndarray:
    """Sorted distance-to-kth-neighbor curves for DBSCAN eps selection
    (expl_kdist, unsupv/cluster.py:138-158). Returns [n, k] columns each
    sorted ascending (or their first-order diffs [n-1, k])."""
    xj = jnp.asarray(x, jnp.float32)
    sq = jnp.sum(xj * xj, axis=1)
    d2 = jnp.maximum(sq[:, None] + sq[None, :] - 2.0 * (xj @ xj.T), 0.0)
    d = jnp.sqrt(d2.at[jnp.diag_indices(xj.shape[0])].set(jnp.inf))
    # k smallest per row (excluding self), then sort each column
    neg_top, _ = jax.lax.top_k(-d, neighbor_index)
    dist = jnp.sort(-neg_top, axis=0)
    out = np.asarray(dist)
    return np.diff(out, axis=0) if first_order_diff else out


def _scale_min_max(v: np.ndarray) -> np.ndarray:
    lo, hi = v.min(), v.max()
    return (v - lo) / (hi - lo) if hi > lo else np.zeros_like(v)


def validity_index(under_partition: np.ndarray,
                   over_partition: np.ndarray) -> np.ndarray:
    """Cluster-count selection index (validity_index,
    unsupv/cluster.py:168-172): min-max-scaled under-partition measure
    (e.g. cohesion) + scaled over-partition measure (e.g. 1/separation);
    minimize over candidate k."""
    return (_scale_min_max(np.asarray(under_partition, np.float64))
            + _scale_min_max(np.asarray(over_partition, np.float64)))
