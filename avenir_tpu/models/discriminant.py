"""Fisher discriminant analysis per feature.

Reference (discriminant/FisherDiscriminant.java:42): reuses chombo's
NumericalAttrStats mapper/combiner to get per-(feature, class) mean and
variance; the reducer computes the pooled variance and a per-feature class
boundary shifted by the log prior odds (:83-96):

    boundary = (m0 + m1)/2 + pooledVar * ln(p(c0)/p(c1)) / (m1 - m0)

One moment-reduction einsum gives all features' stats at once.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np

from avenir_tpu.core.dataset import Dataset

_EPS = 1e-12


class FisherDiscriminant:
    """Per-numeric-feature two-class linear boundary."""

    def __init__(self):
        self.boundaries: Dict[int, float] = {}
        self.means: Dict[int, Tuple[float, float]] = {}
        self.fields: List = []
        self._cnt = None

    def accumulate(self, ds: Dataset) -> "FisherDiscriminant":
        """Fold one chunk's per-class moments (count, sum, sum-sq) —
        additive, so the discriminant streams like every count job.

        The per-chunk sums run in float64 ON THE HOST. They used to be
        a float32 device einsum, whose rounding depends on how many
        rows land in one chunk — at 10M-row corpora that moved the
        published boundary in the 4th decimal when the block size
        changed, breaking the chunk-invariance contract every tuned or
        re-chunked scan relies on (caught by
        bench_scaling.autotune_tripwire's byte-identity gate). float64
        keeps the layout sensitivity ~9 orders below the artifact's
        %.6f formatting; the moment fold is O(rows x features) adds —
        never this job's bottleneck."""
        if self._cnt is None:
            self.fields = [f for f in ds.schema.feature_fields
                           if f.is_numeric]
            assert ds.schema.num_classes() == 2, \
                "Fisher discriminant is two-class"
            self._cnt = np.zeros(2, np.float64)
            self._s1 = np.zeros((2, len(self.fields)), np.float64)
            self._s2 = np.zeros((2, len(self.fields)), np.float64)
        x = np.asarray(ds.feature_matrix(self.fields), np.float64)  # [n, F]
        y = np.asarray(ds.labels())
        for k in (0, 1):
            xk = x[y == k]
            self._cnt[k] += xk.shape[0]
            self._s1[k] += xk.sum(axis=0)
            self._s2[k] += (xk * xk).sum(axis=0)
        return self

    def merge(self, other: "FisherDiscriminant") -> "FisherDiscriminant":
        """Fold another partial fit's per-class moments into this one —
        the NaiveBayesModel.merge algebra for the discriminant: (count,
        sum, sum-sq) are additive, so merging shard fits equals fitting
        the concatenated shards. Both sides must be un-finalized partial
        accumulations over the same numeric feature set; an empty
        `other` merges as a no-op and an empty `self` adopts `other`."""
        if other._cnt is None:
            return self
        if self._cnt is None:
            self.fields = other.fields
            self._cnt, self._s1, self._s2 = other._cnt, other._s1, other._s2
            return self
        if [f.ordinal for f in self.fields] != \
                [f.ordinal for f in other.fields]:
            raise ValueError(
                "cannot merge discriminants over different feature sets")
        self._cnt += other._cnt
        self._s1 += other._s1
        self._s2 += other._s2
        return self

    def finalize(self) -> "FisherDiscriminant":
        cnt_np, s1_np, s2_np = self._cnt, self._s1, self._s2
        mean = s1_np / np.maximum(cnt_np[:, None], _EPS)
        var = s2_np / np.maximum(cnt_np[:, None], _EPS) - mean ** 2
        pooled = (
            (cnt_np[0] * var[0] + cnt_np[1] * var[1])
            / max(cnt_np.sum(), _EPS)
        )
        prior = cnt_np / cnt_np.sum()
        log_odds = np.log(max(prior[0], _EPS) / max(prior[1], _EPS))
        for fi, fld in enumerate(self.fields):
            m0, m1 = mean[0, fi], mean[1, fi]
            sep = m1 - m0
            b = (m0 + m1) / 2.0
            if abs(sep) > _EPS:
                b += pooled[fi] * log_odds / sep
            self.boundaries[fld.ordinal] = float(b)
            self.means[fld.ordinal] = (float(m0), float(m1))
        return self

    def fit(self, ds: Dataset) -> "FisherDiscriminant":
        # refit from scratch (fit has always been idempotent); streaming
        # callers use accumulate()/finalize() directly
        self._cnt = None
        self.boundaries, self.means = {}, {}
        return self.accumulate(ds).finalize()

    def predict(self, ds: Dataset, ordinal: int) -> np.ndarray:
        """Classify by the single-feature boundary: class 1 iff the value is
        on class 1's mean side of the boundary."""
        return self.predict_values(ordinal,
                                   ds.column(ordinal).astype(np.float64))

    def predict_values(self, ordinal: int, x: np.ndarray) -> np.ndarray:
        """Vectorized entry point over raw float64 values — the math
        :meth:`predict` applies to a Dataset column, shared with the
        online scoring path so batch and per-request classifications
        can never drift (each comparison is per-row, so the result is
        invariant to batch composition by construction)."""
        x = np.asarray(x, np.float64)
        b = self.boundaries[ordinal]
        m0, m1 = self.means[ordinal]
        side = x >= b if m1 >= m0 else x < b
        return side.astype(np.int32)

    def save(self, path: str, delim: str = ",", stamp: bool = True) -> None:
        """``stamp`` publishes the format/digest sidecar the serving
        path verifies at load (models/artifact.py)."""
        with open(path, "w") as fh:
            for ordn, b in self.boundaries.items():
                m0, m1 = self.means[ordn]
                fh.write(f"{ordn}{delim}{b:.6f}{delim}{m0:.6f}{delim}{m1:.6f}\n")
        if stamp:
            from avenir_tpu.models.artifact import write_stamp
            write_stamp(path)

    @classmethod
    def load(cls, path: str, delim: str = ",") -> "FisherDiscriminant":
        """Read a saved boundary table back into a servable
        discriminant (digest-verified when a stamp sidecar exists; the
        train-side moments are not persisted, so a loaded model only
        predicts)."""
        from avenir_tpu.models.artifact import verify_stamp
        verify_stamp(path)
        fd = cls()
        with open(path) as fh:
            for ln in fh:
                if not ln.strip():
                    continue
                ordn, b, m0, m1 = ln.rstrip("\n").split(delim)[:4]
                fd.boundaries[int(ordn)] = float(b)
                fd.means[int(ordn)] = (float(m0), float(m1))
        return fd
