"""Sequence mining: GSP candidate generation + support, positional clusters.

Reference (SURVEY §2.8 sequence/): CandidateGenerationWithSelfJoin.java:44-200
implements the GSP candidate-generation self-join of frequent
(k-1)-sequences: sequences a, b join when a[1:] == b[:-1] (candidate =
a + [b[-1]]), with the all-same-token self-join special case
(selfJoinSequence, :156-172); the MR job shards the join via hashed bucket
pairs. SequencePositionalCluster.java:49 scores a sliding time window of
events against locality strategies (hoidla TimeBoundEventLocalityAnalyzer:
occurrence count / average interval / max interval, weighted or
condition-gated) and emits window positions whose score beats a threshold.

TPU-native design: the join is tiny host work over the frequent set (the
bucket-pair sharding exists only because Hadoop must shuffle; in-process a
dict join is exact and cheaper). What the reference leaves to a separate
pass — counting how many data sequences contain each candidate as an
order-preserving subsequence — is the N-proportional work, and runs on
device: one `lax.scan` over time steps advances a per-(row, candidate)
match pointer, so support for ALL candidates over ALL rows is a single
compiled pass with [N, C] state.
"""

from __future__ import annotations

import os

from dataclasses import dataclass
from functools import partial
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from avenir_tpu import obs as _obs
from avenir_tpu.native.ingest import SpillScanMixin


# ---------------------------------------------------------------------------
# GSP candidate generation (host) + device support counting
# ---------------------------------------------------------------------------
def join_sequences(this_seq: Sequence[str], that_seq: Sequence[str]
                   ) -> Optional[List[str]]:
    """GSP join rule (CandidateGenerationWithSelfJoin.joinSquences:174-200):
    if this[1:] == that[:-1] the candidate is this + [that[-1]], else the
    symmetric direction that + [this[-1]]."""
    if list(this_seq[1:]) == list(that_seq[:-1]):
        return list(this_seq) + [that_seq[-1]]
    if list(that_seq[1:]) == list(this_seq[:-1]):
        return list(that_seq) + [this_seq[-1]]
    return None


def self_join_sequence(seq: Sequence[str]) -> Optional[List[str]]:
    """All-same-token sequences extend themselves (selfJoinSequence:156-172)."""
    if all(t == seq[0] for t in seq):
        return list(seq) + [seq[0]]
    return None


def generate_sequence_candidates(frequent: Iterable[Sequence[str]]
                                 ) -> List[Tuple[str, ...]]:
    """All GSP k-candidates from the frequent (k-1)-sequence set, deduped.

    Indexes sequences by their (k-2)-prefix so each sequence only meets the
    sequences whose prefix equals its suffix — the in-process equivalent of
    the MR job's hashed bucket-pair self-join."""
    freq = [tuple(s) for s in frequent]
    by_prefix: Dict[Tuple[str, ...], List[Tuple[str, ...]]] = {}
    for s in freq:
        by_prefix.setdefault(s[:-1], []).append(s)
    out = set()
    for s in freq:
        sj = self_join_sequence(s)
        if sj is not None:
            out.add(tuple(sj))
        for t in by_prefix.get(s[1:], ()):
            j = join_sequences(s, t)
            if j is not None:
                out.add(tuple(j))
    return sorted(out)


@jax.jit
def _subseq_support_kernel(rows: jnp.ndarray, cands: jnp.ndarray,
                           k_vec: jnp.ndarray):
    """counts[c] = #rows containing candidate c as an order-preserving
    (not necessarily contiguous) subsequence.

    rows int32 [N, T] padded with -1, cands int32 [C, k_max] padded with
    -2, k_vec int32 [C] the per-candidate length. One scan over the T
    time steps advances ptr[n, c] (next candidate position to match); a
    row supports the candidate when its pointer reaches k_vec[c]. The
    candidate length rides as DATA, not a static argument, so one
    compiled executable serves every mining round (per block-shape
    bucket) instead of recompiling per k — and candidates of mixed
    lengths can share a call. Zero-length rows (k_vec 0: shape padding)
    never count."""
    n, t = rows.shape
    c, k_max = cands.shape

    def step(ptr, tok):                      # ptr [N, C], tok [N]
        expect = cands[jnp.arange(c)[None, :],
                       jnp.clip(ptr, 0, k_max - 1)]      # [N, C]
        hit = ((tok[:, None] == expect) & (ptr < k_vec[None, :])
               & (tok[:, None] >= 0))
        return ptr + hit.astype(jnp.int32), None

    ptr, _ = jax.lax.scan(step, jnp.zeros((n, c), jnp.int32), rows.T)
    return jnp.sum((ptr >= k_vec[None, :]) & (k_vec > 0)[None, :],
                   axis=0, dtype=jnp.int32)


@partial(jax.jit, donate_argnums=(0,))
def _subseq_fold_kernel(acc: jnp.ndarray, rows: jnp.ndarray,
                        cands: jnp.ndarray, k_vec: jnp.ndarray):
    """acc + _subseq_support_kernel(rows, cands, k_vec) with the
    accumulator DONATED — the streamed GSP per-chunk fold carry. One [C]
    int32 buffer lives on device across the whole per-k pass (no
    per-chunk allocation, no host round trip); int32 support counts are
    exact, so the fold is chunk-layout-invariant by associativity."""
    return acc + _subseq_support_kernel(rows, cands, k_vec)


def stream_candidate_support(src: "StreamingSequenceSource",
                             cands: List[Tuple[str, ...]], c_pad: int,
                             block: int = 65536) -> np.ndarray:
    """One streamed support pass over ONE source: token-space
    candidates encoded via src.token_code (-2 for tokens this source
    never saw, which match nothing), blocks double-buffered against
    the donated int32 device fold. The SINGLE implementation of the
    N-proportional counting — mine_stream, the sharded
    mine_stream_merged driver and the distributed per-k block workers
    all fold through it, which is what makes their counts (and
    therefore their outputs) identical by construction."""
    from avenir_tpu.core.stream import double_buffered

    cand_d, kv = GSPMiner._cand_arrays(cands, src.token_code, c_pad)
    counts_d = jnp.zeros(c_pad, jnp.int32)
    for blk in double_buffered(src.chunks(block)):
        # host-side span: the donated fold dispatches async, so the
        # duration is dispatch+transfer time, not device occupancy
        t0 = _obs.now()
        counts_d = _subseq_fold_kernel(
            counts_d, jnp.asarray(blk), cand_d, kv)
        _obs.record("stream.fold", t0, sink="gsp_support")
    return np.asarray(counts_d, np.int64)


def count_token_supports(src: "StreamingSequenceSource",
                         cands: List[Tuple[str, ...]], c_pad: int,
                         block: int = 65536) -> np.ndarray:
    """Support counts of token-space GSP candidates over ONE source,
    aligned to ``cands`` — the per-shard body of mine_stream_merged
    AND the sharded per-k worker's block fold. GSP candidates are
    already canonical token tuples, so token_code's -2 never-matches
    sentinel handles absent tokens without present-filtering."""
    return stream_candidate_support(src, cands, c_pad,
                                    block)[:len(cands)]


@dataclass
class SequenceSet:
    """Dictionary-encoded, padded sequences (pad token -1)."""
    rows: np.ndarray                 # int32 [N, T]
    lengths: np.ndarray              # int32 [N]
    vocab: List[str]
    index: Dict[str, int]

    @classmethod
    def from_token_rows(cls, token_rows: Sequence[Sequence[str]],
                        skip_field_count: int = 1) -> "SequenceSet":
        vocab: List[str] = []
        index: Dict[str, int] = {}
        enc = []
        for r in token_rows:
            toks = list(r[skip_field_count:])
            row = []
            for tok in toks:
                if tok == "":
                    continue
                if tok not in index:
                    index[tok] = len(vocab)
                    vocab.append(tok)
                row.append(index[tok])
            enc.append(row)
        t = max((len(r) for r in enc), default=1)
        rows = np.full((len(enc), max(t, 1)), -1, np.int32)
        for i, r in enumerate(enc):
            rows[i, :len(r)] = r
        lengths = np.array([len(r) for r in enc], np.int32)
        return cls(rows, lengths, vocab, index)

    def __len__(self) -> int:
        return self.rows.shape[0]


class StreamingSequenceSource(SpillScanMixin):
    """Re-iterable chunked sequence reader for unbounded-size GSP mining.

    GSP is inherently multi-pass (the reference runs one MR job per
    sequence length k over the same input); streaming means each k-pass
    re-scans the file at O(block) host RSS. scan() freezes the token
    vocabulary, row count and max sequence length; chunks() then yields
    fixed-shape padded [block_rows, t_max] blocks encoded against that
    vocabulary (native seq_encode when built, python split otherwise)."""

    def __init__(self, paths: Sequence[str], delim: str = ",",
                 skip_field_count: int = 1, block_bytes: int = 64 << 20,
                 spill_cache: bool = True,
                 cache_budget_bytes: Optional[int] = None):
        self.paths = list(paths)
        self.delim = delim
        self.skip = skip_field_count
        self.block_bytes = block_bytes
        self.spill_cache = spill_cache
        self.cache_budget_bytes = cache_budget_bytes
        self.vocab: List[str] = []
        self.index: Dict[str, int] = {}
        self.n_rows = 0
        self.t_max = 1
        self._item_counts: Optional[np.ndarray] = None
        self._kept_ids: Optional[np.ndarray] = None   # orig ids, ascending
        self._remap: Optional[np.ndarray] = None      # orig id -> masked|-1
        self._cache = None            # EncodedBlockCache once pass 1 ran
        self._scan_counts: Optional[np.ndarray] = None
        self._scan_encoder = None

    def _line_blocks(self):
        from avenir_tpu.core.stream import iter_line_blocks, prefetched

        for path in self.paths:
            yield from prefetched(
                iter_line_blocks(path, self.block_bytes), depth=1)

    # ----------------------------------------------------- frequent mask
    def mask_tokens(self, keep_ids: Sequence[int]) -> int:
        """Install the frequent-token mask after the k=1 scan: chunks()
        thereafter DROPS infrequent tokens and compacts each sequence
        (sound for GSP — every element of a frequent sequence is itself a
        frequent 1-sequence, so no candidate can require a dropped
        token), shrinking both the vocabulary and the time axis the
        support scan walks. Masked ids are ranks of the ascending
        original ids. Returns the masked vocabulary size."""
        kept = np.asarray(sorted(keep_ids), np.int32)
        remap = np.full(max(len(self.vocab), 1), -1, np.int32)
        remap[kept] = np.arange(kept.shape[0], dtype=np.int32)
        self._kept_ids, self._remap = kept, remap
        return int(kept.shape[0])

    def token_code(self, tok: str) -> int:
        """Candidate-encoding lookup in the chunks() id space (masked when
        a mask is installed); -2 never matches any token."""
        i = self.index.get(tok)
        if i is None:
            return -2
        if self._remap is not None:
            i = int(self._remap[i])
            if i < 0:
                return -2
        return i

    # (scan lifecycle, SharedScan sink adapter and cache ownership live
    # in native.ingest.SpillScanMixin — one copy for both miner sources)
    def _reset_scan_state(self) -> None:
        self.n_rows = 0
        self.t_max = 1

    def _scan_result(self) -> Tuple[List[str], np.ndarray, int]:
        return self.vocab, self._item_counts, self.n_rows

    def _note_encoded_rows(self, per_row: np.ndarray, n: int) -> None:
        self.t_max = max(self.t_max, int(per_row.max(initial=0)))
        self.n_rows += n

    def scan(self) -> Tuple[List[str], np.ndarray, int]:
        """Pass 1: (vocab, per-token row-presence counts, n_rows) — the
        k=1 support counts; also records t_max for fixed-shape chunks.
        Rides the native encoder when built (vocabulary-stable blocks
        never touch per-row Python, same discovery scheme as the
        association source), and spills each block's region-compacted
        codes to the encoded-block cache so later per-k support scans
        replay encoded blocks instead of re-parsing CSV."""
        if self._item_counts is not None:
            return self.vocab, self._item_counts, self.n_rows
        return self._scan_all()

    def _scan_block(self, data: bytes) -> None:
        from avenir_tpu.native.ingest import (csr_rows,
                                              distinct_row_code_counts)

        if self._scan_encoder is not None:
            out = self._scan_encoder.encode(data)
            if out is None:
                return
            codes, offsets, region, n = out
            self._grow_counts()
            row_of, _ = csr_rows(offsets)
            per_row = np.bincount(row_of[region].astype(np.intp),
                                  minlength=n)
            self.t_max = max(self.t_max, int(per_row.max(initial=0)))
            self._scan_counts += distinct_row_code_counts(
                row_of, codes, region, len(self.vocab))
            if self._cache is not None:
                self._cache.add_block(per_row, codes[region])
            self.n_rows += n
            return
        lines = [ln for ln in data.decode("utf-8", "replace").split("\n")
                 if ln.strip()]
        if not lines:
            return
        blk_counts = np.zeros(len(lines), np.int64)
        blk_codes: List[int] = []
        for r, ln in enumerate(lines):
            toks = [t.strip(" \t\r")
                    for t in ln.split(self.delim)][self.skip:]
            k0 = len(blk_codes)
            for tok in toks:
                if tok == "":
                    continue
                i = self.index.get(tok)
                if i is None:
                    i = len(self.vocab)
                    self.index[tok] = i
                    self.vocab.append(tok)
                blk_codes.append(i)
            blk_counts[r] = len(blk_codes) - k0
            self.t_max = max(self.t_max, int(blk_counts[r]))
        codes = np.asarray(blk_codes, np.int32)
        self._grow_counts()
        row_of = np.repeat(np.arange(len(lines), dtype=np.int32),
                           blk_counts)
        region = np.ones(codes.shape[0], bool)
        self._scan_counts += distinct_row_code_counts(
            row_of, codes, region, len(self.vocab))
        if self._cache is not None:
            self._cache.add_block(blk_counts, codes)
        self.n_rows += len(lines)

    def chunks(self, block_rows: int = 65536):
        """Yield padded int32 [rows_bucket, t_bucket] blocks (pad -1;
        all-pad rows support no candidate, so padding never counts).

        Both axes quantize to power-of-2 buckets PER BLOCK instead of
        padding everything to global maxima: one anomalously long input
        line must not inflate every block (O(block) RSS is the point of
        this class), and bucketing keeps recompiles logarithmic."""
        from avenir_tpu.native.ingest import (csr_region_mask, csr_rows,
                                              native_seq_ready,
                                              seq_encode_native)

        def bucket(x: int, lo: int) -> int:
            return max(lo, 1 << (max(x, 1) - 1).bit_length())

        def pages(rows_v, pos, enc, n):
            """Fixed-shape padded pages of one block's surviving tokens —
            shared by the re-parse and cache-replay paths so both yield
            bit-identical blocks."""
            bounds = np.searchsorted(
                rows_v, np.arange(0, n + block_rows, block_rows,
                                  dtype=np.int32))
            for page, (lo, hi) in enumerate(zip(bounds[:-1], bounds[1:])):
                rows_here = min(block_rows, n - page * block_rows)
                t_here = int(pos[lo:hi].max(initial=0)) + 1
                blk = np.full((bucket(rows_here, 1024),
                               bucket(t_here, 16)), -1, np.int32)
                blk[rows_v[lo:hi] - page * block_rows,
                    pos[lo:hi]] = enc[lo:hi]
                yield blk

        def replay_pages(blk_iter):
            # encoded-block replay: the pass-1 cache holds each block's
            # region tokens (counts per row + codes) — apply the
            # frequent-token mask, recompute compacted positions, page.
            # No CSV read, no tokenizer, either engine.
            from avenir_tpu.core.stream import prefetched

            for counts, codes in prefetched(blk_iter, depth=1):
                n = counts.shape[0]
                if n <= 0:
                    continue
                starts = np.zeros(n, np.int64)
                starts[1:] = np.cumsum(counts[:-1], dtype=np.int64)
                row_of = np.repeat(np.arange(n, dtype=np.int32), counts)
                if self._remap is not None:
                    enc_all = self._remap[codes]
                    valid = enc_all >= 0
                else:
                    enc_all = codes
                    valid = np.ones(codes.shape[0], bool)
                cs = np.cumsum(valid, dtype=np.int32)
                base = np.zeros(n, np.int32)
                nz = starts > 0
                base[nz] = cs[starts[nz] - 1]
                rows_v = row_of[valid]
                pos = cs[valid] - 1 - base[rows_v]
                yield from pages(rows_v, pos, enc_all[valid], n)

        def parse_pages(path, byte_range=None):
            from avenir_tpu.core.stream import iter_byte_blocks, prefetched

            for data in prefetched(
                    iter_byte_blocks(path, self.block_bytes, byte_range),
                    depth=1):
                codes, offsets = seq_encode_native(
                    data, self.delim, self.vocab)
                n = offsets.shape[0] - 1
                if n <= 0:
                    continue
                # sequence region, empty/meta tokens dropped like the
                # python path (ids can collide with item tokens only
                # at positions < skip, which this mask excludes)
                valid = csr_region_mask(offsets, self.skip,
                                        codes.shape[0])
                np.logical_and(valid, codes >= 0, out=valid)
                if self._remap is not None:
                    # frequent-token mask: infrequent tokens drop and
                    # positions compact (pos derives from survivors)
                    codes = np.where(valid, self._remap[
                        np.clip(codes, 0, None)], -1)
                    np.logical_and(valid, codes >= 0, out=valid)
                row_of, starts = csr_rows(offsets)
                # within-row rank of each surviving token in int32
                # region-mask form: one cumsum over the valid mask
                # replaces the flatnonzero/arange/searchsorted int64
                # triple that was the GSP pass's largest transient
                # (blocks never hold 2^31 tokens — they are tens of MB)
                cs = np.cumsum(valid, dtype=np.int32)
                base = np.zeros(n, np.int32)
                nz = starts > 0
                base[nz] = cs[starts[nz] - 1]
                rows_v = row_of[valid]
                pos = cs[valid] - 1 - base[rows_v]
                yield from pages(rows_v, pos, codes[valid], n)

        if self._cache is not None and self._cache.valid:
            yield from replay_pages(self._cache.blocks())
            return

        if native_seq_ready(self.delim):
            # per-source mix: sources whose segment the cache's byte
            # budget evicted re-parse natively, survivors keep replaying
            for si, path in enumerate(self.paths):
                if self._cache is None:
                    yield from parse_pages(path)
                    continue
                if self._cache.source_valid(si):
                    yield from replay_pages(self._cache.blocks(si))
                    continue
                delta = self._cache.source_delta(si)
                if delta is not None:
                    # appended source: committed blocks still content-
                    # match the file's prefix (per-block fingerprints) —
                    # replay them, re-parse only the appended tail
                    yield from replay_pages(
                        self._cache.blocks(si, prefix=True))
                    yield from parse_pages(
                        path, (delta, os.path.getsize(path)))
                else:
                    yield from parse_pages(path)
            return

        buf: List[List[int]] = []

        def emit(rows_enc):
            t_here = max((len(r) for r in rows_enc), default=1)
            blk = np.full((bucket(len(rows_enc), 1024),
                           bucket(t_here, 16)), -1, np.int32)
            for r, row in enumerate(rows_enc):
                blk[r, : len(row)] = row
            return blk

        for lines in self._line_blocks():
            for ln in lines:
                toks = [t.strip(" \t\r")
                        for t in ln.split(self.delim)][self.skip:]
                enc = [self.index[t] for t in toks if t != ""]
                if self._remap is not None:
                    enc = [m for m in
                           (int(self._remap[i]) for i in enc) if m >= 0]
                buf.append(enc)
                if len(buf) >= block_rows:
                    yield emit(buf)
                    buf = []
        if buf:
            yield emit(buf)


class GSPMiner:
    """Frequent-sequence miner: host GSP joins per k + device support scans.

    Mirrors the per-k loop the reference drives externally; cgs.* keys map
    to the constructor (cgs.item.set.length is the per-round k the job was
    invoked with; here the loop runs to max_length)."""

    def __init__(self, support_threshold: float, max_length: int = 3,
                 block: int = 65536):
        self.support_threshold = support_threshold
        self.max_length = max_length
        self.block = block

    @staticmethod
    def _cand_arrays(cands: List[Tuple[str, ...]], code_of, c_pad: int
                     ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """Packed (cands int32 [c_pad, k_bucket], k_vec int32 [c_pad]):
        mixed-length candidate rows padded with the -2 never-matches
        sentinel, zero-length pad rows never counted. The length axis
        quantizes to a pow2 bucket so successive mining rounds hit the
        same compiled shape."""
        k_max = max((len(cd) for cd in cands), default=1)
        k_max = max(4, 1 << (k_max - 1).bit_length())
        arr = np.full((c_pad, k_max), -2, np.int32)
        kv = np.zeros(c_pad, np.int32)
        for ci, cd in enumerate(cands):
            arr[ci, :len(cd)] = [code_of(tok) for tok in cd]
            kv[ci] = len(cd)
        return jnp.asarray(arr), jnp.asarray(kv)

    def _count(self, ss: SequenceSet, cands: List[Tuple[str, ...]]
               ) -> np.ndarray:
        cand_d, kv = self._cand_arrays(
            cands, lambda tok: ss.index.get(tok, -2), len(cands))
        counts = np.zeros(len(cands), np.int64)
        for s in range(0, len(ss), self.block):
            counts += np.asarray(_subseq_support_kernel(
                jnp.asarray(ss.rows[s:s + self.block]),
                cand_d, kv), dtype=np.int64)
        return counts

    def mine(self, ss: SequenceSet) -> Dict[int, Dict[Tuple[str, ...], float]]:
        n = len(ss)
        min_count = self.support_threshold * n
        out: Dict[int, Dict[Tuple[str, ...], float]] = {}

        cands1 = [(tok,) for tok in ss.vocab]
        counts = self._count(ss, cands1)
        freq = {c: cnt / n for c, cnt in zip(cands1, counts)
                if cnt > min_count}
        out[1] = freq

        for k in range(2, self.max_length + 1):
            cands = generate_sequence_candidates(list(freq))
            if not cands:
                break
            counts = self._count(ss, cands)
            freq = {c: cnt / n for c, cnt in zip(cands, counts)
                    if cnt > min_count}
            if not freq:
                break
            out[k] = freq
        return out

    def mine_stream(self, src: StreamingSequenceSource
                    ) -> Dict[int, Dict[Tuple[str, ...], float]]:
        """mine() at unbounded input size: one streamed scan per sequence
        length k (the reference's one-MR-job-per-k driver), candidate
        support folded across fixed-shape padded blocks so host RSS stays
        O(block). After the k=1 scan the frequent-token mask drops
        infrequent tokens at ingest (shrinking the time axis every later
        support scan walks), the candidate length rides as data so one
        compiled executable serves all rounds, and block encode
        double-buffers against the device fold."""
        from avenir_tpu.core.stream import double_buffered

        vocab, counts1, n = src.scan()
        min_count = self.support_threshold * n
        out: Dict[int, Dict[Tuple[str, ...], float]] = {}
        freq = {(tok,): cnt / n for tok, cnt in zip(vocab, counts1)
                if cnt > min_count}
        out[1] = freq
        src.mask_tokens([src.index[tok] for (tok,) in freq])

        for k in range(2, self.max_length + 1):
            cands = generate_sequence_candidates(list(freq))
            if not cands:
                break
            # candidate axis padded to a pow2 bucket (executable reuse);
            # the -2 sentinel never matches any token, so pad rows count 0.
            # Floor 16, not 64: the scan kernel carries [block, C] pointer
            # state through every time step, so a small round's padding
            # multiplies real work (unlike the bitset matmul's free lanes)
            c_pad = max(16, 1 << (len(cands) - 1).bit_length())
            counts = self._stream_support(src, cands, c_pad)
            freq = {c: cnt / n
                    for c, cnt in zip(cands, counts[: len(cands)])
                    if cnt > min_count}
            if not freq:
                break
            out[k] = freq
        return out

    def _stream_support(self, src: StreamingSequenceSource,
                        cands: List[Tuple[str, ...]], c_pad: int
                        ) -> np.ndarray:
        """One streamed support pass over ONE source — the module-level
        :func:`stream_candidate_support` at this miner's block size."""
        return stream_candidate_support(src, cands, c_pad, self.block)

    def _merged_rounds(self, support1: Dict, n: int, count_fn
                       ) -> Dict[int, Dict[Tuple[str, ...], float]]:
        """The per-k control loop of the MERGED GSP drivers: threshold
        the merged k=1 supports, generate each level's candidates,
        count them through ``count_fn(k, cands, c_pad) -> int64
        [len(cands)]``, prune, stop on an empty frontier. Shared by
        mine_stream_merged (counts per shard source in-process) and
        the sharded per-k driver (counts per ledger block across
        worker processes) — ONE loop, so their kept sets and supports
        agree by construction."""
        min_count = self.support_threshold * n
        out: Dict[int, Dict[Tuple[str, ...], float]] = {}
        freq = {(tok,): cnt / n for tok, cnt in sorted(support1.items())
                if cnt > min_count}
        out[1] = freq

        for k in range(2, self.max_length + 1):
            cands = generate_sequence_candidates(list(freq))
            if not cands:
                break
            c_pad = max(16, 1 << (len(cands) - 1).bit_length())
            counts = count_fn(k, cands, c_pad)
            freq = {c: cnt / n for c, cnt in zip(cands, counts)
                    if cnt > min_count}
            if not freq:
                break
            out[k] = freq
        return out

    def mine_stream_merged(self, sources: Sequence[StreamingSequenceSource]
                           ) -> Dict[int, Dict[Tuple[str, ...], float]]:
        """mine_stream() over P shard sources with the support-merge
        algebra (association.merge_support_counts): every per-k round
        counts each candidate independently per shard through the SAME
        _stream_support fold and sums the counts, thresholding against
        the GLOBAL row count — so the mined output equals a single
        mine_stream over the concatenated shards byte-identically
        (int32 per-shard counts partition exactly across row-aligned
        shards; the shard-merge auditor re-proves this every round).
        GSP candidates are already canonical token tuples, so no
        per-shard id translation beyond token_code is needed."""
        from avenir_tpu.models.association import merge_support_counts

        srcs = list(sources)
        if len(srcs) == 1:
            return self.mine_stream(srcs[0])
        scans = [src.scan() for src in srcs]
        n = sum(s[2] for s in scans)
        min_count = self.support_threshold * n
        support1 = merge_support_counts(
            *[{vocab[i]: int(counts[i]) for i in range(len(vocab))}
              for vocab, counts, _n in scans])
        freq_toks = [tok for tok, cnt in sorted(support1.items())
                     if cnt > min_count]
        for src in srcs:
            src.mask_tokens([src.index[tok] for tok in freq_toks
                             if tok in src.index])

        def count_level(k, cands, c_pad):
            counts = np.zeros(len(cands), np.int64)
            for src in srcs:
                counts += count_token_supports(src, cands, c_pad,
                                               self.block)
            return counts

        return self._merged_rounds(support1, n, count_level)


# ---------------------------------------------------------------------------
# Positional clustering of event sequences
# ---------------------------------------------------------------------------
class EventLocalityAnalyzer:
    """Sliding-window event-locality scoring
    (SequencePositionalCluster.java:49 + hoidla TimeBoundEventLocalityAnalyzer).

    Events are (timestamp, value) rows; an event "fires" when the value
    meets the condition. Per window the locality score comes from the
    configured strategies over firing-event timestamps:

      numOccurence     #events / window capacity (more events -> higher)
      averageInterval  1 - avg inter-event gap / window span
      maxInterval      1 - max inter-event gap / window span

    `weighted_strategies` mixes scores by weight; otherwise the preferred
    strategies are threshold conditions (min_occurence / max_interval_average
    / max_interval_max) combined with any/all (`any_cond`)."""

    STRATEGIES = ("numOccurence", "averageInterval", "maxInterval")

    def __init__(self, window_time_span: float, time_step: float,
                 score_threshold: float,
                 weighted_strategies: Optional[Dict[str, float]] = None,
                 preferred_strategies: Sequence[str] = ("numOccurence",),
                 min_occurence: int = 2,
                 max_interval_average: float = float("inf"),
                 max_interval_max: float = float("inf"),
                 any_cond: bool = True,
                 min_event_time_interval: float = 0.0):
        self.window = window_time_span
        self.step = time_step
        self.threshold = score_threshold
        self.weighted = weighted_strategies
        self.preferred = list(preferred_strategies)
        self.min_occurence = min_occurence
        self.max_interval_average = max_interval_average
        self.max_interval_max = max_interval_max
        self.any_cond = any_cond
        self.min_gap = min_event_time_interval

    def _window_score(self, times: np.ndarray) -> float:
        if len(times) == 0:
            return 0.0
        gaps = np.diff(times) if len(times) > 1 else np.array([self.window])
        gaps = gaps[gaps >= self.min_gap] if self.min_gap > 0 else gaps
        cap = max(self.window / max(self.step, 1e-9), 1.0)
        occ = min(len(times) / cap, 1.0)
        avg_gap = float(gaps.mean()) if len(gaps) else self.window
        max_gap = float(gaps.max()) if len(gaps) else self.window
        scores = {
            "numOccurence": occ,
            "averageInterval": max(1.0 - avg_gap / self.window, 0.0),
            "maxInterval": max(1.0 - max_gap / self.window, 0.0),
        }
        if self.weighted:
            tot_w = sum(self.weighted.values()) or 1.0
            return sum(scores[s] * w for s, w in self.weighted.items()) / tot_w
        conds = []
        for s in self.preferred:
            if s == "numOccurence":
                conds.append(len(times) >= self.min_occurence)
            elif s == "averageInterval":
                conds.append(avg_gap <= self.max_interval_average)
            elif s == "maxInterval":
                conds.append(max_gap <= self.max_interval_max)
        ok = any(conds) if self.any_cond else all(conds)
        return max(scores[s] for s in self.preferred) if ok else 0.0

    def score_events(self, timestamps: np.ndarray, fired: np.ndarray
                     ) -> List[Tuple[float, float]]:
        """Slide the window over (sorted) timestamps; return
        (window_end_time, score) for windows whose score beats the
        threshold — the rows the reference mapper emits."""
        ts = np.asarray(timestamps, np.float64)
        f = np.asarray(fired, bool)
        out = []
        if len(ts) == 0:
            return out
        t = ts.min() + self.window
        t_end = ts.max()
        while t <= t_end + self.step / 2:
            in_win = (ts > t - self.window) & (ts <= t) & f
            score = self._window_score(ts[in_win])
            if score > self.threshold:
                out.append((float(t), float(score)))
            t += self.step
        return out


def positional_cluster(rows: Sequence[Sequence[str]],
                       analyzer: EventLocalityAnalyzer,
                       quant_field_ordinal: int,
                       seq_num_field_ordinal: int,
                       condition=lambda v: True
                       ) -> List[Tuple[float, float]]:
    """SequencePositionalCluster job surface: CSV rows with a timestamp and
    quantity field; emit high-locality window positions."""
    ts = np.array([float(r[seq_num_field_ordinal]) for r in rows])
    vals = np.array([float(r[quant_field_ordinal]) for r in rows])
    order = np.argsort(ts)
    fired = np.array([condition(v) for v in vals[order]])
    return analyzer.score_events(ts[order], fired)
