"""Markov / HMM sequence models (org.avenir.markov + spark/sequence ports).

Reference semantics:
- MarkovStateTransitionModel.java:50 — count (prevState, state) bigrams per
  row-sequence, optional per-class-label matrices; reducer row-normalizes
  into scaled-int matrices; model file = states header line, optional
  "classLabel:<v>" section markers, then matrix rows (:116-133, :184-219).
- MarkovModelClassifier.java:44 — cumulative log odds of a sequence under
  two class matrices, threshold -> class (:127-150).
- HiddenMarkovModelBuilder.java:50 — counts state-transition,
  state-observation and initial-state triples from tagged sequences.
- ViterbiStatePredictor.java:45 + ViterbiDecoder.java:31 — hidden state
  decoding from observations + HMM params.
- ProbabilisticSuffixTreeGenerator.java:51 — sliding-window suffix counts ->
  higher-order conditional probabilities.
- spark/markov/StateTransitionRate.scala:30 / ContTimeStateTransitionStats
  .scala:34 — continuous-time Markov chain rates and dwell statistics.

TPU design: sequences pad to [S, L] int32 (-1 sentinel); bigram/emission
counting is one one-hot einsum over the (prev, next[, class]) codes —
the same contraction pattern as Naive Bayes; Viterbi is a lax.scan over
time vmap'd across the sequence batch.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

_EPS = 1e-12


def encode_sequences(
    seqs: Sequence[Sequence[str]], states: Sequence[str]
) -> Tuple[np.ndarray, np.ndarray]:
    """Pad string sequences to int32 [S, L] with -1 sentinel; returns
    (padded, lengths)."""
    index = {s: i for i, s in enumerate(states)}
    lens = np.array([len(s) for s in seqs], np.int32)
    L = int(lens.max()) if len(seqs) else 0
    out = np.full((len(seqs), L), -1, np.int32)
    for i, s in enumerate(seqs):
        out[i, : len(s)] = [index[tok] for tok in s]
    return out, lens


@partial(jax.jit, static_argnames=("n_states", "n_classes"))
def _bigram_counts(padded, labels, n_states: int, n_classes: int):
    """counts[c, i, j] = #(class c sequences with transition i->j).

    Keyed segment_sum rather than a class one-hot einsum: the class axis
    doubles as the ENTITY axis in the per-entity (multi-tenant) Spark mode
    (MarkovStateTransitionModel.scala:34), where its size scales with the
    data — a [rows, entities] one-hot would be O(rows x entities) memory,
    while the flat (class, prev, next) key keeps it O(rows x seq_len)."""
    prev = padded[:, :-1]
    nxt = padded[:, 1:]
    valid = (prev >= 0) & (nxt >= 0)
    key = (labels[:, None] * n_states + jnp.maximum(prev, 0)) * n_states \
        + jnp.maximum(nxt, 0)
    flat = jax.ops.segment_sum(
        valid.astype(jnp.float32).reshape(-1), key.reshape(-1),
        num_segments=n_classes * n_states * n_states)
    return flat.reshape(n_classes, n_states, n_states)


class MarkovStateTransitionModel:
    """mst.* job equivalent: (per-class) row-normalized transition matrices."""

    def __init__(self, states: Sequence[str], scale: int = 1000,
                 class_labels: Optional[Sequence[str]] = None):
        self.states = list(states)
        self.scale = scale
        self.class_labels = list(class_labels) if class_labels else None
        n, k = len(self.states), (len(class_labels) if class_labels else 1)
        self.counts = np.zeros((k, n, n), np.float64)

    # ----------------------------------------------------------------- fit
    def fit(self, seqs: Sequence[Sequence[str]],
            labels: Optional[Sequence[str]] = None) -> "MarkovStateTransitionModel":
        padded, _ = encode_sequences(seqs, self.states)
        if self.class_labels:
            lab_idx = {v: i for i, v in enumerate(self.class_labels)}
            y = np.array([lab_idx[v] for v in labels], np.int32)
            k = len(self.class_labels)
        else:
            y = np.zeros(len(seqs), np.int32)
            k = 1
        # round the class/entity axis up to a power-of-2 bucket so the
        # jitted kernel's executable is reused while streaming ingest
        # grows the entity set chunk by chunk (fit_entities)
        k_pad = max(1, 1 << (k - 1).bit_length())
        self.counts += np.asarray(
            _bigram_counts(jnp.asarray(padded), jnp.asarray(y),
                           len(self.states), k_pad)
        )[:k]
        return self

    def fit_csr(self, codes: np.ndarray, offsets: np.ndarray,
                skip: int, class_ord: Optional[int] = None,
                label_codes: Optional[np.ndarray] = None,
                y: Optional[np.ndarray] = None
                ) -> "MarkovStateTransitionModel":
        """Fold one CSR-encoded line block (native seq_encode output:
        tokens dictionary-encoded against a vocabulary whose first
        len(states) entries are the states; `label_codes[k]` gives the
        vocab code of class_labels[k] — a label that IS a state shares
        the state's code. Meta tokens are -1). Same semantics as fit() —
        unknown state tokens in the sequence region raise, transitions
        never cross rows — but the whole count is numpy/C speed: the
        sequence jobs' answer to the CSV jobs' native columnar parse."""
        s = len(self.states)
        n = offsets.shape[0] - 1
        if n <= 0:
            return self
        from avenir_tpu.native.ingest import csr_rows

        lens = np.diff(offsets)
        row_of, starts = csr_rows(offsets)
        idx = np.arange(codes.shape[0])
        in_seq = idx >= (starts[row_of] + skip)
        bad = in_seq & ((codes < 0) | (codes >= s))
        if bad.any():
            b = int(np.flatnonzero(bad)[0])
            raise ValueError(
                f"unknown state token at row {int(row_of[b])}, "
                f"position {int(b - starts[row_of[b]])}")
        if y is not None:
            # caller-resolved per-row class/entity indices (the per-entity
            # streaming mode: keys are open-vocabulary strings resolved
            # outside, counts axis already grown to cover max(y))
            k = self.counts.shape[0]
            if (y.shape[0] != n or (y < 0).any()
                    or int(y.max(initial=-1)) >= k):
                raise ValueError("y must give one index in "
                                 "[0, counts.shape[0]) per CSR row")
            y = y.astype(np.int64)
        elif self.class_labels:
            k = len(self.class_labels)
            if class_ord is None:
                raise ValueError("class_ord required with class_labels")
            if label_codes is None:
                # no safe default exists: a label that IS a state shares
                # the state's code, which only the vocab builder knows
                raise ValueError(
                    "label_codes required with class_labels (vocab code "
                    "of each class label, see the mst runner)")
            if (lens <= class_ord).any():
                r = int(np.argmax(lens <= class_ord))
                raise ValueError(f"row {r} has no class field "
                                 f"(ordinal {class_ord})")
            inv = np.full(int(label_codes.max()) + 2, -1, np.int64)
            inv[label_codes] = np.arange(k)
            raw = codes[starts + class_ord].astype(np.int64)
            ok = (raw >= 0) & (raw < inv.shape[0] - 1)
            y = np.where(ok, inv[np.clip(raw, 0, inv.shape[0] - 1)], -1)
            if (y < 0).any():
                r = int(np.argmax(y < 0))
                raise ValueError(f"unknown class label in row {r}")
        else:
            k = 1
            y = np.zeros(n, np.int64)
        prev, nxt = codes[:-1], codes[1:]
        valid = in_seq[:-1] & (row_of[:-1] == row_of[1:])
        key = (y[row_of[:-1]] * s + prev) * s + nxt
        self.counts += np.bincount(
            key[valid], minlength=k * s * s).reshape(k, s, s)
        return self

    def fit_entities(self, seqs: Sequence[Sequence[str]],
                     entity_keys: Sequence[str]) -> "MarkovStateTransitionModel":
        """Per-entity accumulate that grows the label axis in place — the
        streaming mode of the Spark multi-tenant job
        (MarkovStateTransitionModel.scala:51-52): unseen entity keys extend
        class_labels and zero-pad counts, so chunked ingest needs no
        up-front entity scan and preserves first-seen entity order."""
        if self.class_labels is None:
            if self.counts.any():
                raise ValueError(
                    "fit_entities cannot follow unlabeled fit() counts")
            self.class_labels = []
            self.counts = np.zeros((0,) + self.counts.shape[1:], np.float64)
        if not len(seqs):
            return self
        seen = set(self.class_labels)
        new = []
        for key in entity_keys:
            if key not in seen:
                seen.add(key)
                new.append(key)
        if new:
            self.class_labels.extend(new)
            self.counts = np.pad(self.counts,
                                 ((0, len(new)), (0, 0), (0, 0)))
        return self.fit(seqs, entity_keys)

    def merge(self, other: "MarkovStateTransitionModel"
              ) -> "MarkovStateTransitionModel":
        """Fold another partial fit's transition counts into this one —
        the NaiveBayesModel.merge algebra for the (per-class) markov
        counts: bigram counts are additive, so merging shard fits
        equals fitting the concatenated shards, and a streamed fold's
        carry can be checkpointed/merged byte-exactly (integer-valued
        float64 cells). Both sides must agree on states, scale and
        class labels (per-entity fits with divergent entity sets merge
        through fit_entities' growth path instead, outside this op)."""
        if self.states != other.states or self.scale != other.scale \
                or self.class_labels != other.class_labels:
            raise ValueError(
                "cannot merge markov models with different states, "
                "scale or class labels")
        self.counts += other.counts
        return self

    def matrix(self, class_label: Optional[str] = None,
               scaled: bool = True) -> np.ndarray:
        ki = (self.class_labels.index(class_label)
              if class_label and self.class_labels else 0)
        c = self.counts[ki]
        prob = c / np.maximum(c.sum(axis=1, keepdims=True), _EPS)
        return np.rint(prob * self.scale).astype(np.int64) if scaled else prob

    # ------------------------------------------------------------- file IO
    def save(self, path: str, delim: str = ",",
             marker: str = "classLabel", stamp: bool = True) -> None:
        """Reference text format: states line, then (per class) matrix rows,
        class sections marked 'classLabel:<v>'. The per-entity Spark
        variant (spark/sequence/MarkovStateTransitionModel.scala:34, one
        matrix per entity key) writes the same shape with 'entity:<key>'
        section markers — the adaptation of its (Record key, matrix)
        saveAsTextFile pairs to the Hadoop job's single-file format.
        ``stamp`` publishes the format/digest sidecar the serving path
        verifies at load (models/artifact.py)."""
        with open(path, "w") as fh:
            fh.write(delim.join(self.states) + "\n")
            if self.class_labels:
                for cv in self.class_labels:
                    fh.write(f"{marker}:{cv}\n")
                    for row in self.matrix(cv):
                        fh.write(delim.join(str(int(v)) for v in row) + "\n")
            else:
                for row in self.matrix():
                    fh.write(delim.join(str(int(v)) for v in row) + "\n")
        if stamp:
            from avenir_tpu.models.artifact import write_stamp
            write_stamp(path)

    @classmethod
    def load(cls, path: str, delim: str = ",", scale: int = 1000
             ) -> "MarkovStateTransitionModel":
        from avenir_tpu.models.artifact import verify_stamp
        verify_stamp(path)
        with open(path) as fh:
            lines = [ln.rstrip("\n") for ln in fh if ln.strip()]
        states = lines[0].split(delim)
        n = len(states)
        sections: Dict[Optional[str], List[List[float]]] = {}
        cur: Optional[str] = None
        for ln in lines[1:]:
            if ln.startswith("classLabel:") or ln.startswith("entity:"):
                cur = ln.split(":", 1)[1]
                sections[cur] = []
            else:
                sections.setdefault(cur, []).append(
                    [float(v) for v in ln.split(delim)]
                )
        class_labels = [c for c in sections if c is not None] or None
        model = cls(states, scale=scale, class_labels=class_labels)
        for ki, key in enumerate(class_labels or [None]):
            model.counts[ki] = np.asarray(sections[key])  # scaled probs as counts
        return model


class MarkovModelClassifier:
    """mmc.* job: two-class sequence classification by cumulative log odds
    (MarkovModelClassifier.java:127-150)."""

    def __init__(self, model: MarkovStateTransitionModel,
                 pos_class: str, neg_class: str, threshold: float = 0.0):
        assert model.class_labels, "classifier needs a class-based model"
        self.model = model
        self.pos_class = pos_class
        self.neg_class = neg_class
        self.threshold = threshold
        p_pos = model.matrix(pos_class, scaled=False)
        p_neg = model.matrix(neg_class, scaled=False)
        self.log_odds = jnp.asarray(
            np.log(np.maximum(p_pos, _EPS)) - np.log(np.maximum(p_neg, _EPS)),
            jnp.float32,
        )

    def predict(self, seqs: Sequence[Sequence[str]]) -> Tuple[np.ndarray, np.ndarray]:
        """Returns (class strings, log-odds scores).

        The per-row score accumulates transition log odds STRICTLY in
        sequence order (column-wise f32 host reduction). A tree-shaped
        ``sum`` over the padded axis regroups the addends whenever the
        batch's pad width changes, so the same sequence could score
        differently alone vs batched — the online scoring path
        (server/score.py) coalesces arbitrary request mixes into one
        vectorized call and demultiplexes, which is only sound because
        this reduction is invariant to batch composition and padding."""
        padded, _ = encode_sequences(seqs, self.model.states)
        prev, nxt = padded[:, :-1], padded[:, 1:]
        valid = (prev >= 0) & (nxt >= 0)
        lo_np = np.asarray(self.log_odds)
        lo = lo_np[np.maximum(prev, 0), np.maximum(nxt, 0)]
        score = np.zeros(len(seqs), np.float32)
        for t in range(lo.shape[1]):
            score = np.where(valid[:, t], score + lo[:, t], score)
        pred = np.where(score > self.threshold, self.pos_class, self.neg_class)
        return pred, score


# ---------------------------------------------------------------------------
# hidden Markov model
# ---------------------------------------------------------------------------


@dataclass
class HiddenMarkovModel:
    """HMM parameter container (HiddenMarkovModel.java:31)."""

    states: List[str]
    observations: List[str]
    initial: np.ndarray          # [S]
    transition: np.ndarray       # [S, S]
    emission: np.ndarray         # [S, O]

    def save(self, path: str, delim: str = ",") -> None:
        with open(path, "w") as fh:
            fh.write(delim.join(self.states) + "\n")
            fh.write(delim.join(self.observations) + "\n")
            fh.write(delim.join(f"{v:.6f}" for v in self.initial) + "\n")
            for row in self.transition:
                fh.write(delim.join(f"{v:.6f}" for v in row) + "\n")
            for row in self.emission:
                fh.write(delim.join(f"{v:.6f}" for v in row) + "\n")

    @classmethod
    def load(cls, path: str, delim: str = ",") -> "HiddenMarkovModel":
        with open(path) as fh:
            lines = [ln.rstrip("\n") for ln in fh if ln.strip()]
        states = lines[0].split(delim)
        obs = lines[1].split(delim)
        s, o = len(states), len(obs)
        initial = np.array([float(v) for v in lines[2].split(delim)])
        trans = np.array([[float(v) for v in lines[3 + i].split(delim)]
                          for i in range(s)])
        emis = np.array([[float(v) for v in lines[3 + s + i].split(delim)]
                         for i in range(s)])
        return cls(states, obs, initial, trans, emis)


class HiddenMarkovModelBuilder:
    """hmmb.* job: count (state->state), (state->obs) and initial-state
    occurrences from tagged sequences (HiddenMarkovModelBuilder.java:136-153)."""

    def __init__(self, states: Sequence[str], observations: Sequence[str],
                 laplace: float = 1.0):
        self.states = list(states)
        self.observations = list(observations)
        self.laplace = laplace
        s, o = len(self.states), len(self.observations)
        self.trans_counts = np.zeros((s, s))
        self.emis_counts = np.zeros((s, o))
        self.init_counts = np.zeros(s)

    def add(self, state_seq: Sequence[str], obs_seq: Sequence[str]) -> None:
        sidx = {v: i for i, v in enumerate(self.states)}
        oidx = {v: i for i, v in enumerate(self.observations)}
        ss = [sidx[v] for v in state_seq]
        oo = [oidx[v] for v in obs_seq]
        if ss:
            self.init_counts[ss[0]] += 1
        for a, b in zip(ss[:-1], ss[1:]):
            self.trans_counts[a, b] += 1
        for s, o in zip(ss, oo):
            self.emis_counts[s, o] += 1

    def add_csr(self, codes: np.ndarray, offsets: np.ndarray,
                skip: int) -> None:
        """Fold a CSR block of `obs<sub>state` pair tokens encoded with
        pair_code = state_index * n_obs + obs_index (native seq_encode
        against the state-major pair vocabulary — see the hmmb runner).
        Count-identical to calling add() per row; pure numpy bincount."""
        s, o = len(self.states), len(self.observations)
        n = offsets.shape[0] - 1
        if n <= 0:
            return
        from avenir_tpu.native.ingest import csr_rows

        row_of, starts = csr_rows(offsets)
        idx = np.arange(codes.shape[0])
        in_seq = idx >= (starts[row_of] + skip)
        bad = in_seq & ((codes < 0) | (codes >= s * o))
        if bad.any():
            b = int(np.flatnonzero(bad)[0])
            raise ValueError(
                f"unknown obs:state token at row {int(row_of[b])}, "
                f"position {int(b - starts[row_of[b]])}")
        st = np.where(in_seq, codes // o, 0)
        ob = np.where(in_seq, codes % o, 0)
        firsts = starts + skip
        firsts = firsts[firsts < offsets[1:]]
        self.init_counts += np.bincount(st[firsts], minlength=s)
        valid = in_seq[:-1] & (row_of[:-1] == row_of[1:])
        self.trans_counts += np.bincount(
            (st[:-1] * s + st[1:])[valid], minlength=s * s).reshape(s, s)
        self.emis_counts += np.bincount(
            (st * o + ob)[in_seq], minlength=s * o).reshape(s, o)

    def add_partially_tagged(self, tokens: Sequence[str],
                             window_function: Sequence[int]) -> None:
        """Window-function count spreading for partially-tagged sequences
        (HiddenMarkovModelBuilder.processPartiallyTagged, :174-259): tokens
        matching a model state tag the sequence sparsely; every untagged
        token within the window around a state position contributes a
        state->obs count weighted by windowFunction[distance-1] (the last
        weight repeats beyond the function's length). Window bounds reach
        halfway to the neighboring state; at the ends the opposite side's
        window is mirrored (clamped to the sequence), and a lone state
        reaches halfway to both sequence boundaries. Initial-state and
        state->state counts come from the tagged positions alone.

        Deviation from the reference, documented: the Java window-bound
        expressions (:197, :205) read `a - b / 2` — operator precedence
        makes them `a - (b/2)`, which walks the window past the neighboring
        state (and past the sequence end, an array-bounds crash for long
        gaps). This implements the evident intent, half the gap:
        `(a - b) / 2`."""
        sidx = {v: i for i, v in enumerate(self.states)}
        oidx = {v: i for i, v in enumerate(self.observations)}
        wf = list(window_function) or [1]
        pos = [i for i, t in enumerate(tokens) if t in sidx]
        if not pos:
            return
        self.init_counts[sidx[tokens[pos[0]]]] += 1
        for a, b in zip(pos[:-1], pos[1:]):
            self.trans_counts[sidx[tokens[a]], sidx[tokens[b]]] += 1
        n = len(tokens)
        for i, p in enumerate(pos):
            left_w = (p - pos[i - 1]) // 2 if i > 0 else None
            right_w = (pos[i + 1] - p) // 2 if i < len(pos) - 1 else None
            if left_w is None and right_w is None:        # only one state
                lb = p // 2
                rb = p + (n - 1 - p) // 2
            elif left_w is None:                          # first state
                lb = max(p - right_w, 0)
                rb = p + right_w
            elif right_w is None:                         # last state
                lb = p - left_w
                rb = min(p + left_w, n - 1)
            else:
                lb, rb = p - left_w, p + right_w
            s = sidx[tokens[p]]
            for k, j in enumerate(range(p - 1, lb - 1, -1)):
                w = wf[k] if k < len(wf) else wf[-1]
                self.emis_counts[s, oidx[tokens[j]]] += w
            for k, j in enumerate(range(p + 1, rb + 1)):
                w = wf[k] if k < len(wf) else wf[-1]
                self.emis_counts[s, oidx[tokens[j]]] += w

    def finish(self) -> HiddenMarkovModel:
        lp = self.laplace
        t = self.trans_counts + lp
        e = self.emis_counts + lp
        i = self.init_counts + lp
        return HiddenMarkovModel(
            self.states, self.observations,
            i / i.sum(),
            t / t.sum(axis=1, keepdims=True),
            e / e.sum(axis=1, keepdims=True),
        )

    def fit(self, state_seqs, obs_seqs) -> HiddenMarkovModel:
        for ss, oo in zip(state_seqs, obs_seqs):
            self.add(ss, oo)
        return self.finish()

    def fit_partially_tagged(self, token_seqs,
                             window_function: Sequence[int]
                             ) -> HiddenMarkovModel:
        for tokens in token_seqs:
            self.add_partially_tagged(tokens, window_function)
        return self.finish()


@partial(jax.jit, static_argnames=())
def _viterbi_kernel(obs, length, log_init, log_trans, log_emis):
    """Single padded observation sequence [L] -> best state path [L]."""
    L = obs.shape[0]

    def step(carry, t):
        delta = carry                                   # [S]
        o = obs[t]
        cand = delta[:, None] + log_trans               # [S, S]
        best_prev = jnp.argmax(cand, axis=0)            # [S]
        new_delta = jnp.max(cand, axis=0) + log_emis[:, jnp.maximum(o, 0)]
        new_delta = jnp.where(t < length, new_delta, delta)
        best_prev = jnp.where(t < length, best_prev, jnp.arange(delta.shape[0]))
        return new_delta, best_prev

    delta0 = log_init + log_emis[:, jnp.maximum(obs[0], 0)]
    delta, back = lax.scan(step, delta0, jnp.arange(1, L))

    last = jnp.argmax(delta)

    def backstep(carry, t):
        nxt = carry
        prev = back[t][nxt]
        prev = jnp.where(t + 1 < length, prev, nxt)
        return prev, prev

    _, path_rev = lax.scan(backstep, last, jnp.arange(L - 2, -1, -1))
    path = jnp.concatenate([path_rev[::-1], jnp.array([last])])
    return path


class ViterbiDecoder:
    """vsp.* job: hidden state decoding (ViterbiStatePredictor.java:45)."""

    def __init__(self, hmm: HiddenMarkovModel):
        self.hmm = hmm
        self.log_init = jnp.asarray(np.log(np.maximum(hmm.initial, _EPS)), jnp.float32)
        self.log_trans = jnp.asarray(np.log(np.maximum(hmm.transition, _EPS)), jnp.float32)
        self.log_emis = jnp.asarray(np.log(np.maximum(hmm.emission, _EPS)), jnp.float32)

    def decode(self, obs_seqs: Sequence[Sequence[str]]) -> List[List[str]]:
        padded, lens = encode_sequences(obs_seqs, self.hmm.observations)
        paths = jax.vmap(
            lambda o, l: _viterbi_kernel(o, l, self.log_init, self.log_trans,
                                         self.log_emis)
        )(jnp.asarray(padded), jnp.asarray(lens))
        paths = np.asarray(paths)
        return [
            [self.hmm.states[s] for s in paths[i, : lens[i]]]
            for i in range(len(obs_seqs))
        ]


# ---------------------------------------------------------------------------
# probabilistic suffix tree
# ---------------------------------------------------------------------------


class ProbabilisticSuffixTree:
    """pstg.* job: sliding-window suffix counts -> conditional next-symbol
    probabilities up to max_depth history
    (ProbabilisticSuffixTreeGenerator.java:88-123)."""

    def __init__(self, symbols: Sequence[str], max_depth: int = 3):
        self.symbols = list(symbols)
        self.max_depth = max_depth
        self.counts: Dict[Tuple[str, ...], np.ndarray] = {}

    def fit(self, seqs: Sequence[Sequence[str]]) -> "ProbabilisticSuffixTree":
        nsym = len(self.symbols)
        idx = {s: i for i, s in enumerate(self.symbols)}
        for seq in seqs:
            enc = [idx[t] for t in seq]
            for t in range(len(enc)):
                for d in range(0, self.max_depth + 1):
                    if t - d < 0:
                        break
                    ctx = tuple(seq[t - d: t])
                    if ctx not in self.counts:
                        self.counts[ctx] = np.zeros(nsym, np.float64)
                    self.counts[ctx][enc[t]] += 1
        return self

    def cond_prob(self, context: Sequence[str], symbol: str) -> float:
        """P(symbol | longest tracked suffix of context)."""
        ctx = tuple(context[-self.max_depth:])
        while ctx not in self.counts and ctx:
            ctx = ctx[1:]
        c = self.counts.get(ctx)
        if c is None or c.sum() == 0:
            return 1.0 / len(self.symbols)
        return float(c[self.symbols.index(symbol)] / c.sum())

    def sequence_log_prob(self, seq: Sequence[str]) -> float:
        lp = 0.0
        for t, sym in enumerate(seq):
            lp += math.log(max(self.cond_prob(seq[:t], sym), _EPS))
        return lp


# ---------------------------------------------------------------------------
# continuous-time Markov chain (spark/markov ports)
# ---------------------------------------------------------------------------


class StateTransitionRate:
    """CTMC transition rates from timestamped state visits
    (spark/markov/StateTransitionRate.scala:30): rate(i->j) =
    count(i->j) / total dwell time in i."""

    def __init__(self, states: Sequence[str]):
        self.states = list(states)
        n = len(self.states)
        self.trans_counts = np.zeros((n, n))
        self.dwell_time = np.zeros(n)

    def fit(self, seqs: Sequence[Sequence[Tuple[str, float]]]
            ) -> "StateTransitionRate":
        """seqs: per entity, list of (state, timestamp) in time order."""
        idx = {s: i for i, s in enumerate(self.states)}
        for seq in seqs:
            for (s0, t0), (s1, t1) in zip(seq[:-1], seq[1:]):
                i, j = idx[s0], idx[s1]
                self.dwell_time[i] += max(t1 - t0, 0.0)
                if i != j:
                    self.trans_counts[i, j] += 1
        return self

    def rates(self) -> np.ndarray:
        return self.trans_counts / np.maximum(self.dwell_time[:, None], _EPS)

    def dwell_stats(self) -> Dict[str, Tuple[float, float]]:
        """Mean dwell time + exit rate per state
        (ContTimeStateTransitionStats.scala:34)."""
        exits = self.trans_counts.sum(axis=1)
        mean_dwell = self.dwell_time / np.maximum(exits, 1.0)
        return {
            s: (float(mean_dwell[i]), float(exits[i] / max(self.dwell_time[i], _EPS)))
            for i, s in enumerate(self.states)
        }


class ContTimeStateTransitionStats:
    """CTMC statistics by uniformization
    (spark/markov/ContTimeStateTransitionStats.scala:34).

    Given a rate matrix Q (off-diagonal transition rates, diagonal
    -sum(row)), uniformize with maxRate = -min diag: P = I + Q/maxRate,
    count = maxRate * horizon, Poisson(count)-weighted sums over matrix
    powers truncated at 4 + 6*sqrt(count) + count (the reference's limit).

    TPU design: the power table P^0..P^limit is one `lax.scan` of matmuls
    (MXU work); the reference's nested double sums over powers collapse to
    convolutions of the [limit+1] probability vectors a_j = P^j[init,target]
    and b_j = P^j[target,end].
    """

    def __init__(self, rates: np.ndarray, states: Sequence[str],
                 time_horizon: float):
        self.states = list(states)
        self.horizon = float(time_horizon)
        n = len(self.states)
        q = np.asarray(rates, np.float64).copy()
        np.fill_diagonal(q, 0.0)
        np.fill_diagonal(q, -q.sum(axis=1))
        self.max_rate = float(-q.diagonal().min())
        if self.max_rate <= 0:
            raise ValueError("rate matrix has no transitions")
        p = np.eye(n) + q / self.max_rate
        self.count = self.max_rate * self.horizon
        self.limit = int(4 + 6 * math.sqrt(self.count) + self.count)

        # power table on host in float64: limit grows ~linearly with
        # maxRate*horizon, and f32 matmul error compounds over long power
        # chains; S is small, so host numpy is cheap and exact enough
        self.powers = np.empty((self.limit + 1, n, n), np.float64)
        acc = np.eye(n)
        for i in range(self.limit + 1):
            self.powers[i] = acc
            acc = acc @ p
        # Poisson(count) pmf over 0..limit, built in log space for stability
        i = np.arange(self.limit + 1, dtype=np.float64)
        logpmf = -self.count + i * math.log(max(self.count, _EPS)) - (
            np.cumsum(np.concatenate([[0.0], np.log(np.maximum(i[1:], 1.0))])))
        self.pois = np.exp(logpmf)

    def _sindex(self, state: str) -> int:
        return self.states.index(state)

    def _ab(self, init: str, target: str, end: Optional[str]
            ) -> Tuple[np.ndarray, np.ndarray]:
        a = self.powers[:, self._sindex(init), self._sindex(target)]
        b = (self.powers[:, self._sindex(target), self._sindex(end)]
             if end is not None else np.ones(self.limit + 1))
        return a, b

    def _end_prob(self, init_state: str, end_state: str) -> float:
        """P(X_T = end | X_0 = init): the conditioning normalizer."""
        path = self.powers[:, self._sindex(init_state), self._sindex(end_state)]
        return float(np.maximum(np.sum(path * self.pois), _EPS))

    def dwell_time(self, init_state: str, target_state: str,
                   end_state: Optional[str] = None) -> float:
        """Expected time spent in target_state over the horizon, starting
        from init_state; with end_state, the expectation conditioned on
        ending there — the "stateDwellTime" statistic (:161-192).

        Deviation from the reference: it returns the unnormalized joint
        E[dwell * 1{X_T=end}]; dividing by P(X_T=end | init) yields the
        conditional expectation this method documents."""
        a, b = self._ab(init_state, target_state, end_state)
        inner = np.convolve(a, b)[: self.limit + 1]     # sum_{j<=i} a_j b_{i-j}
        i = np.arange(self.limit + 1, dtype=np.float64)
        raw = float(np.sum(self.horizon / (i + 1.0) * inner * self.pois))
        if end_state is not None:
            raw /= self._end_prob(init_state, end_state)
        return raw

    def transition_count(self, init_state: str, from_state: str,
                         to_state: str, end_state: Optional[str] = None
                         ) -> float:
        """Expected number of from->to transitions over the horizon — the
        "StateTransitionCount" statistic (:194-215).

        Deviation from the reference: its inner loop runs j in 0..i
        inclusive (N+1 terms for N uniformized events), overcounting by
        E[P^N[init,from]]; the correct uniformization identity
        E[#trans] = rate(from,to) * E[dwell(from)] needs j in 0..N-1,
        which is what this sums (verified against the analytic two-state
        solution in tests)."""
        a = self.powers[:, self._sindex(init_state), self._sindex(from_state)]
        b = (self.powers[:, self._sindex(to_state), self._sindex(end_state)]
             if end_state is not None else np.ones(self.limit + 1))
        step_pr = self.powers[1, self._sindex(from_state), self._sindex(to_state)]
        conv = np.convolve(a, b)
        # inner[i] = sum_{j<=i-1} a_j b_{i-1-j}: one uniformized step spent
        # on the from->to jump itself
        inner = np.concatenate([[0.0], conv[: self.limit]]) * step_pr
        raw = float(np.sum(inner * self.pois))
        if end_state is not None:
            # conditional, not joint — same deviation note as dwell_time
            raw /= self._end_prob(init_state, end_state)
        return raw


def generate_markov_sequences(
    trans: np.ndarray,
    init: np.ndarray,
    states: Sequence[str],
    n_seqs: int,
    length: int,
    seed: int = 0,
) -> List[List[str]]:
    """Synthetic sequence generation (spark/sequence/SequenceGenerator.scala:31)."""
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n_seqs):
        s = rng.choice(len(states), p=init)
        seq = [states[s]]
        for _ in range(length - 1):
            s = rng.choice(len(states), p=trans[s])
            seq.append(states[s])
        out.append(seq)
    return out


def event_time_distribution(
    seqs: Sequence[Sequence[float]], num_buckets: int = 24,
    bucket_width: float = 3600.0,
) -> np.ndarray:
    """Inter-arrival time histogram
    (spark/sequence/EventTimeDistribution.scala:27)."""
    gaps = []
    for seq in seqs:
        ts = np.asarray(seq)
        gaps.append(np.diff(ts))
    if not gaps:
        return np.zeros(num_buckets)
    all_gaps = np.concatenate(gaps)
    bucket = np.clip((all_gaps // bucket_width).astype(int), 0, num_buckets - 1)
    return np.bincount(bucket, minlength=num_buckets)
