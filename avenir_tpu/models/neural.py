"""Basic one-hidden-layer neural network classifier.

Reference (python/supv/basic_nn.py, SURVEY §2.10): a numpy two-layer net —
tanh hidden layer, softmax output, cross-entropy loss with L2 decay —
trained by full-batch ("batch") or per-sample ("stochastic") gradient
descent on scikit-learn moons data, with a held-out validation slice.

TPU-first design: parameters live in a pytree; one jitted `lax.scan` runs
the entire epoch loop on device (grads via `jax.grad` rather than
hand-derived backprop). Batch mode scans full-batch steps; minibatch mode
scans over reshaped [steps, B, D] batches. The moons generator is
re-implemented in numpy (no sklearn dependency).
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Dict, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

Params = Dict[str, jnp.ndarray]


def make_moons(n: int, noise: float = 0.2, seed: int = 0
               ) -> Tuple[np.ndarray, np.ndarray]:
    """Two interleaving half circles (sklearn.datasets.make_moons analog,
    basic_nn.py:46)."""
    rng = np.random.default_rng(seed)
    n_out = n // 2
    n_in = n - n_out
    t_out = np.pi * rng.random(n_out)
    t_in = np.pi * rng.random(n_in)
    x = np.concatenate([
        np.stack([np.cos(t_out), np.sin(t_out)], axis=1),
        np.stack([1.0 - np.cos(t_in), 0.5 - np.sin(t_in)], axis=1),
    ])
    y = np.concatenate([np.zeros(n_out, np.int64), np.ones(n_in, np.int64)])
    x += rng.normal(0.0, noise, x.shape)
    perm = rng.permutation(n)
    return x[perm].astype(np.float32), y[perm]


def _init_params(key, n_in: int, n_hidden: int, n_out: int) -> Params:
    k1, k2 = jax.random.split(key)
    return {
        "w1": jax.random.normal(k1, (n_in, n_hidden)) / jnp.sqrt(n_in),
        "b1": jnp.zeros((n_hidden,)),
        "w2": jax.random.normal(k2, (n_hidden, n_out)) / jnp.sqrt(n_hidden),
        "b2": jnp.zeros((n_out,)),
    }


def _logits(params: Params, x: jnp.ndarray) -> jnp.ndarray:
    h = jnp.tanh(x @ params["w1"] + params["b1"])
    return h @ params["w2"] + params["b2"]


def _loss(params: Params, x, y, reg: float) -> jnp.ndarray:
    logp = jax.nn.log_softmax(_logits(params, x))
    nll = -jnp.mean(jnp.take_along_axis(logp, y[:, None], axis=1))
    l2 = sum(jnp.sum(w * w) for k, w in params.items() if k.startswith("w"))
    return nll + reg * l2


@partial(jax.jit, static_argnames=("epochs", "reg"))
def _train_batch(params: Params, x, y, lr, epochs: int, reg: float):
    grad = jax.grad(_loss)

    def step(p, _):
        g = grad(p, x, y, reg)
        return jax.tree_util.tree_map(lambda w, gw: w - lr * gw, p, g), None

    params, _ = jax.lax.scan(step, params, None, length=epochs)
    return params


@partial(jax.jit, static_argnames=("reg",))
def _train_minibatch(params: Params, xb, yb, lr, reg: float):
    """xb: [steps, B, D], yb: [steps, B] — scan over the step axis."""
    grad = jax.grad(_loss)

    def step(p, batch):
        x, y = batch
        g = grad(p, x, y, reg)
        return jax.tree_util.tree_map(lambda w, gw: w - lr * gw, p, g), None

    params, _ = jax.lax.scan(step, params, (xb, yb))
    return params


@dataclass
class BasicNeuralNetwork:
    """1-hidden-layer tanh classifier (basic_nn.py surface: hidden size,
    iteration count, learning rate epsilon, training mode batch/stochastic)."""

    n_hidden: int = 8
    n_classes: int = 2
    learning_rate: float = 0.01
    iterations: int = 1000
    reg: float = 0.0001
    training_mode: str = "batch"        # batch / stochastic / minibatch
    batch_size: int = 32
    seed: int = 0

    params: Optional[Params] = None

    def fit(self, x: np.ndarray, y: np.ndarray) -> "BasicNeuralNetwork":
        x = jnp.asarray(x, jnp.float32)
        y = jnp.asarray(y, jnp.int32)
        params = _init_params(jax.random.key(self.seed), x.shape[1],
                              self.n_hidden, self.n_classes)
        if self.training_mode == "batch":
            params = _train_batch(params, x, y, self.learning_rate,
                                  self.iterations, self.reg)
        else:
            n = x.shape[0]
            bs = 1 if self.training_mode == "stochastic" else min(
                self.batch_size, n)
            rng = np.random.default_rng(self.seed)
            # exactly `iterations` gradient steps, one sampled batch each
            order = rng.integers(0, n, (self.iterations, bs))
            xb = x[order.reshape(-1)].reshape(self.iterations, bs, x.shape[1])
            yb = y[order.reshape(-1)].reshape(self.iterations, bs)
            params = _train_minibatch(params, xb, yb, self.learning_rate,
                                      self.reg)
        self.params = params
        return self

    def predict_proba(self, x: np.ndarray) -> np.ndarray:
        if self.params is None:
            raise RuntimeError("model not fitted")
        return np.asarray(jax.nn.softmax(
            _logits(self.params, jnp.asarray(x, jnp.float32))))

    def predict(self, x: np.ndarray) -> np.ndarray:
        return np.asarray(self.predict_proba(x).argmax(axis=1))

    def score(self, x: np.ndarray, y: np.ndarray) -> float:
        return float(np.mean(self.predict(x) == np.asarray(y)))
