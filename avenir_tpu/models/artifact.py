"""Format stamps for served model artifacts (PR 19's manifest contract
extended to models).

A trained artifact (NB distribution file, fisher boundary table, markov
transition matrix, bandit group stats) is a delimited text file whose
bytes the batch jobs own. Serving those artifacts from a long-lived
process adds a failure mode batch never had: a *newer writer* with a
*newer layout* can replace the file under a warm server, and the server
would happily parse tomorrow's format with today's parser. Cache
manifests solved this with an embedded ``format_version``; model
artifacts cannot embed one without breaking every existing reader
(``MarkovStateTransitionModel.load`` treats line 0 as the states line),
so the stamp rides in an atomic *sidecar*: ``<artifact>.stamp.json``
holding the format version and a content digest.

Contract (mirrors the cache-manifest rules):

- **unstamped loads** — a pre-existing artifact with no sidecar is a
  legacy artifact; loaders accept it unverified (the batch jobs' own
  trust model).
- **stamped-and-current loads verified** — the digest is recomputed at
  load; a mismatch means the artifact changed under its stamp (torn
  replace, partial copy) and the load REFUSES.
- **stamped-but-foreign refuses** — a ``format_version`` this build
  does not speak raises :class:`ModelFormatSkew`; the caller goes cold
  (retrain / re-fetch), never parses blind.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Optional

from avenir_tpu.core.atomic import publish_json

#: layout generation of the delimited model artifacts this build writes
MODEL_FORMAT_VERSION = 1

_STAMP_SUFFIX = ".stamp.json"


class ModelFormatSkew(RuntimeError):
    """A model artifact's stamp names a format this build does not
    speak (or its digest no longer matches the bytes): refuse the load
    and go cold rather than parse a foreign layout."""


def stamp_path(path: str) -> str:
    return path + _STAMP_SUFFIX


def file_digest(path: str) -> str:
    """Content digest of one artifact file (sha1, hex)."""
    h = hashlib.sha1()
    with open(path, "rb") as fh:
        for chunk in iter(lambda: fh.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def write_stamp(path: str) -> str:
    """Publish the sidecar stamp for an artifact that was just written.
    Atomic (tmp + rename), so a reader never sees a torn stamp."""
    return publish_json({"format_version": MODEL_FORMAT_VERSION,
                         "digest": file_digest(path)}, stamp_path(path))


def read_stamp(path: str) -> Optional[dict]:
    """The artifact's stamp document, or None when unstamped (legacy).
    An unreadable/unparseable stamp is skew, not absence — a present
    sidecar that cannot be trusted must not be shrugged off."""
    try:
        with open(stamp_path(path)) as fh:
            return json.load(fh)
    except FileNotFoundError:
        return None
    except (OSError, ValueError) as exc:
        raise ModelFormatSkew(
            f"unreadable stamp beside {path}: {exc}") from exc


def stamp_version(path: str) -> int:
    """The stamped format version, 0 for unstamped legacy artifacts —
    a cache-key dimension (a restamp to a foreign version must miss)."""
    stamp = read_stamp(path)
    return int(stamp.get("format_version", 0)) if stamp else 0


def verify_stamp(path: str) -> Optional[dict]:
    """Digest-verified load gate. Returns the stamp (None when
    unstamped); raises :class:`ModelFormatSkew` when the stamp is
    present but names a foreign format or no longer matches the
    artifact bytes."""
    stamp = read_stamp(path)
    if stamp is None:
        return None
    version = stamp.get("format_version")
    if version != MODEL_FORMAT_VERSION:
        raise ModelFormatSkew(
            f"{path}: stamped format_version={version!r}, this build "
            f"speaks {MODEL_FORMAT_VERSION} — refusing to parse a "
            f"foreign layout (retrain or upgrade)")
    digest = file_digest(path)
    if stamp.get("digest") != digest:
        raise ModelFormatSkew(
            f"{path}: artifact digest {digest[:12]} does not match its "
            f"stamp {str(stamp.get('digest'))[:12]} — artifact changed "
            f"under its stamp")
    return stamp


def rm_stamp(path: str) -> None:
    """Drop the sidecar (used when an artifact is removed)."""
    try:
        os.remove(stamp_path(path))
    except FileNotFoundError:
        pass
