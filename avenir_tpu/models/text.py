"""Text utilities: tokenizer + word counting.

Reference (SURVEY §2.8 text/): WordCounter.java:54 — an MR job that splits a
CSV field (or the whole line) with a Lucene StandardAnalyzer and counts
tokens. The same tokenizer backs the Naive Bayes free-text mode
(BayesianDistribution.java:186-195).

The StandardAnalyzer's observable behavior — lowercase, split on
non-alphanumerics, keep digits, drop English stop words — is reproduced
with a host regex tokenizer (tokenizing is irreducibly host/string work;
the counting after dictionary-encoding is a bincount)."""

from __future__ import annotations

import re
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

# Lucene StandardAnalyzer's default English stop set
STOP_WORDS = frozenset(
    "a an and are as at be but by for if in into is it no not of on or such "
    "that the their then there these they this to was will with".split())

_TOKEN_RE = re.compile(r"[a-z0-9]+(?:'[a-z0-9]+)?")


def tokenize(text: str, drop_stop_words: bool = True) -> List[str]:
    """StandardAnalyzer-like tokens: lowercased alphanumeric runs,
    stop words removed."""
    toks = _TOKEN_RE.findall(text.lower())
    if drop_stop_words:
        return [t for t in toks if t not in STOP_WORDS]
    return toks


class WordCounter:
    """Word-count job (WordCounter.java:54): count tokens of one CSV field
    (text_field_ordinal >= 0) or of whole lines (< 0); output rows of
    (token, count)."""

    def __init__(self, text_field_ordinal: int = -1, delim: str = ",",
                 drop_stop_words: bool = True):
        self.ordinal = text_field_ordinal
        self.delim = delim
        self.drop_stop = drop_stop_words

    def count(self, lines: Iterable[str]) -> List[Tuple[str, int]]:
        vocab: Dict[str, int] = {}
        codes: List[int] = []
        for line in lines:
            line = line.rstrip("\n")
            if not line.strip():
                continue
            text = (line.split(self.delim)[self.ordinal]
                    if self.ordinal >= 0 else line)
            for tok in tokenize(text, self.drop_stop):
                codes.append(vocab.setdefault(tok, len(vocab)))
        if not codes:
            return []
        counts = np.bincount(np.asarray(codes, np.int64), minlength=len(vocab))
        inv = list(vocab)
        return sorted(((inv[i], int(c)) for i, c in enumerate(counts)),
                      key=lambda kv: (-kv[1], kv[0]))


class TextNaiveBayes:
    """Free-text Naive Bayes — the reference's text-input mode of
    BayesianDistribution (mapText, BayesianDistribution.java:186-195:
    rows are `text,classVal`; each Lucene token contributes a
    (classVal, token) count) with the matching multinomial predictor.

    TPU design: tokens dictionary-encode on host (string work); training
    counts fold per streamed chunk with a host bincount over class*V+token
    keys (the vocabulary grows chunk to chunk, so table shapes are not
    jit-stable — and the count is memory-bound string work, not FLOPs);
    scoring is one bag-of-words [n, V] x log P[V, K] matmul on the MXU."""

    def __init__(self, laplace: float = 1.0, drop_stop_words: bool = True):
        self.laplace = laplace
        self.drop_stop = drop_stop_words
        self.vocab: Dict[str, int] = {}
        self.class_values: List[str] = []
        self.log_prob: Optional[np.ndarray] = None      # [V, K]
        self.log_prior: Optional[np.ndarray] = None     # [K]
        # streaming accumulator state (first-seen class order; finish()
        # sorts classes so chunked == whole-fit output exactly)
        self._classes: List[str] = []
        self._cidx: Dict[str, int] = {}
        self._counts = np.zeros((0, 0), np.float64)     # [V, K]
        self._class_counts = np.zeros(0, np.float64)    # [K]

    def _encode(self, texts: Sequence[str], grow: bool):
        doc_ids, tok_ids = [], []
        for d, text in enumerate(texts):
            for tok in tokenize(text, self.drop_stop):
                if tok not in self.vocab:
                    if not grow:
                        continue            # unseen test token: skip
                    self.vocab[tok] = len(self.vocab)
                doc_ids.append(d)
                tok_ids.append(self.vocab[tok])
        return (np.asarray(doc_ids, np.int32), np.asarray(tok_ids, np.int32))

    def accumulate(self, texts: Sequence[str], labels: Sequence[str]
                   ) -> "TextNaiveBayes":
        """Fold one chunk of (classVal, token) counts — additive, so the
        free-text mode streams like the tabular one; vocabulary and class
        set grow across chunks (count tables zero-pad)."""
        for lab in labels:
            if lab not in self._cidx:
                self._cidx[lab] = len(self._classes)
                self._classes.append(lab)
        y = np.asarray([self._cidx[v] for v in labels], np.int32)
        doc_ids, tok_ids = self._encode(texts, grow=True)
        v, k = len(self.vocab), len(self._classes)
        if self._counts.shape != (v, k):
            grown = np.zeros((v, k), np.float64)
            grown[: self._counts.shape[0], : self._counts.shape[1]] = \
                self._counts
            self._counts = grown
            self._class_counts = np.pad(
                self._class_counts, (0, k - self._class_counts.shape[0]))
        if len(tok_ids):
            self._counts += np.bincount(
                np.asarray(tok_ids, np.int64) * k + y[doc_ids],
                minlength=v * k).reshape(v, k)
        self._class_counts += np.bincount(y, minlength=k)
        return self

    def finish(self) -> "TextNaiveBayes":
        """Derive the model; classes sort so chunked == whole-fit."""
        order = np.argsort(self._classes)
        self.class_values = [self._classes[i] for i in order]
        counts = self._counts[:, order]
        class_counts = self._class_counts[order]
        smoothed = counts + self.laplace
        self.log_prob = np.log(smoothed / smoothed.sum(axis=0, keepdims=True))
        self.log_prior = np.log(np.maximum(
            class_counts / max(class_counts.sum(), 1.0), 1e-30))
        return self

    def fit(self, texts: Sequence[str], labels: Sequence[str]) -> "TextNaiveBayes":
        # refit from scratch (fit has always been idempotent); streaming
        # callers use accumulate()/finish() directly
        self.vocab = {}
        self._classes, self._cidx = [], {}
        self._counts = np.zeros((0, 0), np.float64)
        self._class_counts = np.zeros(0, np.float64)
        return self.accumulate(texts, labels).finish()

    def _bow(self, texts: Sequence[str]) -> np.ndarray:
        doc_ids, tok_ids = self._encode(texts, grow=False)
        bow = np.zeros((len(texts), len(self.vocab)), np.float32)
        np.add.at(bow, (doc_ids, tok_ids), 1.0)
        return bow

    def scores(self, texts: Sequence[str]) -> np.ndarray:
        """[n, K] log posterior scores: bag-of-words matmul."""
        import jax.numpy as jnp

        bow = jnp.asarray(self._bow(texts))
        return np.asarray(bow @ jnp.asarray(self.log_prob, jnp.float32)
                          + jnp.asarray(self.log_prior, jnp.float32)[None, :])

    def predict(self, texts: Sequence[str]) -> List[str]:
        s = self.scores(texts)
        return [self.class_values[i] for i in s.argmax(axis=1)]

    # ------------------------------------------------------------- file IO
    def save(self, path: str, delim: str = ",") -> None:
        """Model CSV in the reference's count-row spirit:
        a `#params` header (laplace, stop-word setting), then
        classVal,token,logProb rows + prior rows."""
        inv = {i: t for t, i in self.vocab.items()}
        with open(path, "w") as fh:
            fh.write(f"#params{delim}{self.laplace}{delim}"
                     f"{str(self.drop_stop).lower()}\n")
            for ki, cv in enumerate(self.class_values):
                fh.write(f"{cv}{delim}{delim}{self.log_prior[ki]:.6f}\n")
                for vi in range(len(inv)):
                    fh.write(f"{cv}{delim}{inv[vi]}{delim}"
                             f"{self.log_prob[vi, ki]:.6f}\n")

    @classmethod
    def load(cls, path: str, delim: str = ",") -> "TextNaiveBayes":
        m = cls()
        rows = []
        with open(path) as fh:
            for ln in fh:
                toks = ln.rstrip("\n").split(delim)
                if toks and toks[0] == "#params":
                    m.laplace = float(toks[1])
                    m.drop_stop = toks[2] == "true"
                    continue
                if len(toks) == 3:
                    rows.append(toks)
        m.class_values = sorted({r[0] for r in rows})
        cidx = {v: i for i, v in enumerate(m.class_values)}
        vocab_rows = [r for r in rows if r[1] != ""]
        m.vocab = {}
        for r in vocab_rows:
            if r[1] not in m.vocab:
                m.vocab[r[1]] = len(m.vocab)
        v, k = len(m.vocab), len(m.class_values)
        m.log_prob = np.zeros((v, k))
        m.log_prior = np.zeros(k)
        for cv, tok, val in rows:
            if tok == "":
                m.log_prior[cidx[cv]] = float(val)
            else:
                m.log_prob[m.vocab[tok], cidx[cv]] = float(val)
        return m
