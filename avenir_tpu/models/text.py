"""Text utilities: tokenizer + word counting.

Reference (SURVEY §2.8 text/): WordCounter.java:54 — an MR job that splits a
CSV field (or the whole line) with a Lucene StandardAnalyzer and counts
tokens. The same tokenizer backs the Naive Bayes free-text mode
(BayesianDistribution.java:186-195).

The StandardAnalyzer's observable behavior — lowercase, split on
non-alphanumerics, keep digits, drop English stop words — is reproduced
with a host regex tokenizer (tokenizing is irreducibly host/string work;
the counting after dictionary-encoding is a bincount)."""

from __future__ import annotations

import re
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

# Lucene StandardAnalyzer's default English stop set
STOP_WORDS = frozenset(
    "a an and are as at be but by for if in into is it no not of on or such "
    "that the their then there these they this to was will with".split())

_TOKEN_RE = re.compile(r"[a-z0-9]+(?:'[a-z0-9]+)?")


def tokenize(text: str, drop_stop_words: bool = True) -> List[str]:
    """StandardAnalyzer-like tokens: lowercased alphanumeric runs,
    stop words removed."""
    toks = _TOKEN_RE.findall(text.lower())
    if drop_stop_words:
        return [t for t in toks if t not in STOP_WORDS]
    return toks


class WordCounter:
    """Word-count job (WordCounter.java:54): count tokens of one CSV field
    (text_field_ordinal >= 0) or of whole lines (< 0); output rows of
    (token, count)."""

    def __init__(self, text_field_ordinal: int = -1, delim: str = ",",
                 drop_stop_words: bool = True):
        self.ordinal = text_field_ordinal
        self.delim = delim
        self.drop_stop = drop_stop_words

    def count(self, lines: Iterable[str]) -> List[Tuple[str, int]]:
        vocab: Dict[str, int] = {}
        codes: List[int] = []
        for line in lines:
            line = line.rstrip("\n")
            if not line.strip():
                continue
            text = (line.split(self.delim)[self.ordinal]
                    if self.ordinal >= 0 else line)
            for tok in tokenize(text, self.drop_stop):
                codes.append(vocab.setdefault(tok, len(vocab)))
        if not codes:
            return []
        counts = np.bincount(np.asarray(codes, np.int64), minlength=len(vocab))
        inv = list(vocab)
        return sorted(((inv[i], int(c)) for i, c in enumerate(counts)),
                      key=lambda kv: (-kv[1], kv[0]))
