"""K-nearest-neighbor classifier/regressor: the 5-job pipeline fused.

Reference flow (resource/knn.sh:44-132, SURVEY §3.3): (1) external sifarish
SameTypeSimilarity computes all-pairs train-test distances; (2-3) Bayesian
jobs compute per-train-entity feature posterior probabilities; (4) a join MR
attaches them to the distance file; (5) NearestNeighbor re-keys with
secondary sort so the reducer sees distance-ranked neighbors and votes
(knn/NearestNeighbor.java, knn/Neighborhood.java).

Here all five jobs are one device program per test batch: blocked streaming
top-k over the train set (ops.distance), kernel scores, and a one-hot
matmul vote — with the class-conditional weighting computed directly from a
NaiveBayesModel instead of a file join.

Kernel semantics follow Neighborhood.processClassDitribution
(Neighborhood.java:150-218) with KERNEL_SCALE=100 and int-floored scores;
distances are mapped to the reference's int scale (0..100) first:
  none                 score = 1
  linearMultiplicative score = d==0 ? 200 : floor(100/d)
  linearAdditive       score = 100 - d
  gaussian             score = floor(100 * exp(-0.5 (d/param)^2))
Class-conditional weighting multiplies each neighbor's score by its feature
posterior prob (Neighbor.setScore, :393-404), optionally by 1/d (inverse
distance). Classification = arg-max class score, or decision-threshold
pos/neg ratio test (classify(), :272-312). Regression = average / median /
per-query simple linear regression over the neighbors (doRegression(),
:223-250).
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from avenir_tpu.core.dataset import Dataset, pad_rows
from avenir_tpu.models.naive_bayes import NaiveBayesModel
from avenir_tpu.ops.distance import blocked_topk_neighbors, pad_train
from avenir_tpu.utils.metrics import ConfusionMatrix

KERNEL_SCALE = 100

KERNELS = ("none", "linearMultiplicative", "linearAdditive", "gaussian")


from avenir_tpu.core.dataset import extract_mixed_features as _extract


def _expand_mixed(x_num, ranges, x_cat, bins, metric: str):
    """One-hot-expand categoricals into the numeric matrix so the MIXED
    metric rides the numeric pallas kernels: a one-hot pair contributes
    ||a-b||^2 = 2*[a != b] (and L1 = 2*[a != b]), so scaling the one-hot
    by 1/sqrt(2) (euclidean) or 1/2 (manhattan) makes the kernel's summed
    term exactly the hamming mismatch count of ops.distance's mixed
    semantics. The caller divides by the SEMANTIC attribute count
    (n_attrs) instead of the expanded column count."""
    n = x_num.shape[0] if x_num is not None else x_cat.shape[0]
    cols = []
    if x_num is not None and x_num.shape[1]:
        cols.append(np.asarray(x_num, np.float32)
                    / np.maximum(np.asarray(ranges, np.float32), 1e-9))
    scale = (1.0 / np.sqrt(2.0)) if metric == "euclidean" else 0.5
    rows = np.arange(n, dtype=np.int32)
    for f, b in enumerate(bins or ()):
        oh = np.zeros((n, b), np.float32)
        oh[rows, np.asarray(x_cat[:, f], np.int32)] = scale
        cols.append(oh)
    x = np.concatenate(cols, axis=1) if cols else np.zeros((n, 0), np.float32)
    n_attrs = (x_num.shape[1] if x_num is not None else 0) + len(bins or ())
    return x, n_attrs


@partial(jax.jit, static_argnames=("kernel", "num_classes", "class_cond",
                                   "inverse_weighted"))
def _vote(
    dist: jnp.ndarray,            # [nq, k] raw distances in [0, ~1]
    neigh_labels: jnp.ndarray,    # [nq, k] int class codes
    neigh_post: jnp.ndarray,      # [nq, k] feature posterior probs (or ones)
    kernel: str,
    kernel_param: float,
    num_classes: int,
    class_cond: bool,
    inverse_weighted: bool,
):
    d = jnp.floor(dist * KERNEL_SCALE)          # reference's int distance scale
    if kernel == "none":
        score = jnp.ones_like(d)
    elif kernel == "linearMultiplicative":
        score = jnp.where(d == 0, 2.0 * KERNEL_SCALE, jnp.floor(KERNEL_SCALE / jnp.maximum(d, 1.0)))
    elif kernel == "linearAdditive":
        # clamp at 0: distances can exceed the normalized range when test
        # values fall outside the schema's declared [min, max], and a
        # negative score would subtract votes from the neighbor's class
        score = jnp.maximum(KERNEL_SCALE - d, 0.0)
    elif kernel == "gaussian":
        t = d / kernel_param
        score = jnp.floor(KERNEL_SCALE * jnp.exp(-0.5 * t * t))
    else:
        raise ValueError(f"unknown kernel {kernel}")

    if class_cond:
        w = jnp.where(neigh_post > 0, score * neigh_post, score)
        if inverse_weighted:
            w = w / jnp.maximum(d, 1.0)
        score = w

    # unfilled neighbor slots (dist=inf, idx=-1 sentinel) contribute nothing
    score = jnp.where(jnp.isfinite(dist), score, 0.0)
    oh = jax.nn.one_hot(neigh_labels, num_classes, dtype=jnp.float32)
    class_scores = jnp.einsum("qk,qkc->qc", score.astype(jnp.float32), oh)
    return class_scores


class NeighborIndex:
    """Streaming nearest-neighbor search over a train Dataset — the part of
    the pipeline that replaces sifarish. Label-free: usable for regression
    and clustering datasets whose schema has no class attribute."""

    def __init__(
        self,
        train: Dataset,
        k: int = 5,
        metric: str = "manhattan",
        block: int = 4096,
        approx: bool = False,
        use_pallas: Optional[bool] = None,
        packed: bool = False,
    ):
        """packed=True opts into the lane-resident packed-key kernel
        (ops.pallas_knn.knn_topk_lanes) — several times faster, but
        distances are quantized to ~2^-13 relative, which can reorder
        near-tied neighbors. The default (packed=False) keeps the exact
        kernel so TPU results match the jnp/reference path bit-for-bit
        modulo f32 dot-form error."""
        self.schema = train.schema
        # the reference takes "the first topMatchCount values" — a train set
        # smaller than k just yields all of it
        self.k = max(1, min(k, len(train)))
        self.metric = metric
        self.approx = approx
        self.block = min(block, max(len(train), 1))

        x_num, ranges, x_cat, bins = _extract(train)
        # the pallas kernels serve numeric AND mixed data on real TPU (the
        # flop-heavy sifarish role): categoricals one-hot-expand into the
        # numeric matrix (_expand_mixed) so the hamming term is matmul work
        from avenir_tpu.ops.pallas_knn import pallas_available

        has_features = (x_num.shape[1] + (x_cat.shape[1] if x_cat is not None
                                          else 0)) > 0
        if use_pallas:
            # explicit opt-in still requires the kernel's preconditions
            if not pallas_available():
                raise RuntimeError(
                    "pallas KNN kernel needs a TPU backend "
                    "(jax.default_backend() != 'tpu')")
            if not has_features:
                raise ValueError("pallas KNN kernel: schema has no features")
            if metric not in ("euclidean", "manhattan"):
                raise ValueError(f"pallas KNN kernel: unsupported metric {metric!r}")
            if approx:
                raise ValueError(
                    "the pallas KNN kernels compute full (non-approximate) "
                    "top-k; approx=True needs the jnp path (approx_min_k)")
        self.use_pallas = (
            use_pallas if use_pallas is not None
            else (pallas_available() and has_features
                  and metric in ("euclidean", "manhattan") and not approx)
        )
        self.packed = packed and self.use_pallas
        self.n_attrs = None
        self._expand_ranges = ranges
        if self.use_pallas:
            # normalize + one-hot-expand once; pad to the kernel block.
            # 256x8192 f32 tile = 8 MB VMEM, the measured sweet spot; the
            # lane-packed kernel carries global chunk ids so block_t has no
            # index-bit cap (corpus cap 524288 rows enforced by the kernel)
            x_num, self.n_attrs = _expand_mixed(x_num, ranges, x_cat, bins,
                                                metric)
            x_cat = None
            # 256-row granularity: the lane kernel's pair-fold front end
            # requires block_t % 256 == 0 (the exact kernel only needs
            # 128, but a 128-odd block would crash the packed path)
            self.block = max(256, min(pad_rows(len(train), 256), 8192))
            t_num, x_cat, n_valid = pad_train(x_num, None, self.block)
        else:
            t_num, x_cat, n_valid = pad_train(x_num, x_cat, self.block)
        # the cap is a static property of the corpus: decide the packed
        # routing once here, not per query (beyond the lane kernel's
        # packed-chunk-id cap the exact kernel serves — explicit index
        # carries, no cap)
        if self.packed and t_num is not None:
            from avenir_tpu.ops.pallas_knn import LANE_CORPUS_CAP

            self.packed = t_num.shape[0] <= LANE_CORPUS_CAP
        self.t_num = jnp.asarray(t_num) if t_num is not None else None
        self.t_cat = jnp.asarray(x_cat) if x_cat is not None else None
        self.cat_bins = bins
        self.ranges = jnp.asarray(ranges) if ranges.size else None
        self.n_valid = n_valid
        self.n_padded = (
            self.t_num.shape[0] if self.t_num is not None else self.t_cat.shape[0]
        )

    def neighbors(self, test: Dataset) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """(dist [nq,k], train index [nq,k]); unfillable slots are (+inf, -1)."""
        q_num, _, q_cat, _ = _extract(test)
        if self.use_pallas:
            from avenir_tpu.ops.pallas_knn import knn_topk_lanes, knn_topk_pallas

            q, _ = _expand_mixed(q_num, self._expand_ranges, q_cat,
                                 self.cat_bins, self.metric)
            bq = 256
            nq = q.shape[0]
            pad = (-nq) % bq
            if pad:
                q = np.concatenate([q, np.zeros((pad, q.shape[1]), q.dtype)])
            if self.packed:
                dist, idx = knn_topk_lanes(
                    jnp.asarray(q), self.t_num, k=self.k, block_q=bq,
                    block_t=self.block, metric=self.metric,
                    n_valid=self.n_valid, n_attrs=self.n_attrs)
            else:
                dist, idx = knn_topk_pallas(
                    jnp.asarray(q), self.t_num, k=self.k, block_q=bq,
                    block_t=self.block, metric=self.metric,
                    n_valid=self.n_valid, n_attrs=self.n_attrs)
            return dist[:nq], idx[:nq]
        return blocked_topk_neighbors(
            jnp.asarray(q_num) if self.t_num is not None else None,
            self.t_num,
            jnp.asarray(q_cat) if self.t_cat is not None else None,
            self.t_cat,
            cat_bins=self.cat_bins,
            num_ranges=self.ranges,
            k=self.k,
            block=self.block,
            metric=self.metric,
            n_valid=self.n_valid,
            approx=self.approx,
        )

    def classify_scores(self, test: Dataset, train_labels: jnp.ndarray,
                        n_classes: int, kernel_fn: str,
                        kernel_param: float) -> Optional[jnp.ndarray]:
        """Fully fused device classification: kernel-weighted top-k vote
        scores [nq, C] via ops.pallas_knn.knn_classify_lanes — the top-k
        results never leave the kernel (non-class-conditional vote modes).
        Returns None when this index can't serve the fused path (jnp
        route, or a block too small for the lane kernel's pair fold)."""
        if not self.use_pallas or self.block % 256 != 0:
            return None
        from avenir_tpu.ops.pallas_knn import knn_classify_lanes

        q_num, _, q_cat, _ = _extract(test)
        q, _ = _expand_mixed(q_num, self._expand_ranges, q_cat,
                             self.cat_bins, self.metric)
        bq = 256
        nq = q.shape[0]
        pad = (-nq) % bq
        if pad:
            q = np.concatenate([q, np.zeros((pad, q.shape[1]), q.dtype)])
        scores = knn_classify_lanes(
            jnp.asarray(q), self.t_num, train_labels, k=self.k,
            n_classes=n_classes, n_attrs=self.n_attrs,
            kernel_fn=kernel_fn, kernel_param=kernel_param, block_q=bq,
            block_t=self.block, metric=self.metric, n_valid=self.n_valid)
        return scores[:nq]


class NearestNeighborClassifier:
    """nen.* job equivalent. Parameters mirror the knn.properties keys."""

    def __init__(
        self,
        train: Dataset,
        top_match_count: int = 5,
        kernel_function: str = "none",
        kernel_param: float = 1.0,
        class_cond_weighted: bool = False,
        inverse_distance_weighted: bool = False,
        decision_threshold: float = -1.0,
        positive_class: Optional[str] = None,
        metric: str = "manhattan",
        block: int = 4096,
        nb_model: Optional[NaiveBayesModel] = None,
        approx: bool = False,
        fused: bool = False,
        packed: bool = False,
    ):
        """fused=True opts into the in-kernel vote (knn_classify_lanes) for
        the non-class-conditional modes: class scores come straight out of
        the pallas kernel (distances quantized ~2^-21, ties biased toward
        lower class codes). packed=True opts the top-k side into the
        lane-resident packed-key kernel (NeighborIndex). The default
        composes the exact top-k with the jitted _vote."""
        self.index = NeighborIndex(train, k=top_match_count, metric=metric,
                                   block=block, approx=approx, packed=packed)
        self.fused = fused
        self.schema = train.schema
        self.k = self.index.k
        self.kernel = kernel_function
        self.kernel_param = kernel_param
        self.class_cond = class_cond_weighted
        self.inverse_weighted = inverse_distance_weighted
        self.decision_threshold = decision_threshold
        self.class_values = train.schema.class_values()
        self.positive_class = (
            self.class_values.index(positive_class) if positive_class else 1
        )
        pad = self.index.n_padded
        n_valid = self.index.n_valid
        labels = np.zeros((pad,), np.int32)
        labels[:n_valid] = train.labels()
        self.train_labels = jnp.asarray(labels)

        # class-conditional weighting: P(features_i | class_i) per train row,
        # the quantity jobs (2)-(4) of the reference pipeline compute + join
        # (BayesianPredictor bap.output.feature.prob.only=true mode) — the
        # same NaiveBayesPredictor.feature_prob the file-based job emits
        post = np.ones((pad,), np.float32)
        if class_cond_weighted:
            from avenir_tpu.models.naive_bayes import NaiveBayesPredictor

            model = nb_model if nb_model is not None else NaiveBayesModel.fit(train)
            post[: len(train)] = NaiveBayesPredictor(model).feature_prob(
                train).astype(np.float32)
        self.train_post = jnp.asarray(post)

    # ------------------------------------------------------------- neighbors
    def neighbors(self, test: Dataset) -> Tuple[np.ndarray, np.ndarray]:
        """(dist [nq,k], train index [nq,k]) over the real train rows."""
        return self.index.neighbors(test)

    # --------------------------------------------------------------- predict
    def predict(self, test: Dataset) -> Tuple[np.ndarray, np.ndarray]:
        """Returns (predicted class codes [nq], class scores [nq, K])."""
        scores = None
        if self.fused and not self.class_cond:
            scores = self.index.classify_scores(
                test, self.train_labels, len(self.class_values),
                self.kernel, self.kernel_param)
        if scores is None:
            dist, idx = self.neighbors(test)
            neigh_labels = self.train_labels[idx]
            neigh_post = self.train_post[idx]
            scores = _vote(
                dist, neigh_labels, neigh_post,
                self.kernel, self.kernel_param, len(self.class_values),
                self.class_cond, self.inverse_weighted,
            )
        scores = np.asarray(scores)
        # the reference's threshold branch exists only in non-class-cond mode
        # (Neighborhood.classify(), :272-312: weighted path pure-argmaxes)
        if (self.decision_threshold > 0 and len(self.class_values) == 2
                and not self.class_cond):
            pos = self.positive_class
            neg = 1 - pos
            ratio = scores[:, pos] / np.maximum(scores[:, neg], 1e-9)
            pred = np.where(ratio > self.decision_threshold, pos, neg).astype(np.int32)
        else:
            pred = scores.argmax(axis=1).astype(np.int32)
        return pred, scores

    def validate(self, test: Dataset, pos_class: Optional[int] = None) -> ConfusionMatrix:
        pred, _ = self.predict(test)
        cm = ConfusionMatrix(
            self.class_values,
            pos_class=self.positive_class if pos_class is None else pos_class,
        )
        cm.add(test.labels(), pred)
        return cm


class NearestNeighborRegressor:
    """Regression modes of Neighborhood.doRegression: average / median /
    per-query simple linear regression (commons-math3 SimpleRegression
    equivalent via closed-form least squares, vmap'd over queries)."""

    def __init__(
        self,
        train: Dataset,
        target: np.ndarray,
        top_match_count: int = 5,
        method: str = "average",
        regr_input: Optional[np.ndarray] = None,
        metric: str = "manhattan",
        block: int = 4096,
    ):
        self.index = NeighborIndex(train, k=top_match_count, metric=metric,
                                   block=block)
        pad = self.index.n_padded
        t = np.zeros((pad,), np.float32)
        t[: len(target)] = np.asarray(target, np.float32)
        self.target = jnp.asarray(t)
        self.method = method
        if regr_input is not None:
            ri = np.zeros((pad,), np.float32)
            ri[: len(regr_input)] = np.asarray(regr_input, np.float32)
            self.regr_input = jnp.asarray(ri)
        else:
            self.regr_input = None

    def predict(self, test: Dataset,
                query_input: Optional[np.ndarray] = None) -> np.ndarray:
        dist, idx = self.index.neighbors(test)
        y = self.target[idx]                                    # [nq, k]
        if self.method == "average":
            return np.asarray(y.mean(axis=1))
        if self.method == "median":
            return np.asarray(jnp.median(y, axis=1))
        if self.method == "linearRegression":
            assert self.regr_input is not None and query_input is not None
            x = self.regr_input[idx]                            # [nq, k]
            xm = x.mean(axis=1, keepdims=True)
            ym = y.mean(axis=1, keepdims=True)
            cov = ((x - xm) * (y - ym)).sum(axis=1)
            var = ((x - xm) ** 2).sum(axis=1)
            slope = cov / jnp.maximum(var, 1e-9)
            intercept = ym[:, 0] - slope * xm[:, 0]
            return np.asarray(intercept + slope * jnp.asarray(query_input))
        raise ValueError(f"unknown regression method {self.method}")
