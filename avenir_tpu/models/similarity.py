"""Pairwise record similarity: the sifarish / spark-similarity role.

The reference outsources all-pairs record distances to an external MR job
(sifarish SameTypeSimilarity, driven at resource/knn.sh:44-57, `sts.*`
config keys) and carries two Spark analogs: RecordSimilarity (all-pairs via
bucket-pair joins, spark/.../similarity/RecordSimilarity.scala:34) and
GroupedRecordSimilarity (within-group pairs, GroupedRecordSimilarity.scala:29),
both delegating the mixed-attribute metric to chombo InterRecordDistance.

TPU design: the bucket-pair shuffle trick exists only to spread O(n²) work
over Spark executors — on device the same coverage is a blocked tile sweep
where each [bi, bj] distance tile is one `pairwise_distance` call (matmul
work on the MXU), so there is no analog of the bucket hashing at all. The
distance-file output surface stays: `id1,id2,scaled-int-distance` rows
(sts.distance.scale=1000) that downstream consumers (KNN, agglomerative
clustering) read back via `read_distance_file`.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np
import jax.numpy as jnp

from avenir_tpu.core.dataset import Dataset, extract_mixed_features
from avenir_tpu.ops.distance import pairwise_distance


class RecordSimilarity:
    """Blocked all-pairs mixed-attribute distances over Datasets.

    metric/weights follow the distance-schema semantics of the reference
    (numeric range-normalized, categorical 0/1 mismatch, weight-averaged).
    `intra()` yields the i<j pairs of one dataset (RecordSimilarity.scala
    coverage); `inter()` the cross pairs of two datasets
    (sts.inter.set.matching=true, the KNN train-vs-test mode).
    """

    def __init__(
        self,
        metric: str = "manhattan",
        scale: int = 1000,
        block: int = 2048,
        num_weights: Optional[Sequence[float]] = None,
        cat_weights: Optional[Sequence[float]] = None,
    ):
        self.metric = metric
        self.scale = scale
        self.block = block
        self.num_weights = (np.asarray(num_weights, np.float32)
                            if num_weights is not None else None)
        self.cat_weights = (tuple(float(w) for w in cat_weights)
                            if cat_weights is not None else None)

    # ------------------------------------------------------------- kernels
    def _tiles(self, a: Dataset, b: Dataset, upper_only: bool
               ) -> Iterator[Tuple[int, int, np.ndarray]]:
        """Yield (row0, col0, dist tile) over block-pair tiles."""
        a_num, ranges, a_cat, bins = extract_mixed_features(a)
        b_num, _, b_cat, _ = extract_mixed_features(b)
        nw = jnp.asarray(self.num_weights) if self.num_weights is not None else None
        na, nb = len(a), len(b)
        for i0 in range(0, na, self.block):
            i1 = min(i0 + self.block, na)
            for j0 in range(0, nb, self.block):
                if upper_only and j0 + self.block <= i0:
                    continue  # tile entirely below the diagonal
                j1 = min(j0 + self.block, nb)
                d = pairwise_distance(
                    jnp.asarray(a_num[i0:i1]), jnp.asarray(b_num[j0:j1]),
                    jnp.asarray(a_cat[i0:i1]) if a_cat is not None else None,
                    jnp.asarray(b_cat[j0:j1]) if b_cat is not None else None,
                    bins, jnp.asarray(ranges), self.metric,
                    nw, self.cat_weights,
                )
                yield i0, j0, np.asarray(d)

    # -------------------------------------------------------------- intra
    def intra(self, ds: Dataset) -> Iterator[Tuple[str, str, float]]:
        """All unordered pairs (i < j) of one dataset."""
        ids = ds.ids()
        for i0, j0, tile in self._tiles(ds, ds, upper_only=True):
            for ii in range(tile.shape[0]):
                jstart = max(i0 + ii + 1 - j0, 0)
                for jj in range(jstart, tile.shape[1]):
                    yield str(ids[i0 + ii]), str(ids[j0 + jj]), float(tile[ii, jj])

    # -------------------------------------------------------------- inter
    def inter(self, base: Dataset, other: Dataset
              ) -> Iterator[Tuple[str, str, float]]:
        """All cross pairs (base x other) — the train-vs-test matching mode."""
        bids, oids = base.ids(), other.ids()
        for i0, j0, tile in self._tiles(base, other, upper_only=False):
            for ii in range(tile.shape[0]):
                for jj in range(tile.shape[1]):
                    yield str(bids[i0 + ii]), str(oids[j0 + jj]), float(tile[ii, jj])

    # ------------------------------------------------------------ file IO
    def save(self, pairs: Iterator[Tuple[str, str, float]], path: str,
             delim: str = ",", id_first: bool = True) -> int:
        """Write `id1,id2,scaledDist` rows (sts.output.id.first and
        sts.distance.scale semantics). Returns the pair count."""
        n = 0
        with open(path, "w") as fh:
            for id1, id2, d in pairs:
                sd = int(round(d * self.scale))
                if id_first:
                    fh.write(f"{id1}{delim}{id2}{delim}{sd}\n")
                else:
                    fh.write(f"{sd}{delim}{id1}{delim}{id2}\n")
                n += 1
        return n


class GroupedRecordSimilarity(RecordSimilarity):
    """Within-group all-pairs distances (GroupedRecordSimilarity.scala:29):
    rows grouped by one or more field ordinals; pairs never cross groups."""

    def __init__(self, group_ordinals: Sequence[int], **kw):
        super().__init__(**kw)
        self.group_ordinals = list(group_ordinals)

    def _group_key(self, ds: Dataset, i: int) -> Tuple:
        key = []
        for o in self.group_ordinals:
            fld = ds.schema.field_by_ordinal(o)
            v = ds.column(o)[i]
            key.append(fld.decode_value(int(v)) if fld.is_categorical else str(v))
        return tuple(key)

    def grouped_intra(self, ds: Dataset
                      ) -> Iterator[Tuple[Tuple, str, str, float]]:
        groups: Dict[Tuple, List[int]] = {}
        for i in range(len(ds)):
            groups.setdefault(self._group_key(ds, i), []).append(i)
        for key in sorted(groups):
            sub = ds.take(np.asarray(groups[key]))
            for id1, id2, d in self.intra(sub):
                yield key, id1, id2, d


# --------------------------------------------------------------- dist files
def read_distance_file(path: str, delim: str = ",", scale: int = 1000,
                       id_first: bool = True) -> Dict[Tuple[str, str], float]:
    """Load a distance file back into a symmetric pair->distance map — the
    EntityDistanceMapFileAccessor role (util/EntityDistanceMapFileAccessor.java:42)
    that feeds AgglomerativeGraphical clustering. `id_first` must match the
    layout the file was written with (save(..., id_first=...))."""
    out: Dict[Tuple[str, str], float] = {}
    with open(path) as fh:
        for ln in fh:
            toks = [t.strip() for t in ln.rstrip("\n").split(delim)]
            if len(toks) < 3:
                continue
            if id_first:
                id1, id2, sd = toks[0], toks[1], float(toks[2])
            else:
                sd, id1, id2 = float(toks[0]), toks[1], toks[2]
            d = sd / scale
            out[(id1, id2)] = d
            out[(id2, id1)] = d
    return out


def distance_matrix_from_file(path: str, ids: Sequence[str],
                              delim: str = ",", scale: int = 1000,
                              default: float = np.inf,
                              pairs: Optional[Dict[Tuple[str, str], float]]
                              = None) -> np.ndarray:
    """Dense [n, n] matrix over `ids` from a distance file (missing pairs
    get `default`; diagonal 0). Pass `pairs` from a prior
    read_distance_file call to skip re-parsing the (O(n^2)-line) file."""
    if pairs is None:
        pairs = read_distance_file(path, delim, scale)
    n = len(ids)
    m = np.full((n, n), default, np.float64)
    np.fill_diagonal(m, 0.0)
    index = {str(v): i for i, v in enumerate(ids)}
    for (a, b), d in pairs.items():
        ia, ib = index.get(a), index.get(b)
        if ia is not None and ib is not None:
            m[ia, ib] = d
    return m
