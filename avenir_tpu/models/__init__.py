"""Algorithm layer: the org.avenir.* job families re-built as jitted array programs."""
