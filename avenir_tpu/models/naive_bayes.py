"""Naive Bayes: class-conditional feature distributions + posterior predictor.

Reference semantics (org.avenir.bayesian):
- Train (BayesianDistribution.java): one pass over labeled CSV. Categorical /
  bucketed numeric features contribute (classVal, featureOrd, bin) -> count;
  unbinned numerics contribute (classVal, featureOrd) -> (count, sum, sum-sq)
  turned into per-class Gaussian mean/stddev (mapper :137-178, reducer
  :263-327); class priors and feature priors aggregate from the posteriors
  (cleanup :240-258). Model is a flat CSV file.
- Predict (BayesianPredictor.java): per record, per class,
  P(C|F) = P(F|C) * P(C) / P(F) with P(F|C) a product over per-feature bin
  probabilities (Gaussian density for continuous), scaled to int percent
  (:396-421); max-prob or cost-based arbitration (:342-391); confusion
  matrix counters in cleanup (:170-180).

TPU design: the two MR jobs collapse into two jitted programs. Training is
one einsum contraction onehot(class) x onehot(feature bins) -> [F, K, B]
count tensor (MXU work, no shuffle); counts are additive, so streaming
batches and mesh shards combine by psum — the same tensor algebra replaces
both the Hadoop combiner and the reducer. Prediction is a single
log-space matmul over one-hot feature codes.

Deviation from reference noted: the reference computes continuous means with
integer (long) division (BayesianDistribution.java:248); we use float math.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from functools import partial
from typing import Dict, List, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from avenir_tpu.core.dataset import Dataset
from avenir_tpu.core.schema import FeatureField, FeatureSchema
from avenir_tpu.utils.metrics import ConfusionMatrix, CostBasedArbitrator

_TINY = 1e-30


@dataclass
class NaiveBayesModel:
    """Count-space model (additive; finish() derives probability tables)."""

    schema: FeatureSchema
    class_values: List[str]
    binned_fields: List[FeatureField]
    cont_fields: List[FeatureField]
    bins: List[int]
    # counts: [F, K, Bmax] posterior bin counts (padded over B)
    post_counts: np.ndarray
    # continuous: [Fc, K, 3] (count, sum, sumsq) and prior [Fc, 3]
    cont_moments: np.ndarray
    class_counts: np.ndarray  # [K]
    # set when a model was loaded from CSV (mean/std known, raw moments not):
    cont_params: Optional[np.ndarray] = None        # [Fc, K, 2] (mean, std)
    cont_prior_params: Optional[np.ndarray] = None  # [Fc, 2]
    # deferred device-side accumulator (streaming ingest): a pytree of
    # per-chunk count tensors folded on device. Unweighted counts with no
    # continuous features fold as int32 (exact to 2^31 rows per cell — no
    # mid-stream flush at any realistic scale); weighted or moment-bearing
    # folds stay f32 and flush to the float64 host arrays before any cell
    # could exceed f32 integer exactness (2^24)
    _pending: Optional[tuple] = None
    _pending_rows: int = 0
    _pending_int: bool = False

    # rows a single pending f32 cell can safely absorb (2^24 ~ 16.7M,
    # with margin); crossing it flushes to host float64. int32 folds get
    # a 2^30 bound.
    _FLUSH_ROWS = 14 << 20
    _FLUSH_ROWS_INT = 1 << 30

    # ------------------------------------------------------------ training
    @classmethod
    def empty(cls, schema: FeatureSchema) -> "NaiveBayesModel":
        binned = [f for f in schema.feature_fields if f.num_bins() > 0]
        cont = [f for f in schema.feature_fields if f.is_numeric and not f.bucket_width]
        bins = [f.num_bins() for f in binned]
        k = schema.num_classes()
        bmax = max(bins) if bins else 1
        return cls(
            schema=schema,
            class_values=schema.class_values(),
            binned_fields=binned,
            cont_fields=cont,
            bins=bins,
            post_counts=np.zeros((len(binned), k, bmax), np.float64),
            cont_moments=np.zeros((len(cont), k, 3), np.float64),
            class_counts=np.zeros((k,), np.float64),
        )

    def accumulate(self, codes, labels, x_cont, weights=None,
                   defer: bool = False) -> None:
        """Add one batch of sufficient statistics.

        defer=False (default) fetches the device-computed count pytree to
        the host immediately. defer=True — the streaming-ingest path —
        folds it into a device-side accumulator instead, so a chunk loop
        dispatches asynchronously with no host round trip per chunk; the
        fold flushes to the float64 host arrays automatically before any
        cell could lose f32 integer exactness, and flush() (called by
        finish/to_csv/merge) drains the remainder."""
        k = len(self.class_values)
        bmax = self.post_counts.shape[2]
        n = labels.shape[0]
        int_mode = weights is None and self.cont_moments.shape[0] == 0
        if int_mode and jax.default_backend() == "cpu":
            # XLA:CPU pays the [n, F, bmax] one-hot einsum in memory
            # bandwidth — ~100MB of materialized one-hots per 500k-row
            # chunk for a table that is only F*K*bmax cells. Host
            # bincount builds the same integer counts directly into the
            # float64 arrays: bit-identical tables, same CPU-host
            # contract as explore._mi_chunk_counts_host.
            self.flush()
            codes_h = np.ascontiguousarray(codes, np.int32)
            y_h = np.asarray(labels, np.int32)
            yb = y_h * np.int32(bmax)
            for f in range(self.post_counts.shape[0]):
                self.post_counts[f] += np.bincount(
                    yb + codes_h[:, f],
                    minlength=k * bmax).reshape(k, bmax)
            self.class_counts += np.bincount(y_h, minlength=k)
            return
        if self._pending is not None and self._pending_int != int_mode:
            self.flush()
        w = (jnp.asarray(weights) if weights is not None
             else jnp.ones((n,), jnp.float32))
        if self._pending is None:
            f, fc = self.post_counts.shape[0], self.cont_moments.shape[0]
            dt = jnp.int32 if int_mode else jnp.float32
            self._pending = (jnp.zeros((f, k, bmax), dt),
                             jnp.zeros((fc, k, 3), jnp.float32),
                             jnp.zeros((k,), dt))
            self._pending_int = int_mode
        # count + fold is ONE jitted dispatch with a donated accumulator —
        # a chunk loop never round-trips the host (per-dispatch latency,
        # not device FLOPs, is what kills a chunked loop otherwise)
        self._pending = _fold_batch_kernel(
            self._pending, jnp.asarray(codes), jnp.asarray(labels),
            jnp.asarray(x_cont), w, k, bmax)
        # shape only — np.asarray here would fetch the whole device chunk
        self._pending_rows += int(n)
        bound = self._FLUSH_ROWS_INT if int_mode else self._FLUSH_ROWS
        if not defer or self._pending_rows >= bound:
            self.flush()

    def flush(self) -> None:
        """Drain the deferred device accumulator into the host arrays."""
        if self._pending is None:
            return
        post, mom, cls = self._pending
        self._pending = None
        self._pending_rows = 0
        self.post_counts += np.asarray(post, np.float64)
        self.cont_moments += np.asarray(mom, np.float64)
        self.class_counts += np.asarray(cls, np.float64)

    @classmethod
    def fit(cls, dataset: Dataset) -> "NaiveBayesModel":
        model = cls.empty(dataset.schema)
        codes, _ = dataset.feature_codes(model.binned_fields)
        x_cont = dataset.feature_matrix(model.cont_fields)
        model.accumulate(codes, dataset.labels(), x_cont)
        return model

    def merge(self, other: "NaiveBayesModel") -> "NaiveBayesModel":
        """Combine sufficient statistics of two partial fits (counts are
        additive — the same algebra that merges mesh shards via psum merges
        input splits; replaces the reference's reducer-side summation)."""
        if self.cont_params is not None or other.cont_params is not None:
            raise ValueError("cannot merge models loaded from CSV "
                             "(raw moments unavailable)")
        self.flush()
        other.flush()
        self.post_counts = self.post_counts + other.post_counts
        self.cont_moments = self.cont_moments + other.cont_moments
        self.class_counts = self.class_counts + other.class_counts
        return self

    # ----------------------------------------------------------- finishing
    def finish(self) -> Dict[str, jnp.ndarray]:
        """Derive the probability tables used by the jitted predictor.

        Mirrors BayesianModel.finishUp() (BayesianModel.java:217-233):
        posterior P(bin|class) normalized within class, feature prior P(bin),
        class prior P(class); continuous features get per-class and prior
        Gaussian (mean, std)."""
        self.flush()
        f, k, bmax = self.post_counts.shape
        post = self.post_counts
        post_p = post / np.maximum(post.sum(axis=2, keepdims=True), _TINY)
        prior_counts = post.sum(axis=1)                       # [F, B]
        prior_p = prior_counts / np.maximum(
            prior_counts.sum(axis=1, keepdims=True), _TINY
        )
        class_p = self.class_counts / max(self.class_counts.sum(), _TINY)

        if self.cont_params is not None:
            mean, std = self.cont_params[..., 0], self.cont_params[..., 1]
            pmean, pstd = self.cont_prior_params[..., 0], self.cont_prior_params[..., 1]
        else:
            cm = self.cont_moments
            cnt = np.maximum(cm[..., 0], _TINY)
            mean = cm[..., 1] / cnt
            var = (cm[..., 2] - cnt * mean * mean) / np.maximum(cnt - 1, 1.0)
            std = np.sqrt(np.maximum(var, _TINY))
            pm = cm.sum(axis=1)                                # prior moments [Fc,3]
            pcnt = np.maximum(pm[..., 0], _TINY)
            pmean = pm[..., 1] / pcnt
            pvar = (pm[..., 2] - pcnt * pmean * pmean) / np.maximum(pcnt - 1, 1.0)
            pstd = np.sqrt(np.maximum(pvar, _TINY))
        std = np.maximum(std, 1e-6)
        pstd = np.maximum(pstd, 1e-6)

        return {
            "log_post": jnp.asarray(np.log(np.maximum(post_p, _TINY)), jnp.float32),
            "log_prior": jnp.asarray(np.log(np.maximum(prior_p, _TINY)), jnp.float32),
            "log_class": jnp.asarray(np.log(np.maximum(class_p, _TINY)), jnp.float32),
            "cont_mean": jnp.asarray(mean, jnp.float32),
            "cont_std": jnp.asarray(std, jnp.float32),
            "cont_prior_mean": jnp.asarray(pmean, jnp.float32),
            "cont_prior_std": jnp.asarray(pstd, jnp.float32),
        }

    # ------------------------------------------------------------- file IO
    def to_csv(self, delim: str = ",") -> str:
        """Reference-compatible model CSV (BayesianDistribution reducer
        format, parsed back by BayesianPredictor.loadModel :186-224):
          classVal,ord,bin,count          feature posterior (binned)
          classVal,ord,,mean,stddev       feature posterior (continuous)
          classVal,,,count                class prior (per reduce emit)
          ,ord,bin,count                  feature prior (binned, per class)
          ,ord,,mean,stddev               feature prior (continuous)
        """
        self.flush()
        out: List[str] = []
        d = delim
        for fi, fld in enumerate(self.binned_fields):
            for ki, cv in enumerate(self.class_values):
                for b in range(self.bins[fi]):
                    c = int(self.post_counts[fi, ki, b])
                    if c == 0:
                        continue
                    blabel = fld.cardinality[b] if fld.is_categorical else str(b)
                    out.append(f"{cv}{d}{fld.ordinal}{d}{blabel}{d}{c}")
                    out.append(f"{cv}{d}{d}{d}{c}")
                    out.append(f"{d}{fld.ordinal}{d}{blabel}{d}{c}")
        for fi, fld in enumerate(self.cont_fields):
            for ki, cv in enumerate(self.class_values):
                cnt, s, sq = self.cont_moments[fi, ki]
                if cnt <= 0:
                    continue
                mean = s / cnt
                var = (sq - cnt * mean * mean) / max(cnt - 1, 1.0)
                std = math.sqrt(max(var, 0.0))
                out.append(f"{cv}{d}{fld.ordinal}{d}{d}{mean:.6f}{d}{std:.6f}")
                out.append(f"{cv}{d}{d}{d}{int(cnt)}")
            pm = self.cont_moments[fi].sum(axis=0)
            pmean = pm[1] / max(pm[0], 1.0)
            pvar = (pm[2] - pm[0] * pmean * pmean) / max(pm[0] - 1, 1.0)
            out.append(
                f"{d}{fld.ordinal}{d}{d}{pmean:.6f}{d}{math.sqrt(max(pvar, 0.0)):.6f}"
            )
        return "\n".join(out) + "\n"

    def save(self, path: str, delim: str = ",", stamp: bool = True) -> None:
        """``stamp`` publishes the format/digest sidecar the serving
        path verifies at load (models/artifact.py)."""
        with open(path, "w") as fh:
            fh.write(self.to_csv(delim))
        if stamp:
            from avenir_tpu.models.artifact import write_stamp
            write_stamp(path)

    @classmethod
    def load(cls, path: str, schema: FeatureSchema, delim: str = ",") -> "NaiveBayesModel":
        from avenir_tpu.models.artifact import verify_stamp
        verify_stamp(path)
        # the model file is self-describing (the reference's BayesianModel
        # is built from the file alone, BayesianPredictor.java:332-340):
        # class values and categorical feature bins it mentions extend any
        # data-discovered vocabularies a freshly-loaded schema lacks,
        # in file order so codes match the training-side discovery
        cat_need = {f.ordinal: f for f in schema.fields
                    if f.is_categorical and not f.cardinality
                    and not f.id_field}
        if cat_need:
            cls_fld = schema.class_field
            cls_ord = cls_fld.ordinal if cls_fld is not None else None
            seen: Dict[int, List[str]] = {o: [] for o in cat_need}
            with open(path) as fh:
                for line in fh:
                    items = line.rstrip("\n").split(delim)
                    if len(items) < 4:
                        continue
                    cv, o, b = items[0], items[1], items[2]
                    if cv and cls_ord in seen and cv not in seen[cls_ord]:
                        seen[cls_ord].append(cv)
                    if o and b:
                        ordn = int(o)
                        if ordn in seen and ordn != cls_ord \
                                and b not in seen[ordn]:
                            seen[ordn].append(b)
            for o, fld in cat_need.items():
                if seen[o]:
                    fld.cardinality = seen[o]
                    fld.discovered_cardinality = True
        model = cls.empty(schema)
        bin_index = {f.ordinal: i for i, f in enumerate(model.binned_fields)}
        cont_index = {f.ordinal: i for i, f in enumerate(model.cont_fields)}
        cls_index = {v: i for i, v in enumerate(model.class_values)}
        k = len(model.class_values)
        if model.cont_fields:
            model.cont_params = np.zeros((len(model.cont_fields), k, 2))
            model.cont_prior_params = np.zeros((len(model.cont_fields), 2))
        class_counts = np.zeros_like(model.class_counts)
        with open(path) as fh:
            for line in fh:
                items = line.rstrip("\n").split(delim)
                if len(items) < 4:
                    continue
                cv, o, b = items[0], items[1], items[2]
                if cv == "" and o != "":
                    if b == "":  # continuous feature prior: ,ord,,mean,std
                        fi = cont_index[int(o)]
                        model.cont_prior_params[fi] = [float(items[3]), float(items[4])]
                    # binned feature priors re-derive from posteriors
                elif cv != "" and o == "" and b == "":
                    # class prior rows: reference emits one per reduce group and
                    # sums on load (BayesianModel.addClassPrior); normalization
                    # cancels the duplication
                    class_counts[cls_index[cv]] += float(items[3])
                elif cv != "" and o != "":
                    ordn = int(o)
                    ki = cls_index[cv]
                    if b != "":  # binned posterior
                        fi = bin_index[ordn]
                        fld = model.binned_fields[fi]
                        code = (
                            fld.cardinality_index()[b]
                            if fld.is_categorical
                            else int(b)
                        )
                        model.post_counts[fi, ki, code] += float(items[3])
                    else:  # continuous posterior: classVal,ord,,mean,std
                        fi = cont_index[ordn]
                        model.cont_params[fi, ki] = [float(items[3]), float(items[4])]
        model.class_counts = class_counts
        return model


@partial(jax.jit, static_argnames=("k", "bmax"))
def _count_batch_kernel(codes, labels, x_cont, w, k: int, bmax: int):
    oh_k = jax.nn.one_hot(labels, k, dtype=jnp.float32) * w[:, None]   # [n,K]
    oh_b = jax.nn.one_hot(codes, bmax, dtype=jnp.float32)              # [n,F,B]
    post = jnp.einsum("nk,nfb->fkb", oh_k, oh_b)
    trip = jnp.stack(
        [jnp.ones_like(x_cont), x_cont, x_cont * x_cont], axis=-1
    )                                                                  # [n,Fc,3]
    mom = jnp.einsum("nk,nfm->fkm", oh_k, trip)
    cls = oh_k.sum(axis=0)
    return post, mom, cls


def _count_batch(codes, labels, x_cont, k: int, bmax: int, weights=None):
    n = labels.shape[0]
    w = weights if weights is not None else jnp.ones((n,), jnp.float32)
    return _count_batch_kernel(codes, labels, x_cont, w, k, bmax)


@partial(jax.jit, static_argnames=("k", "bmax"), donate_argnums=(0,))
def _fold_batch_kernel(acc, codes, labels, x_cont, w, k: int, bmax: int):
    batch = _count_batch_kernel(codes, labels, x_cont, w, k, bmax)
    # per-batch einsum counts are <= batch rows, exact in f32; the fold
    # target's dtype (int32 on the unweighted path) sets the ceiling
    return jax.tree.map(lambda a, b: a + b.astype(a.dtype), acc, batch)


class NaiveBayesPredictor:
    """Jitted posterior computation + arbitration over a finished model."""

    def __init__(
        self,
        model: NaiveBayesModel,
        arbitrator: Optional[CostBasedArbitrator] = None,
    ):
        self.model = model
        self.tables = model.finish()
        self.arbitrator = arbitrator

        @jax.jit
        def predict(codes, x_cont, tables):
            # binned: log P(F|C) = sum_f log_post[f, :, code_f]; einsum over
            # one-hot keeps it on the MXU.
            parts = []
            if codes.shape[1] > 0:
                oh = jax.nn.one_hot(codes, tables["log_post"].shape[2],
                                    dtype=jnp.float32)          # [n,F,B]
                lp = jnp.einsum("nfb,fkb->nk", oh, tables["log_post"])
                lprior = jnp.einsum("nfb,fb->n", oh, tables["log_prior"])
                parts.append((lp, lprior))
            if x_cont.shape[1] > 0:
                mean, std = tables["cont_mean"], tables["cont_std"]        # [Fc,K]
                x = x_cont[:, :, None]                                      # [n,Fc,1]
                logpdf = (
                    -0.5 * jnp.log(2 * jnp.pi)
                    - jnp.log(std)[None]
                    - 0.5 * ((x - mean[None]) / std[None]) ** 2
                )                                                           # [n,Fc,K]
                lp = logpdf.sum(axis=1)
                pmean, pstd = tables["cont_prior_mean"], tables["cont_prior_std"]
                logpdf_pr = (
                    -0.5 * jnp.log(2 * jnp.pi)
                    - jnp.log(pstd)[None]
                    - 0.5 * ((x_cont - pmean[None]) / pstd[None]) ** 2
                )
                parts.append((lp, logpdf_pr.sum(axis=1)))
            log_feat_c = sum(p[0] for p in parts)
            log_feat = sum(p[1] for p in parts)
            log_post = log_feat_c + tables["log_class"][None, :] - log_feat[:, None]
            prob_pct = jnp.floor(jnp.exp(log_post) * 100.0).astype(jnp.int32)
            pred = jnp.argmax(prob_pct, axis=1)
            return pred, prob_pct

        self._predict = predict

    def predict(self, dataset: Dataset) -> Tuple[np.ndarray, np.ndarray]:
        codes, _ = dataset.feature_codes(self.model.binned_fields)
        x_cont = dataset.feature_matrix(self.model.cont_fields)
        pred, prob = self._predict(jnp.asarray(codes), jnp.asarray(x_cont),
                                   self.tables)
        pred, prob = np.asarray(pred), np.asarray(prob)
        if self.arbitrator is not None and len(self.model.class_values) == 2:
            neg = self.model.class_values.index(self.arbitrator.neg_class)
            pos = 1 - neg
            is_pos = self.arbitrator.arbitrate(prob[:, neg], prob[:, pos])
            pred = np.where(is_pos, pos, neg).astype(pred.dtype)
        return pred, prob

    def validate(self, dataset: Dataset, pos_class: int = 0) -> ConfusionMatrix:
        pred, _ = self.predict(dataset)
        cm = ConfusionMatrix(self.model.class_values, pos_class=pos_class)
        cm.add(dataset.labels(), pred)
        return cm

    def feature_prob(self, dataset: Dataset) -> np.ndarray:
        """Per-row P(features | actual class): the bap.output.feature.prob.only
        mode whose output the reference's KNN pipeline joins as
        class-conditional weights (BayesianPredictor.java:262-286)."""
        codes, _ = dataset.feature_codes(self.model.binned_fields)
        y = dataset.labels()
        logp = np.zeros(len(dataset), np.float64)
        if codes.shape[1]:
            lp = np.asarray(self.tables["log_post"])       # [F, K, B]
            for f in range(codes.shape[1]):
                logp += lp[f, y, codes[:, f]]
        x_cont = dataset.feature_matrix(self.model.cont_fields)
        if x_cont.shape[1]:
            mean = np.asarray(self.tables["cont_mean"])    # [Fc, K]
            std = np.asarray(self.tables["cont_std"])
            for f in range(x_cont.shape[1]):
                m, s = mean[f, y], std[f, y]
                logp += (-0.5 * np.log(2 * np.pi) - np.log(s)
                         - 0.5 * ((x_cont[:, f] - m) / s) ** 2)
        return np.exp(logp)
