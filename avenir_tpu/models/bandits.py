"""Batch multi-armed bandits: the MR bandit jobs, group-vectorized on device.

Reference (SURVEY §2.7): the batch side of org/avenir/reinforce/ is a set of
map-only MR jobs run once per decision round by a driver loop
(resource/price_optimize_tutorial.txt:20-82). Input rows are
(groupID, itemID, trialCount, avgReward); each mapper streams one group at a
time and selects `batch.size` items for that group:

- GreedyRandomBandit.java:148-310 — ε-greedy; per position i the effective
  trial count is (roundNum-1)*batchSize + i and the exploration probability
  decays linearly (prob*c/count) or log-linearly (prob*c*ln(count)/count),
  clamped at the base prob; "AuerGreedy" scales ε by d²-separation of the
  top two rewards.
- AuerDeterministic.java:130-175 — UCB1: untried items first, then by
  reward + confidence-radius value.
- RandomFirstGreedyBandit.java:55-120 — pure exploration for the first E
  rounds (E = factor*itemCount, or the PAC bound 4/d² + ln(2k/δ)), then
  greedy by rank.
- SoftMaxBandit.java:82-187 — Boltzmann sampling with temperature.

TPU-native design: one round over ALL groups is a single jitted call on
padded [G, A] arrays (counts, rewards, validity mask) — the group loop of
the mapper becomes the leading array axis, selection math vectorizes over
it, and `jax.random` drives exploration reproducibly. The between-rounds
reward-aggregate file (chombo RunningAggregator) stays a plain CSV via
GroupBanditData.from_rows / to_rows.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Dict, Iterable, List, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp

NEG = -1e30


# ---------------------------------------------------------------------------
# Group-padded round state (the reward-aggregate file between rounds)
# ---------------------------------------------------------------------------
@dataclass
class GroupBanditData:
    """Padded per-group item stats: the round input/output surface."""
    group_ids: List[str]
    item_ids: List[List[str]]       # per group, length = n items of group
    counts: np.ndarray              # int32 [G, A] trial counts (padded 0)
    rewards: np.ndarray             # float32 [G, A] avg rewards (padded 0)
    mask: np.ndarray                # bool [G, A] valid item slots

    @classmethod
    def from_rows(cls, rows: Iterable[Sequence[str]],
                  count_ord: int = 2, reward_ord: int = 3
                  ) -> "GroupBanditData":
        """Rows of (groupID, itemID, trialCount, avgReward) CSV fields,
        group-contiguous or not."""
        groups: Dict[str, List[Tuple[str, int, float]]] = {}
        order: List[str] = []
        for r in rows:
            g = r[0]
            if g not in groups:
                groups[g] = []
                order.append(g)
            groups[g].append((r[1], int(r[count_ord]), float(r[reward_ord])))
        a = max(len(v) for v in groups.values()) if groups else 0
        gn = len(order)
        counts = np.zeros((gn, a), np.int32)
        rewards = np.zeros((gn, a), np.float32)
        mask = np.zeros((gn, a), bool)
        item_ids = []
        for gi, g in enumerate(order):
            items = groups[g]
            item_ids.append([it[0] for it in items])
            for ai, (_, c, rw) in enumerate(items):
                counts[gi, ai] = c
                rewards[gi, ai] = rw
                mask[gi, ai] = True
        return cls(order, item_ids, counts, rewards, mask)

    def to_device(self) -> "GroupBanditData":
        """A copy whose stat arrays live on the device, making the
        per-round `jnp.asarray` in every select() a no-op.

        Uploading 3 x [G, A] arrays per round makes large-G selection
        transfer-bound; resident state eliminates the reference's analog
        cost (re-reading the reward-aggregate file in each round job).
        Deliberately a copy, not a cache: in-place edits of host arrays
        keep working on the original, with no staleness hazard."""
        return GroupBanditData(
            self.group_ids, self.item_ids, jnp.asarray(self.counts),
            jnp.asarray(self.rewards), jnp.asarray(self.mask))

    def write_selections(self, sel: np.ndarray, fh, delim: str = ",",
                         output_decision_count: bool = False) -> int:
        """Decode [G, B] selected indices to the reference's per-round
        output rows (GreedyRandomBandit.java:148-203) and write them to
        fh; returns rows written. Vectorized numpy decode when every
        group has the same item count (the map-only job's hot shape);
        falls back to selections_to_rows otherwise."""
        rect = (isinstance(self.item_ids, np.ndarray)
                and self.item_ids.ndim == 2) or \
            len({len(it) for it in self.item_ids}) == 1
        if output_decision_count or not rect:
            rows = self.selections_to_rows(sel, output_decision_count)
            for row in rows:
                fh.write(delim.join(row) + "\n")
            return len(rows)
        ids_arr = np.asarray(self.item_ids)                    # [G, A]
        g_arr = np.char.add(np.asarray(self.group_ids, dtype=str), delim)
        sel = np.asarray(sel)
        picks = ids_arr[np.arange(g_arr.shape[0])[:, None], sel]  # [G, B]
        lines = np.char.add(g_arr[:, None], picks).ravel()
        fh.write("\n".join(lines.tolist()) + "\n")
        return int(lines.shape[0])

    def selections_to_rows(self, sel: np.ndarray,
                           output_decision_count: bool = False
                           ) -> List[List[str]]:
        """[G, B] selected item indices -> output rows, reference format:
        (group, item) per pick, or (group, item, count) when counting
        (GreedyRandomBandit.java output modes)."""
        out: List[List[str]] = []
        for gi, g in enumerate(self.group_ids):
            picks = [self.item_ids[gi][int(ai)] for ai in sel[gi]
                     if int(ai) < len(self.item_ids[gi])]
            if output_decision_count:
                cnt: Dict[str, int] = {}
                for it in picks:
                    cnt[it] = cnt.get(it, 0) + 1
                out.extend([[g, it, str(c)] for it, c in cnt.items()])
            else:
                out.extend([[g, it] for it in picks])
        return out


# ---------------------------------------------------------------------------
# Jitted selection kernels, vectorized over groups
# ---------------------------------------------------------------------------
@partial(jax.jit, static_argnames=("batch_size", "log_linear", "unique"))
def _eps_greedy_kernel(key, rewards, mask, round_num,
                       base_prob, red_const, min_prob,
                       batch_size: int, log_linear: bool, unique: bool):
    """ε-greedy batch select per group (GreedyRandomBandit.linearSelect).

    Per position i the decayed ε uses count = (round-1)*B + i + 1; a greedy
    position takes the (next-)best reward, a random position a uniformly
    random valid item. `unique` walks down the reward order so a batch never
    repeats an item (selection.unique)."""
    g, a = rewards.shape

    def position(carry, i):
        key, taken = carry
        key, k1, k2 = jax.random.split(key, 3)
        count = (round_num - 1.0) * batch_size + i + 1.0
        if log_linear:
            p = base_prob * red_const * jnp.log(count) / count
        else:
            p = base_prob * red_const / count
        p = jnp.clip(p, min_prob, base_prob)
        explore = jax.random.uniform(k1, (g,)) < p
        # both paths draw from valid, not-yet-taken (when unique) slots;
        # when a group exhausts its items, fall back to the full mask
        avail = (mask & ~taken) if unique else mask
        avail = jnp.where(avail.any(axis=1, keepdims=True), avail, mask)
        rnd_pick = jax.random.categorical(
            k2, jnp.where(avail, 0.0, NEG), axis=1)
        greedy_pick = jnp.argmax(jnp.where(avail, rewards, NEG), axis=1)
        pick = jnp.where(explore, rnd_pick, greedy_pick)
        taken = taken.at[jnp.arange(g), pick].set(True)
        return (key, taken), pick

    init = (key, jnp.zeros_like(mask))
    _, picks = jax.lax.scan(position, init, jnp.arange(batch_size))
    return picks.T                                      # [G, B]


def _ranked_batch(score: jnp.ndarray, mask: jnp.ndarray,
                  batch_size: int) -> jnp.ndarray:
    """Top-`batch_size` valid indices by score per group; when the batch
    exceeds a group's valid item count, that group's ranked list repeats
    cyclically (padded slots are never picked)."""
    _, idx = jax.lax.top_k(score, score.shape[1])      # full rank, valid first
    n_valid = jnp.maximum(mask.sum(axis=1), 1)
    cols = jnp.arange(batch_size)[None, :] % n_valid[:, None]
    return jnp.take_along_axis(idx, cols, axis=1)


@partial(jax.jit, static_argnames=("batch_size",))
def _ucb1_kernel(counts, rewards, mask, round_num, max_reward,
                 batch_size: int):
    """Deterministic UCB1 (AuerDeterministic): untried items first (score
    +inf), then reward/maxReward + sqrt(2 ln t / n) — rewards normalize to
    [0, 1] so the confidence radius stays comparable to the value term
    (AuerDeterministic.java value scoring)."""
    t = jnp.maximum(round_num * batch_size, 2.0)
    n = counts.astype(jnp.float32)
    radius = jnp.sqrt(2.0 * jnp.log(t) / jnp.maximum(n, 1.0))
    score = jnp.where(n > 0, rewards / max_reward + radius, jnp.inf)
    score = jnp.where(mask, score, NEG)
    return _ranked_batch(score, mask, batch_size)       # [G, B]


@partial(jax.jit, static_argnames=("batch_size",))
def _softmax_kernel(key, rewards, mask, temp, batch_size: int):
    """Boltzmann batch sampling (SoftMaxBandit.java:187):
    p ∝ exp(reward / temp) over valid items, batch draws with replacement."""
    logits = jnp.where(mask, rewards / temp, NEG)
    g = rewards.shape[0]
    return jax.random.categorical(
        key, logits[:, None, :], axis=-1, shape=(g, batch_size))  # [G, B]


@partial(jax.jit, static_argnames=("batch_size",))
def _random_explore_kernel(key, mask, batch_size: int):
    """Uniform random batch over valid items (exploration rounds)."""
    logits = jnp.where(mask, 0.0, NEG)
    g = mask.shape[0]
    return jax.random.categorical(
        key, logits[:, None, :], axis=-1, shape=(g, batch_size))


# ---------------------------------------------------------------------------
# Round-job facades (the MR job analogs)
# ---------------------------------------------------------------------------
class GreedyRandomBandit:
    """ε-greedy round job (GreedyRandomBandit.java:49).

    Config keys mirror the reference: random.selection.prob,
    prob.reduction.constant, prob.reduction.algorithm (linear | logLinear |
    auerGreedy), current.round.num, selection.unique, min.prob."""

    def __init__(self, batch_size: int, random_selection_prob: float = 0.5,
                 prob_reduction_constant: float = 1.0,
                 prob_reduction_algorithm: str = "linear",
                 selection_unique: bool = False,
                 min_prob: float = 0.0,
                 auer_greedy_constant: float = 1.0,
                 seed: int = 0):
        self.batch_size = batch_size
        self.prob = random_selection_prob
        self.const = prob_reduction_constant
        self.algo = prob_reduction_algorithm
        self.unique = selection_unique
        self.min_prob = min_prob
        self.auer_const = auer_greedy_constant
        self.key = jax.random.PRNGKey(seed)

    def select(self, data: GroupBanditData, round_num: int) -> np.ndarray:
        self.key, sub = jax.random.split(self.key)
        if self.algo in ("linear", "logLinear"):
            rewards_d = jnp.asarray(data.rewards)
            mask_d = jnp.asarray(data.mask)
            picks = _eps_greedy_kernel(
                sub, rewards_d, mask_d, float(round_num),
                self.prob, self.const, self.min_prob,
                self.batch_size, self.algo == "logLinear", self.unique)
        elif self.algo == "auerGreedy":
            picks = self._auer_greedy(sub, data, round_num)
        else:
            raise ValueError(f"unknown prob reduction algorithm {self.algo}")
        return np.asarray(picks)

    def _auer_greedy(self, key, data: GroupBanditData, round_num: int):
        """AuerGreedy (GreedyRandomBandit.greedyAuerSelect): ε scaled by the
        relative gap d of the two best rewards, ε = c·k/(d²·t) capped at 1;
        untried items are taken first. All math on device so to_device()
        round state stays resident (no per-round host round trip)."""
        counts_d = jnp.asarray(data.counts)
        rewards_d = jnp.asarray(data.rewards)
        mask_d = jnp.asarray(data.mask)
        r = jnp.where(mask_d, rewards_d, -jnp.inf)
        if r.shape[1] > 1:
            top2 = jax.lax.top_k(r, 2)[0]
            best, second = top2[:, 0], top2[:, 1]
        else:
            best = second = r[:, 0]
        d = jnp.where(best > 0, (best - second) / jnp.maximum(best, 1e-9), 0.0)
        kcnt = mask_d.sum(axis=1)
        t = max((round_num - 1) * self.batch_size, 1)
        eps = jnp.where(
            d <= 0, 1.0,
            jnp.minimum(
                self.auer_const * kcnt / (jnp.maximum(d, 1e-9) ** 2 * t), 1.0),
        ).astype(jnp.float32)
        k1, k2 = jax.random.split(key)
        rnd = _random_explore_kernel(k1, mask_d, self.batch_size)
        # untried items come first (greedyAuerSelect collects not-tried
        # before value-ranked picks), then by reward
        greedy_score = jnp.where(counts_d > 0, rewards_d, jnp.inf)
        greedy_score = jnp.where(mask_d, greedy_score, NEG)
        greedy = _ranked_batch(greedy_score, mask_d, self.batch_size)
        explore = jax.random.uniform(
            k2, (mask_d.shape[0], self.batch_size)) < eps[:, None]
        return jnp.where(explore, rnd, greedy)


class AuerDeterministic:
    """UCB1 deterministic round job (AuerDeterministic.java:47).
    max_reward normalizes avg rewards into [0, 1] for the UCB score."""

    def __init__(self, batch_size: int, max_reward: float = 100.0):
        self.batch_size = batch_size
        self.max_reward = max_reward

    def select(self, data: GroupBanditData, round_num: int) -> np.ndarray:
        return np.asarray(_ucb1_kernel(
            jnp.asarray(data.counts), jnp.asarray(data.rewards),
            jnp.asarray(data.mask), float(round_num), self.max_reward,
            self.batch_size))


class RandomFirstGreedyBandit:
    """Explore-first-then-greedy round job (RandomFirstGreedyBandit.java:47).

    Exploration round count per group: simple = factor * itemCount, or the
    PAC bound 4/d² + ln(2k/δ) (getExplorationCount, :71-79)."""

    def __init__(self, batch_size: int,
                 expl_count_strategy: str = "simple",
                 exploration_count_factor: int = 2,
                 reward_diff: float = 0.1, prob_diff: float = 0.2,
                 seed: int = 0):
        self.batch_size = batch_size
        self.strategy = expl_count_strategy
        self.factor = exploration_count_factor
        self.reward_diff = reward_diff
        self.prob_diff = prob_diff
        self.key = jax.random.PRNGKey(seed)

    def exploration_rounds(self, item_count: int) -> int:
        if self.strategy == "simple":
            return self.factor * item_count
        return int(4.0 / (self.reward_diff ** 2)
                   + np.log(2.0 * item_count / self.prob_diff))

    def select(self, data: GroupBanditData, round_num: int) -> np.ndarray:
        self.key, sub = jax.random.split(self.key)
        rewards_d = jnp.asarray(data.rewards)
        mask_d = jnp.asarray(data.mask)
        rnd = np.asarray(_random_explore_kernel(sub, mask_d, self.batch_size))
        greedy_score = jnp.where(mask_d, rewards_d, NEG)
        greedy = np.asarray(_ranked_batch(greedy_score, mask_d,
                                          self.batch_size))
        expl = np.array([
            round_num <= self.exploration_rounds(len(items))
            for items in data.item_ids
        ])
        return np.where(expl[:, None], rnd, greedy)


class SoftMaxBandit:
    """Boltzmann round job (SoftMaxBandit.java:49)."""

    def __init__(self, batch_size: int, temp_constant: float = 1.0,
                 seed: int = 0):
        self.batch_size = batch_size
        self.temp = temp_constant
        self.key = jax.random.PRNGKey(seed)

    def select(self, data: GroupBanditData, round_num: int) -> np.ndarray:
        self.key, sub = jax.random.split(self.key)
        return np.asarray(_softmax_kernel(
            sub, jnp.asarray(data.rewards), jnp.asarray(data.mask),
            self.temp, self.batch_size))


def make_bandit_job(name: str, batch_size: int, **kw):
    """Round-job factory by the reference's job/algorithm names."""
    table = {
        "greedyRandomBandit": GreedyRandomBandit,
        "auerDeterministic": AuerDeterministic,
        "randomFirstGreedyBandit": RandomFirstGreedyBandit,
        "softMaxBandit": SoftMaxBandit,
    }
    if name not in table:
        raise ValueError(f"invalid bandit job: {name}")
    return table[name](batch_size, **kw)
