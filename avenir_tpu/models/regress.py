"""Logistic regression: jitted full-batch gradient loop.

Reference (regress/LogisticRegressionJob.java:51, SURVEY §3.6): each MR
iteration accumulates batch gradient aggregates in mappers, appends the new
coefficient row to coeff.file.path, and signals convergence through process
exit codes (CONVERGED=100/NOT_CONVERGED=101) checked by an external driver
loop; criteria are iterLimit / all coeff diffs below threshold / average
below threshold (:95-119).

Here the whole driver loop is in-process: one jitted step computes the
sigmoid gradient over the full (device-resident) batch, the coefficient
history is kept (and optionally written in the same one-row-per-iteration
file format), and the same three convergence criteria apply.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from avenir_tpu.core.dataset import Dataset
from avenir_tpu.utils.metrics import ConfusionMatrix

CONVERGED = 100
NOT_CONVERGED = 101


@jax.jit
def _lr_grad(coeff, x, y, w=None):
    """Unnormalized log-likelihood gradient x^T((y - sigmoid(xc)) * w) —
    the shared core of the single-device and shard_map LR steps."""
    r = y - jax.nn.sigmoid(x @ coeff)
    if w is not None:
        r = r * w
    return x.T @ r


def _lr_step(coeff, x, y, lr, n_eff=None):
    """One full-batch gradient ascent step on the log likelihood.
    `n_eff` overrides the row normalizer when x carries zero padding rows
    (mesh shard divisibility — padded rows contribute 0 to the gradient)."""
    grad = _lr_grad(coeff, x, y) / (n_eff if n_eff is not None else x.shape[0])
    return coeff + lr * grad, grad


class LogisticRegression:
    """Binary logistic regression over numeric features (+ intercept)."""

    def __init__(
        self,
        learning_rate: float = 1.0,
        iteration_limit: int = 10,
        convergence_criteria: str = "iterLimit",   # allBelowThreshold / averageBelowThreshold
        convergence_threshold: float = 5.0,
        pos_class: Optional[str] = None,
    ):
        self.lr = learning_rate
        self.iter_limit = iteration_limit
        self.criteria = convergence_criteria
        self.threshold = convergence_threshold
        self.pos_class = pos_class
        self.coeff_history: List[np.ndarray] = []

    # ---------------------------------------------------------------- data
    def _design(self, ds: Dataset) -> Tuple[jnp.ndarray, jnp.ndarray]:
        x = ds.feature_matrix().astype(np.float64)
        # standardize for stable full-batch gradient steps (the raw-feature
        # gradient diverges on wide-range columns; deviation from the
        # reference, which leaves scaling to the user)
        if not hasattr(self, "_mu"):
            self._mu = x.mean(axis=0)
            self._sigma = np.maximum(x.std(axis=0), 1e-9)
        x = (x - self._mu) / self._sigma
        x = np.concatenate([np.ones((len(ds), 1)), x], axis=1).astype(np.float32)
        y = ds.labels().astype(np.float32)
        if self.pos_class is not None:
            pi = ds.schema.class_values().index(self.pos_class)
            y = (ds.labels() == pi).astype(np.float32)
        return jnp.asarray(x), jnp.asarray(y)

    # ----------------------------------------------------------------- fit
    def fit(self, ds: Dataset, mesh=None) -> "LogisticRegression":
        """Full-batch gradient epochs. With `mesh`, the design matrix shards
        over the mesh rows and XLA psums the per-shard gradient halves —
        the reference's mapper-aggregate/reducer round (SURVEY §3.6) as one
        collective per epoch."""
        x, y = self._design(ds)
        n_eff = x.shape[0]
        if mesh is not None:
            from avenir_tpu.parallel.mesh import shard_rows

            x = shard_rows(mesh, np.asarray(x))
            y = shard_rows(mesh, np.asarray(y))
        coeff = jnp.zeros((x.shape[1],), jnp.float32)
        self.coeff_history = [np.asarray(coeff)]
        for _ in range(self.iter_limit):
            coeff, _ = _lr_step(coeff, x, y, self.lr, n_eff)
            self.coeff_history.append(np.asarray(coeff))
            if self.check_convergence() == CONVERGED:
                break
        self.coeff = np.asarray(coeff)
        return self

    def check_convergence(self) -> int:
        """Reference exit-code semantics (LogisticRegressionJob.java:95-119).
        Threshold criteria compare coefficient change in percent terms."""
        lines = self.coeff_history
        if self.criteria == "iterLimit":
            return NOT_CONVERGED if len(lines) - 1 < self.iter_limit else CONVERGED
        if len(lines) < 2:
            return NOT_CONVERGED
        prev, cur = lines[-2], lines[-1]
        denom = np.maximum(np.abs(prev), 1e-9)
        diff_pct = np.abs(cur - prev) / denom * 100.0
        if self.criteria == "allBelowThreshold":
            ok = bool((diff_pct < self.threshold).all())
        elif self.criteria == "averageBelowThreshold":
            ok = bool(diff_pct.mean() < self.threshold)
        else:
            raise ValueError(f"invalid convergence criteria {self.criteria}")
        return CONVERGED if ok else NOT_CONVERGED

    # ------------------------------------------------------------- file IO
    def save_coeff_history(self, path: str, delim: str = ",") -> None:
        """coeff.file.path format: one coefficient row per iteration."""
        with open(path, "w") as fh:
            for row in self.coeff_history:
                fh.write(delim.join(f"{v:.6f}" for v in row) + "\n")

    @classmethod
    def load_coeff(cls, path: str, delim: str = ",") -> np.ndarray:
        with open(path) as fh:
            lines = [ln for ln in fh.read().splitlines() if ln.strip()]
        return np.array([float(v) for v in lines[-1].split(delim)])

    # ------------------------------------------------------------- predict
    def predict_proba(self, ds: Dataset) -> np.ndarray:
        x, _ = self._design(ds)
        return np.asarray(jax.nn.sigmoid(x @ jnp.asarray(self.coeff)))

    def predict(self, ds: Dataset, threshold: float = 0.5) -> np.ndarray:
        return (self.predict_proba(ds) >= threshold).astype(np.int32)

    def validate(self, ds: Dataset, pos_class_idx: int = 1) -> ConfusionMatrix:
        y = ds.labels()
        if self.pos_class is not None:
            pi = ds.schema.class_values().index(self.pos_class)
            y = (y == pi).astype(np.int32)
            pos_class_idx = 1
        cm = ConfusionMatrix(["neg", "pos"], pos_class=pos_class_idx)
        cm.add(y, self.predict(ds))
        return cm
