"""Exploration / feature-selection suite (org.avenir.explore re-designed).

Every job in the reference package is a contingency-table or moment
reduction over records: mutual information + selection scores
(MutualInformation.java, MutualInformationScore.java), Cramér / categorical
/ heterogeneity-reduction / numerical correlation, Relief feature relevance,
per-value class affinity, supervised categorical->continuous encoding,
class-balancing samplers. On TPU each is one or two one-hot einsum
contractions (cross_count) producing small count tensors, with the greedy
selection loops on host over those tiny tables.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from avenir_tpu.core.dataset import Dataset
from avenir_tpu.core.schema import FeatureField
from avenir_tpu.ops.infotheory import (bits_entropy, entropy, gini,
                                       mutual_information,
                                       weighted_split_score)
from avenir_tpu.ops.reduce import cross_count, keyed_reduce

_EPS = 1e-12
# fused MI chunk keys are int32: past this keyspace they would wrap, so
# add() drops to per-pair cross_counts (each in its own small keyspace)
_FUSED_KEYSPACE_LIMIT = 2**31


def _padded_add(acc: Optional[np.ndarray], new: np.ndarray) -> np.ndarray:
    """acc + new where either may be smaller along any axis (growing
    data-discovered vocabularies); missing cells are zero counts."""
    if acc is None:
        return new
    if acc.shape == new.shape:
        return acc + new
    shape = tuple(max(a, b) for a, b in zip(acc.shape, new.shape))
    out = np.zeros(shape, np.float64)
    out[tuple(slice(0, s) for s in acc.shape)] += acc
    out[tuple(slice(0, s) for s in new.shape)] += new
    return out


# ---------------------------------------------------------------------------
# mutual information + feature selection scores
# ---------------------------------------------------------------------------


@partial(jax.jit, static_argnames=("bmax", "k", "nf"))
def _mi_chunk_counts(codes, y, bmax: int, k: int, nf: int):
    """One chunk's complete MI count-table set in three keyed reductions:
    fc [F, bmax, k], pair [P, bmax, bmax] and pairc [P, bmax, bmax, k],
    P = F(F-1)/2 in upper-triangle order. int32 counts (exact to 2^31);
    peak memory is the [n, P] key tensor — pair analysis is inherently
    O(F^2) work either way, this shape just buys it with 3 dispatches
    instead of F^2. Caller guarantees the fused keyspace
    P*bmax^2*k < 2^31 (add() falls back to per-pair cross_count past
    that; int keys would wrap)."""
    n = codes.shape[0]

    def count(keys, num):
        return keyed_reduce(keys.reshape(-1),
                            jnp.ones((keys.size,), jnp.int32), num)

    f_idx = jnp.arange(nf, dtype=jnp.int32)[None, :]
    fc = count((f_idx * bmax + codes) * k + y[:, None],
               nf * bmax * k).reshape(nf, bmax, k)
    ii, jj = np.triu_indices(nf, 1)            # static under jit
    npair = len(ii)
    if npair == 0:
        return (fc, jnp.zeros((0, bmax, bmax), jnp.int32),
                jnp.zeros((0, bmax, bmax, k), jnp.int32))
    ci, cj = codes[:, ii], codes[:, jj]        # [n, P]
    p_idx = jnp.arange(npair, dtype=jnp.int32)[None, :]
    key_p = (p_idx * bmax + ci) * bmax + cj
    pair = count(key_p, npair * bmax * bmax).reshape(npair, bmax, bmax)
    pairc = count(key_p * k + y[:, None],
                  npair * bmax * bmax * k).reshape(npair, bmax, bmax, k)
    return fc, pair, pairc


def _mi_chunk_counts_host(codes, y, bmax: int, k: int, nf: int):
    """_mi_chunk_counts in host numpy. XLA:CPU lowers segment_sum to a
    SERIAL per-element scatter — ~3s per 1.2M-row chunk on a laptop-class
    core, 50x the parse cost, which made MI the limiter of the CPU
    streaming proxies (and of the shared-scan fan-out, where its fold
    shares the scan with NB + discriminant). Here each table is one
    np.bincount over a small per-table int32 keyspace: per-PAIR keys (no
    fused [n, P] key tensor — the giant temporaries, not the counting,
    dominate host time), vectorized and exact. The device kernel fuses
    pairs because a dispatch costs ~fixed latency; a numpy call doesn't.
    Counts are integers, so both paths produce bit-identical tables and
    chunk-layout invariance is unaffected."""
    codes = np.ascontiguousarray(codes, np.int32)
    y = np.asarray(y, np.int32)
    # one [n, F] class-fused key tensor: column f's key code*k + y IS
    # the fc table key and the low digits of every pair-class key, so
    # each pair costs one add + one bincount; the class-marginal pair
    # table is the exact integer sum of pairc over the class axis —
    # not a second bincount pass over n
    cy = codes * np.int32(k) + y[:, None]                       # [n, F]
    fc = np.empty((nf, bmax, k), np.int64)
    for f in range(nf):
        fc[f] = np.bincount(cy[:, f],
                            minlength=bmax * k).reshape(bmax, k)
    npair = nf * (nf - 1) // 2
    pair = np.empty((npair, bmax, bmax), np.int64)
    pairc = np.empty((npair, bmax, bmax, k), np.int64)
    p = 0
    for i in range(nf):
        ci_bk = codes[:, i] * np.int32(bmax * k)
        for j in range(i + 1, nf):
            pairc[p] = np.bincount(
                ci_bk + cy[:, j],
                minlength=bmax * bmax * k).reshape(bmax, bmax, k)
            pair[p] = pairc[p].sum(axis=2)
            p += 1
    return fc, pair, pairc


class MutualInformationAnalyzer:
    """MutualInformation MR job equivalent (MutualInformation.java:62).

    One device pass builds all the distributions the reducer held in memory
    (class, feature, feature-pair, feature-class, feature-pair-class,
    MutualInformation.java:138-216); the score algorithms are the greedy
    loops of MutualInformationScore.java over those tables:
      mutual.info.maximization (MIM)        :98
      mutual.info.selection (MIFS, beta)    :116-140
      joint.mutual.info (JMI)               :177
      double.input.symmetric.relevance(DISR):185-229
      min.redundancy.max.relevance (mRMR)   :265-288
    MI values are in nats (reference uses log base e via Math.log).
    """

    def __init__(self, ds: Optional[Dataset] = None):
        self.ds = ds
        self.fields: Optional[List[FeatureField]] = None
        self.bins: List[int] = []
        self.k = 0
        self.n = 0
        self._fc: List[np.ndarray] = []            # per f: [Bf, K]
        self._pair: Dict[Tuple[int, int], np.ndarray] = {}   # [Bi, Bj]
        self._pairc: Dict[Tuple[int, int], np.ndarray] = {}  # [Bi, Bj, K]
        if ds is not None:
            self.add(ds)
            self.finalize()

    @classmethod
    def from_chunks(cls, chunks) -> "MutualInformationAnalyzer":
        """Build from streamed Dataset chunks: every distribution the
        reducer held (MutualInformation.java:138-216) is an additive count
        tensor, so folding per-chunk cross_counts yields bit-identical
        tables to the whole-file pass at O(chunk) host RSS."""
        self = cls()
        for ds in chunks:
            self.add(ds)
        if self.fields is None:
            raise ValueError("no input chunks")
        self.finalize()
        return self

    def add(self, ds: Dataset) -> None:
        """Fold one chunk's contingency counts into the running tables.
        Data-discovered categorical vocabularies may extend between chunks
        (the shared-schema contract of CsvBlockReader); accumulated tables
        zero-pad along the grown bin axes.

        All F feature-class tables and both F(F-1)/2 pair-table families
        come out of THREE keyed segment_sums per chunk (bin axes padded to
        the chunk's max bin count) — not one dispatch per table, which is
        what makes the streaming path tunnel-latency-proof on device."""
        if self.fields is None:
            self.fields = ds.encodable_feature_fields()
            self.k = ds.schema.num_classes()
            F = len(self.fields)
            self.bins = [0] * F
            self._fc = [np.zeros((0, self.k), np.float64) for _ in range(F)]
        codes, bins = ds.feature_codes(self.fields)
        F = len(self.fields)
        self.bins = [max(a, b) for a, b in zip(self.bins, bins)]
        bmax = max(bins) if bins else 1
        fused_keys = (F * (F - 1) // 2) * bmax * bmax * self.k
        if fused_keys < _FUSED_KEYSPACE_LIMIT:
            # device segment_sums on accelerators; vectorized bincount on
            # CPU hosts (XLA:CPU scatter is serial — see the host fn).
            # Integer counts: both produce bit-identical tables.
            if jax.default_backend() == "cpu":
                kernel, codes_a, y_a = (_mi_chunk_counts_host, codes,
                                        ds.labels())
            else:
                kernel = _mi_chunk_counts
                codes_a, y_a = jnp.asarray(codes), jnp.asarray(ds.labels())
            fc, pair, pairc = (np.asarray(a, np.float64) for a in
                               kernel(codes_a, y_a, bmax, self.k, F))
            p = 0
            for i in range(F):
                self._fc[i] = _padded_add(self._fc[i], fc[i, :bins[i]])
                for j in range(i + 1, F):
                    bi, bj = bins[i], bins[j]
                    self._pair[(i, j)] = _padded_add(
                        self._pair.get((i, j)), pair[p, :bi, :bj])
                    self._pairc[(i, j)] = _padded_add(
                        self._pairc.get((i, j)), pairc[p, :bi, :bj])
                    p += 1
        else:
            # fused int32 keys would wrap (many features x huge bin
            # counts): per-pair cross_counts, each in its own keyspace
            codes_d = jnp.asarray(codes)
            y = jnp.asarray(ds.labels())
            for f in range(F):
                self._fc[f] = _padded_add(self._fc[f], np.asarray(
                    cross_count(codes_d[:, f], y, bins[f], self.k),
                    np.float64))
            for i in range(F):
                for j in range(i + 1, F):
                    bi, bj = bins[i], bins[j]
                    self._pair[(i, j)] = _padded_add(
                        self._pair.get((i, j)), np.asarray(
                            cross_count(codes_d[:, i], codes_d[:, j],
                                        bi, bj), np.float64))
                    comb = codes_d[:, i] * bj + codes_d[:, j]
                    self._pairc[(i, j)] = _padded_add(
                        self._pairc.get((i, j)), np.asarray(
                            cross_count(comb, y, bi * bj, self.k),
                            np.float64).reshape(bi, bj, self.k))
        self.n += len(ds)

    def merge(self, other: "MutualInformationAnalyzer"
              ) -> "MutualInformationAnalyzer":
        """Fold another analyzer's count tables into this one — the
        NaiveBayesModel.merge algebra for MI: every table is an additive
        integer-count tensor, so ``merge(add(A), add(B))`` equals
        ``add(A ++ B)`` exactly (the shard-merge contract graftlint
        --merge proves mechanically). Both sides must be un-finalized
        partial fits over the same feature set; an empty `other` (no
        chunks seen) merges as a no-op, and an empty `self` adopts
        `other`'s state. Grown data-discovered vocabularies zero-pad
        along the bin axes, exactly like chunked add()."""
        if other.fields is None:
            return self
        if self.fields is None:
            self.fields = other.fields
            self.k = other.k
            self.bins = [0] * len(other.fields)
            self._fc = [np.zeros((0, self.k), np.float64)
                        for _ in other.fields]
        if self.k != other.k or [f.ordinal for f in self.fields] != \
                [f.ordinal for f in other.fields]:
            raise ValueError(
                "cannot merge MI analyzers over different feature sets "
                "or class counts")
        self.bins = [max(a, b) for a, b in zip(self.bins, other.bins)]
        for i in range(len(self.fields)):
            self._fc[i] = _padded_add(self._fc[i], other._fc[i])
        for key, tbl in other._pair.items():
            self._pair[key] = _padded_add(self._pair.get(key), tbl)
        for key, tbl in other._pairc.items():
            self._pairc[key] = _padded_add(self._pairc.get(key), tbl)
        self.n += other.n
        return self

    def finalize(self) -> None:
        """Derive all MI statistics from the accumulated count tables."""
        F = len(self.bins)
        self.feature_class_mi = np.zeros(F)
        self.pair_mi = np.zeros((F, F))
        self.pair_class_mi = np.zeros((F, F))
        self.pair_class_entropy = np.zeros((F, F))
        for f in range(F):
            self.feature_class_mi[f] = float(
                mutual_information(jnp.asarray(self._fc[f])))
        for (i, j), joint_ij in self._pair.items():
            mi_ij = float(mutual_information(jnp.asarray(joint_ij)))
            self.pair_mi[i, j] = self.pair_mi[j, i] = mi_ij
        for (i, j), joint_ijc in self._pairc.items():
            flat = jnp.asarray(joint_ijc.reshape(-1, self.k))
            mic = float(mutual_information(flat))
            self.pair_class_mi[i, j] = self.pair_class_mi[j, i] = mic
            h = float(entropy(flat.reshape(-1), axis=-1))
            self.pair_class_entropy[i, j] = self.pair_class_entropy[j, i] = h

    # ------------------------------------------------------------- scores
    def _ordinals(self) -> List[int]:
        return [f.ordinal for f in self.fields]

    def mim(self) -> List[Tuple[int, float]]:
        """Max relevance: features sorted by I(Xf; C) descending."""
        order = np.argsort(-self.feature_class_mi)
        ords = self._ordinals()
        return [(ords[i], float(self.feature_class_mi[i])) for i in order]

    def mifs(self, redundancy_factor: float = 1.0) -> List[Tuple[int, float]]:
        """Greedy: score = I(Xf;C) - beta * sum_{s in selected} I(Xf;Xs)."""
        F = len(self.bins)
        selected: List[int] = []
        out = []
        while len(selected) < F:
            best, best_score = -1, -np.inf
            for f in range(F):
                if f in selected:
                    continue
                red = sum(self.pair_mi[f, s] for s in selected)
                score = self.feature_class_mi[f] - redundancy_factor * red
                if score > best_score:
                    best, best_score = f, score
            selected.append(best)
            out.append((self._ordinals()[best], float(best_score)))
        return out

    def _jmi_helper(self, joint: bool) -> List[Tuple[int, float]]:
        F = len(self.bins)
        first = int(np.argmax(self.feature_class_mi))
        selected = [first]
        out = [(self._ordinals()[first], float(self.feature_class_mi[first]))]
        while len(selected) < F:
            best, best_score = -1, -np.inf
            for f in range(F):
                if f in selected:
                    continue
                if joint:
                    s_sum = sum(self.pair_class_mi[f, s] for s in selected)
                else:
                    s_sum = sum(
                        self.pair_class_mi[f, s]
                        / max(self.pair_class_entropy[f, s], _EPS)
                        for s in selected
                    )
                if s_sum > best_score:
                    best, best_score = f, s_sum
            selected.append(best)
            out.append((self._ordinals()[best], float(best_score)))
        return out

    def jmi(self) -> List[Tuple[int, float]]:
        """Joint mutual information selection."""
        return self._jmi_helper(True)

    def disr(self) -> List[Tuple[int, float]]:
        """Double-input symmetric relevance (JMI normalized by pair entropy)."""
        return self._jmi_helper(False)

    def mrmr(self) -> List[Tuple[int, float]]:
        """Greedy: score = I(Xf;C) - mean_{s in selected} I(Xf;Xs)."""
        F = len(self.bins)
        selected: List[int] = []
        out = []
        while len(selected) < F:
            best, best_score = -1, -np.inf
            for f in range(F):
                if f in selected:
                    continue
                red = sum(self.pair_mi[f, s] for s in selected)
                score = (
                    self.feature_class_mi[f] - red / len(selected)
                    if selected else self.feature_class_mi[f]
                )
                if score > best_score:
                    best, best_score = f, score
            selected.append(best)
            out.append((self._ordinals()[best], float(best_score)))
        return out

    def score(self, algorithm: str, redundancy_factor: float = 1.0):
        """Dispatch by the reference's mut.* algorithm names."""
        return {
            "mutual.info.maximization": self.mim,
            "mutual.info.selection": lambda: self.mifs(redundancy_factor),
            "joint.mutual.info": self.jmi,
            "double.input.symmetric.relevance": self.disr,
            "min.redundancy.max.relevance": self.mrmr,
        }[algorithm]()


# ---------------------------------------------------------------------------
# candidate-split class partition stats
# ---------------------------------------------------------------------------
class ClassPartitionGenerator:
    """Candidate-split class-histogram stats — the older two-job tree flow's
    first stage (explore/ClassPartitionGenerator.java:61, cpg.* keys).

    For every candidate split of the requested attributes, one device
    segment_sum produces the [segment, class] histogram; the split stat is
    computed per cpg.split.algorithm: `entropy` / `giniIndex` (weighted
    child info content, lower = better) or `hellingerDistance`
    (AttributeSplitStat.java:228-283, higher = better, binary class only).
    """

    def __init__(self, ds: Dataset, attributes: Optional[Sequence[int]] = None,
                 algorithm: str = "giniIndex", cat_partition_cap: int = 128):
        from avenir_tpu.models.tree import enumerate_splits

        self.ds = ds
        self.algorithm = algorithm
        splits = enumerate_splits(ds.schema, cat_partition_cap)
        if attributes is not None:
            attrs = set(attributes)
            splits = [s for s in splits if s.attribute in attrs]
        self.splits = splits
        self.k = ds.schema.num_classes()
        self.histograms = self._histograms()

    def _histograms(self) -> List[np.ndarray]:
        """Per split: [n_segments, k] class counts — the tree level
        histogram kernel with a single root leaf."""
        from avenir_tpu.models.tree import _level_histogram

        if not self.splits:
            return []
        n = len(self.ds)
        smax = max(s.n_segments for s in self.splits)
        seg = np.stack(
            [s.segment_of(np.asarray(self.ds.column(s.attribute)))
             for s in self.splits], axis=1,
        ).astype(np.int8)                                    # [n, NS]
        hists = np.asarray(_level_histogram(
            jnp.zeros(n, jnp.int32), jnp.asarray(seg),
            jnp.asarray(self.ds.labels()), jnp.ones(n, jnp.float32),
            1, len(self.splits), smax, self.k,
        ))[0]                                                # [NS, smax, k]
        return [hists[i, : s.n_segments] for i, s in enumerate(self.splits)]

    def split_stats(self) -> List[Tuple[object, float]]:
        """(CandidateSplit, stat) per candidate, computed per algorithm."""
        out = []
        for s, h in zip(self.splits, self.histograms):
            if self.algorithm == "hellingerDistance":
                if self.k != 2:
                    raise ValueError("Hellinger distance algorithm is only "
                                     "valid for binary valued class attributes")
                tot = np.maximum(h.sum(axis=0), _EPS)        # per-class totals
                d = np.sqrt(h[:, 0] / tot[0]) - np.sqrt(h[:, 1] / tot[1])
                stat = float(np.sqrt((d * d).sum()))
            else:
                stat = float(weighted_split_score(jnp.asarray(h), self.algorithm))
            out.append((s, stat))
        return out

    def best_split(self):
        """(CandidateSplit, stat): max stat for Hellinger, min info content
        for entropy/gini."""
        stats = self.split_stats()
        pick = max if self.algorithm == "hellingerDistance" else min
        return pick(stats, key=lambda t: t[1])


# ---------------------------------------------------------------------------
# correlations
# ---------------------------------------------------------------------------


def contingency(ds: Dataset, fld: FeatureField) -> np.ndarray:
    """[Bf, K] feature-value x class count table (one one-hot matmul)."""
    codes, _ = ds.feature_codes([fld])
    return np.asarray(cross_count(
        jnp.asarray(codes[:, 0]), jnp.asarray(ds.labels()),
        fld.num_bins(), ds.schema.num_classes(),
    ))


def cramer_index(table: np.ndarray) -> float:
    """Cramér index V^2 = chi2 / (n * min(r-1, c-1))
    (CramerCorrelation.java via chombo ContingencyMatrix)."""
    n = table.sum()
    if n == 0:
        return 0.0
    row = table.sum(axis=1, keepdims=True)
    col = table.sum(axis=0, keepdims=True)
    expected = row @ col / n
    chi2 = float(np.where(expected > 0,
                          (table - expected) ** 2 / np.maximum(expected, _EPS),
                          0.0).sum())
    r, c = table.shape
    denom = n * max(min(r - 1, c - 1), 1)
    return chi2 / denom


class ContingencyAccumulator:
    """Streaming per-field feature-value x class contingency tables.

    The whole correlation family (Cramér, categorical, heterogeneity
    reduction) is a function of these [B, K] tables, and the tables are
    additive over records — the reference's mapper/combiner/reducer count
    algebra (CramerCorrelation.java:54) at chunk granularity. The bin axis
    grows in place as data-discovered vocabularies extend between chunks."""

    def __init__(self):
        self.fields: Optional[List[FeatureField]] = None
        self.tables: Dict[int, np.ndarray] = {}      # ordinal -> [B, K]
        self.class_counts: Optional[np.ndarray] = None
        self.k = 0
        self.n = 0

    def add(self, ds: Dataset) -> None:
        if self.fields is None:
            self.fields = [f for f in ds.schema.feature_fields
                           if f.num_bins() > 0]
            self.k = ds.schema.num_classes()
            self.class_counts = np.zeros(self.k, np.float64)
        y = ds.labels()
        self.class_counts += np.bincount(y, minlength=self.k)
        if self.fields:
            codes, bins = ds.feature_codes(self.fields)
            codes_d = jnp.asarray(codes)
            yd = jnp.asarray(y)
            for i, f in enumerate(self.fields):
                tab = np.asarray(
                    cross_count(codes_d[:, i], yd, bins[i], self.k),
                    np.float64)
                self.tables[f.ordinal] = _padded_add(
                    self.tables.get(f.ordinal), tab)
        self.n += len(ds)

    def cramer(self) -> Dict[int, float]:
        return {o: cramer_index(t) for o, t in sorted(self.tables.items())}

    def heterogeneity(self, algo: str = "entropy") -> Dict[int, float]:
        imp_fn = bits_entropy if algo == "entropy" else gini
        base = float(np.asarray(imp_fn(jnp.asarray(self.class_counts))))
        out = {}
        for o, tab in sorted(self.tables.items()):
            seg_tot = tab.sum(axis=1)
            seg_imp = np.asarray(imp_fn(jnp.asarray(tab), axis=-1))
            cond = float((seg_tot / max(seg_tot.sum(), _EPS) * seg_imp).sum())
            out[o] = (base - cond) / max(base, _EPS)
        return out


class NumericMomentAccumulator:
    """Streaming Pearson moments (n, sum, cross-products) over the numeric
    features + numeric-coded class (NumericalCorrelation.java:48). The
    correlation matrix from raw moments equals np.corrcoef's (the
    normalization factor cancels in the ratio)."""

    def __init__(self):
        self.n = 0
        self.s: Optional[np.ndarray] = None
        self.ss: Optional[np.ndarray] = None

    def add(self, ds: Dataset) -> None:
        x = ds.feature_matrix()
        y = ds.labels().astype(np.float32)[:, None]
        m = np.concatenate([x, y], axis=1).astype(np.float64)
        if self.s is None:
            d = m.shape[1]
            self.s = np.zeros(d, np.float64)
            self.ss = np.zeros((d, d), np.float64)
        self.n += m.shape[0]
        self.s += m.sum(axis=0)
        self.ss += m.T @ m

    def correlation(self) -> np.ndarray:
        mean = self.s / max(self.n, 1)
        cov = self.ss / max(self.n, 1) - np.outer(mean, mean)
        sd = np.sqrt(np.clip(np.diag(cov), _EPS, None))
        return cov / np.outer(sd, sd)


def cramer_correlation(ds: Dataset) -> Dict[int, float]:
    """Per-categorical-feature Cramér index against the class attribute."""
    acc = ContingencyAccumulator()
    acc.add(ds)
    return acc.cramer()


def heterogeneity_reduction(ds: Dataset, algo: str = "entropy") -> Dict[int, float]:
    """Proportional impurity reduction of the class by each feature
    (HeterogeneityReductionCorrelation.java:38):
    (imp(C) - sum_b p(b) imp(C|b)) / imp(C)."""
    acc = ContingencyAccumulator()
    acc.add(ds)
    return acc.heterogeneity(algo)


def numerical_correlation(ds: Dataset) -> np.ndarray:
    """Pearson correlation matrix over numeric features + numeric-coded
    class, via a single moment pass (NumericalCorrelation.java:48)."""
    acc = NumericMomentAccumulator()
    acc.add(ds)
    return acc.correlation()


# ---------------------------------------------------------------------------
# Relief feature relevance
# ---------------------------------------------------------------------------


def relief_relevance(
    ds: Dataset,
    sample_size: Optional[int] = None,
    seed: int = 0,
    block: int = 8192,
    query_block: int = 8192,
) -> Dict[int, float]:
    """Relief: w_f += diff_f(x, nearest miss) - diff_f(x, nearest hit),
    averaged over sampled records (ReliefFeatureRelevance.java:49).

    Device-scale: nearest hit/miss come from per-class blocked streaming
    top-k (ops.distance.blocked_topk_neighbors) with query chunking, so
    peak memory is O(query_block x block) — never the [m, m] diff
    matrices. The per-attribute-averaged manhattan metric of the search
    is relief's own mean of range-normalized diffs, so hit/miss selection
    is unchanged; the final per-feature weights evaluate those diffs only
    at the selected (record, hit/miss) pairs. Ranges use the schema's
    min/max with a data-derived fallback, as the reference's metric."""
    from avenir_tpu.ops.distance import blocked_topk_neighbors, pad_train

    n = len(ds)
    rng = np.random.default_rng(seed)
    idx = (np.arange(n) if sample_size is None or sample_size >= n
           else rng.choice(n, sample_size, replace=False))
    sub = ds.take(idx)
    y = sub.labels()
    m = len(sub)
    k_classes = ds.schema.num_classes()

    num_fields = [f for f in ds.schema.feature_fields if f.is_numeric]
    cat_fields = [f for f in ds.schema.feature_fields if f.is_categorical]
    num_cols, ranges = [], []
    for f in num_fields:
        col = sub.column(f.ordinal).astype(np.float32)
        rngf = (f.max - f.min) if f.max is not None and f.min is not None else (
            float(col.max() - col.min()) or 1.0)
        num_cols.append(col)
        ranges.append(max(rngf, _EPS))
    x_num = (np.stack(num_cols, axis=1) if num_cols
             else np.zeros((m, 0), np.float32))
    ranges_arr = np.asarray(ranges, np.float32)
    if cat_fields:
        x_cat = np.stack([sub.column(f.ordinal).astype(np.int32)
                          for f in cat_fields], axis=1)
        bins = tuple(len(f.cardinality) for f in cat_fields)
    else:
        x_cat, bins = None, None

    # nearest neighbor of every record within each class (self excluded)
    best_d = np.full((m, k_classes), np.inf, np.float32)
    best_i = np.zeros((m, k_classes), np.int64)
    q_num_j = jnp.asarray(x_num) if x_num.shape[1] else None
    q_cat_j = jnp.asarray(x_cat) if x_cat is not None else None
    rng_j = jnp.asarray(ranges_arr) if ranges_arr.size else None
    for ki in range(k_classes):
        rows_c = np.flatnonzero(y == ki)
        if len(rows_c) == 0:
            continue
        blk = min(block, len(rows_c))
        t_num, t_cat, n_valid = pad_train(
            x_num[rows_c] if x_num.shape[1] else None,
            x_cat[rows_c] if x_cat is not None else None, blk)
        kk = min(2, len(rows_c))
        t_num_j = jnp.asarray(t_num) if t_num is not None else None
        t_cat_j = jnp.asarray(t_cat) if t_cat is not None else None
        for qs in range(0, m, query_block):
            qe = min(qs + query_block, m)
            dist, nidx = blocked_topk_neighbors(
                q_num_j[qs:qe] if q_num_j is not None else None,
                t_num_j,
                q_cat_j[qs:qe] if q_cat_j is not None else None,
                t_cat_j,
                cat_bins=bins, num_ranges=rng_j, k=kk, block=blk,
                metric="manhattan", n_valid=n_valid)
            dist, nidx = np.asarray(dist), np.asarray(nidx)
            in_c = y[qs:qe] == ki
            # in-class queries find themselves first: take the runner-up
            sel = np.where(in_c, kk - 1, 0)
            r = np.arange(qe - qs, dtype=np.int32)
            d = dist[r, sel]
            j = nidx[r, sel]
            if kk == 1:        # a singleton class has no non-self hit
                d = np.where(in_c, np.inf, d)
            best_d[qs:qe, ki] = d
            best_i[qs:qe, ki] = rows_c[np.clip(j, 0, len(rows_c) - 1)]

    rows = np.arange(m)
    hit_i = best_i[rows, y]
    hit_ok = np.isfinite(best_d[rows, y])
    miss_view = best_d.copy()
    miss_view[rows, y] = np.inf
    miss_cls = miss_view.argmin(axis=1)
    miss_i = best_i[rows, miss_cls]
    miss_ok = np.isfinite(miss_view[rows, miss_cls])
    valid = hit_ok & miss_ok
    if not valid.any():
        return {f.ordinal: 0.0 for f in num_fields + cat_fields}

    weights = {}
    for fi, f in enumerate(num_fields):
        col = x_num[:, fi]
        d_hit = np.abs(col - col[hit_i]) / ranges_arr[fi]
        d_miss = np.abs(col - col[miss_i]) / ranges_arr[fi]
        weights[f.ordinal] = float((d_miss - d_hit)[valid].mean())
    for fi, f in enumerate(cat_fields):
        col = x_cat[:, fi]
        d_hit = (col != col[hit_i]).astype(np.float32)
        d_miss = (col != col[miss_i]).astype(np.float32)
        weights[f.ordinal] = float((d_miss - d_hit)[valid].mean())
    return weights


# ---------------------------------------------------------------------------
# class affinity + supervised encoding
# ---------------------------------------------------------------------------


def class_affinity_from_table(tab: np.ndarray, fld: FeatureField,
                              class_values: Sequence[str], top_n: int = 3
                              ) -> Dict[str, List[Tuple[str, float]]]:
    """class_affinity from an accumulated [B, K] contingency table —
    the streaming form (tables fold additively per chunk)."""
    cls_tot = tab.sum(axis=0)
    out = {}
    for ki, cv in enumerate(class_values):
        p = tab[:, ki] / max(cls_tot[ki], _EPS)
        order = np.argsort(-p)[:top_n]
        out[cv] = [(fld.cardinality[b], float(p[b])) for b in order
                   if b < len(fld.cardinality)]
    return out


def class_affinity(ds: Dataset, fld: FeatureField, top_n: int = 3
                   ) -> Dict[str, List[Tuple[str, float]]]:
    """Per class: top-n categorical values by P(value | class)
    (CategoricalClassAffinity.java:51)."""
    return class_affinity_from_table(contingency(ds, fld), fld,
                                     ds.schema.class_values(), top_n)


def supervised_encoding_from_table(
    tab: np.ndarray,
    fld: FeatureField,
    classes: Sequence[str],
    strategy: str = "supervisedRatio",
    pos_class: Optional[str] = None,
) -> Dict[str, float]:
    """supervised_encoding from an accumulated [B, K] contingency table —
    the streaming form."""
    pi = classes.index(pos_class) if pos_class else 1
    pos = tab[:, pi]
    neg = tab.sum(axis=1) - pos
    total_pos = max(pos.sum(), _EPS)
    total_neg = max(neg.sum(), _EPS)
    out = {}
    for b, value in enumerate(fld.cardinality[:tab.shape[0]]):
        if strategy == "weightOfEvidence":
            num = max(pos[b], 0.5) / total_pos        # 0.5 = continuity corr.
            den = max(neg[b], 0.5) / total_neg
            out[value] = math.log(num / den)
        else:
            out[value] = float(pos[b] / max(pos[b] + neg[b], _EPS))
    return out


def supervised_encoding(
    ds: Dataset,
    fld: FeatureField,
    strategy: str = "supervisedRatio",
    pos_class: Optional[str] = None,
) -> Dict[str, float]:
    """Categorical value -> continuous code
    (CategoricalContinuousEncoding.java:47, coe.encoding.strategy):
      supervisedRatio: count(value, pos) / count(value)
      weightOfEvidence: ln( (count(value,pos)/total_pos) /
                            (count(value,neg)/total_neg) )
    """
    return supervised_encoding_from_table(
        contingency(ds, fld), fld, ds.schema.class_values(),
        strategy, pos_class)


# ---------------------------------------------------------------------------
# samplers
# ---------------------------------------------------------------------------


def undersample_balance(ds: Dataset, seed: int = 0) -> Dataset:
    """Undersample majority classes to the minority count
    (UnderSamplingBalancer.java:45)."""
    y = ds.labels()
    rng = np.random.default_rng(seed)
    counts = np.bincount(y, minlength=ds.schema.num_classes())
    target = counts[counts > 0].min()
    keep = []
    for c in range(len(counts)):
        rows = np.flatnonzero(y == c)
        if len(rows) > target:
            rows = rng.choice(rows, target, replace=False)
        keep.append(rows)
    keep = np.sort(np.concatenate(keep))
    return ds.take(keep)


def bagging_sample(ds: Dataset, rate: float = 1.0, seed: int = 0) -> Dataset:
    """Bootstrap sample (BaggingSampler.java:47)."""
    rng = np.random.default_rng(seed)
    n = len(ds)
    idx = rng.integers(0, n, int(n * rate))
    return ds.take(idx)


# ---------------------------------------------------------------------------
# top matches by class + rule evaluation
# ---------------------------------------------------------------------------


def top_matches_by_class(ds: Dataset, k: int = 3, block: int = 4096,
                         query_block: int = 16384
                         ) -> Dict[str, Tuple[np.ndarray, np.ndarray]]:
    """Per class: k nearest same-class neighbors for each record of that
    class (TopMatchesByClass.java:47). Returns class -> (dist [m, k],
    global dataset row idx [m, k]); row r of the pair is the class's r-th
    record in dataset order (np.flatnonzero(labels == class)).

    Queries stream in `query_block` chunks against the blocked index, so
    peak memory is O(query_block x block) however large the class."""
    from avenir_tpu.models.knn import NeighborIndex

    y = ds.labels()
    out = {}
    for ki, cv in enumerate(ds.schema.class_values()):
        rows = np.flatnonzero(y == ki)
        if len(rows) < 2:
            continue
        sub = ds.take(rows)
        index = NeighborIndex(sub, k=min(k + 1, len(rows)), block=block)
        dists, idxs = [], []
        for qs in range(0, len(rows), query_block):
            d, i = index.neighbors(
                sub.take(np.arange(qs, min(qs + query_block, len(rows)),
                                   dtype=np.int32)))
            dists.append(np.asarray(d))
            idxs.append(np.asarray(i))
        dist = np.concatenate(dists)
        idx = np.concatenate(idxs)
        # first neighbor is self (distance 0); drop it
        out[cv] = (dist[:, 1:], rows[idx[:, 1:]])
    return out


@dataclass
class Rule:
    """condition => consequence, both conjunctions of simple predicates
    "attr op value" with op in (eq, ne, gt, ge, lt, le, in)
    (RuleEvaluator.java:48, util/RuleExpression.java)."""

    condition: List[str]
    consequence: List[str]

    @staticmethod
    def _eval_one(ds: Dataset, expr: str) -> np.ndarray:
        toks = expr.strip().split(None, 2)
        attr, op, val = int(toks[0]), toks[1], toks[2]
        fld = ds.schema.field_by_ordinal(attr)
        col = ds.column(attr)
        if fld.is_categorical:
            index = fld.cardinality_index()
            if op == "in":
                codes = [index[v] for v in val.split(":") if v in index]
                return np.isin(col.astype(np.int64), codes)
            code = index[val]
            m = col.astype(np.int64) == code
            return m if op == "eq" else ~m
        x = col.astype(np.float64)
        v = float(val)
        return {
            "eq": x == v, "ne": x != v, "gt": x > v, "ge": x >= v,
            "lt": x < v, "le": x <= v,
        }[op]

    def counts(self, ds: Dataset) -> Tuple[int, int, int]:
        """(rows, conditionCount, bothCount) for one chunk — additive, so
        rule evaluation streams like every other counting job."""
        cond = np.ones(len(ds), bool)
        for e in self.condition:
            cond &= self._eval_one(ds, e)
        cons = np.ones(len(ds), bool)
        for e in self.consequence:
            cons &= self._eval_one(ds, e)
        return len(ds), int(cond.sum()), int((cond & cons).sum())

    @staticmethod
    def finalize(n: int, cond: int, both: int) -> Dict[str, float]:
        return {"support": float(both / n if n else 0.0),
                "confidence": float(both / max(cond, 1)),
                "conditionCount": cond, "bothCount": both}

    def evaluate(self, ds: Dataset) -> Dict[str, float]:
        return self.finalize(*self.counts(ds))
