"""Support vector machine classifier: jitted kernel-primal training.

Reference (python/supv/svm.py, SURVEY §2.10): a scikit-learn SVC driver with
properties config offering linear / rbf / poly kernels, sequential k-fold
validation (train_kfold_validation_ext, svm.py:53-99), random-split
repeated validation (train_rfold_validation, :100-165), bagging training
with an ensemble of persisted models (train_bagging, :22-38), per-fold
false-positive / false-negative error reporting (validate), and
majority-vote ensemble prediction (predict, :167-210).

TPU-first design: instead of wrapping libsvm, the classifier trains the
kernelized primal with a squared-hinge loss by full-batch gradient descent
— every step is a [n,n] kernel matmul + elementwise loss, which XLA maps
straight onto the MXU, and `lax.scan` keeps the whole epoch loop inside one
compiled program. Bagging vmaps one training program over estimator-many
bootstrap masks, so an ensemble costs one compile and one device launch.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import List, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

KERNELS = ("linear", "rbf", "poly")


def _kernel_matrix(x1: jnp.ndarray, x2: jnp.ndarray, kernel: str,
                   gamma: float, degree: int, coef0: float) -> jnp.ndarray:
    """Gram matrix [n1, n2]; all three kernels ride one x1 @ x2.T matmul."""
    inner = x1 @ x2.T
    if kernel == "linear":
        return inner
    if kernel == "poly":
        return (gamma * inner + coef0) ** degree
    # rbf: ||a-b||^2 = |a|^2 + |b|^2 - 2ab
    sq1 = jnp.sum(x1 * x1, axis=1)[:, None]
    sq2 = jnp.sum(x2 * x2, axis=1)[None, :]
    return jnp.exp(-gamma * (sq1 + sq2 - 2.0 * inner))


@partial(jax.jit, static_argnames=("epochs",))
def _train_kernel_primal(gram, y, sample_mask, c, lr, epochs):
    """Squared-hinge kernel-primal descent.

    Decision f = gram @ (alpha * y) + b; minimizes
    0.5 * alpha K alpha + C * sum(max(0, 1 - y f)^2) over masked samples.
    Returns (alpha, b). `sample_mask` zeroes rows excluded by a fold or a
    bootstrap draw so every fold/estimator shares one compiled program.
    """
    n = gram.shape[0]
    ay0 = jnp.zeros((n,), gram.dtype)
    # curvature-aware step: the squared-hinge Hessian in alpha space is
    # ~ 2C/n * K^2 + I, so the stable step is 2/(2C*lam^2/n + 1) with lam
    # the Gram spectral norm (power iteration); `lr` is a fraction of it.
    v = jnp.ones((n,), gram.dtype) / jnp.sqrt(n)

    def power(v, _):
        w = gram @ v
        return w / jnp.maximum(jnp.linalg.norm(w), 1e-30), None

    v, _ = jax.lax.scan(power, v, None, length=16)
    lam = jnp.linalg.norm(gram @ v)
    lr = lr * 2.0 / (2.0 * c * lam * lam / n + 1.0)

    def step(carry, _):
        ay, b = carry
        f = gram @ ay + b
        margin = 1.0 - y * f
        viol = jnp.maximum(margin, 0.0) * sample_mask
        # d/d f of C*viol^2 = -2C*y*viol ; primal reg pulls ay toward 0
        grad_f = -2.0 * c * y * viol
        grad_ay = gram @ grad_f / n + ay
        grad_b = jnp.sum(grad_f) / n
        return (ay - lr * grad_ay, b - lr * grad_b), None

    (ay, b), _ = jax.lax.scan(step, (ay0, jnp.zeros((), gram.dtype)),
                              None, length=epochs)
    return ay, b


@dataclass
class SVMClassifier:
    """Binary SVM over numeric feature matrices, labels in {0, 1}.

    Config keys mirror the reference properties (svm.py build_model):
    kernel linear/rbf/poly, penalty C, rbf gamma, poly degree/coef0.
    """

    kernel: str = "rbf"
    c: float = 1.0
    gamma: float = 0.5
    degree: int = 3
    coef0: float = 1.0
    learning_rate: float = 0.1
    epochs: int = 200

    x_train: Optional[np.ndarray] = None
    dual_coef: Optional[np.ndarray] = None       # alpha_i * y_i
    intercept: float = 0.0

    def __post_init__(self):
        if self.kernel not in KERNELS:
            raise ValueError(f"unknown kernel {self.kernel!r}")

    # -- core fit/predict ---------------------------------------------------
    def fit(self, x: np.ndarray, y: np.ndarray,
            sample_mask: Optional[np.ndarray] = None) -> "SVMClassifier":
        x = jnp.asarray(x, jnp.float32)
        ypm = jnp.asarray(np.where(np.asarray(y) > 0, 1.0, -1.0), jnp.float32)
        mask = (jnp.ones_like(ypm) if sample_mask is None
                else jnp.asarray(sample_mask, jnp.float32))
        gram = _kernel_matrix(x, x, self.kernel, self.gamma, self.degree,
                              self.coef0)
        ay, b = _train_kernel_primal(gram, ypm, mask, self.c,
                                     self.learning_rate, self.epochs)
        self.x_train = np.asarray(x)
        self.dual_coef = np.asarray(ay)
        self.intercept = float(b)
        return self

    def decision_function(self, x: np.ndarray) -> np.ndarray:
        if self.dual_coef is None:
            raise RuntimeError("model not fitted")
        k = _kernel_matrix(jnp.asarray(x, jnp.float32),
                           jnp.asarray(self.x_train), self.kernel,
                           self.gamma, self.degree, self.coef0)
        return np.asarray(k @ jnp.asarray(self.dual_coef) + self.intercept)

    def predict(self, x: np.ndarray) -> np.ndarray:
        return (self.decision_function(x) > 0.0).astype(np.int64)

    def score(self, x: np.ndarray, y: np.ndarray) -> float:
        return float(np.mean(self.predict(x) == np.asarray(y)))

    @property
    def support_indices(self) -> np.ndarray:
        """Indices with non-negligible dual coefficient (support vectors)."""
        ay = np.abs(self.dual_coef)
        return np.flatnonzero(ay > 1e-6 * max(ay.max(), 1e-30))

    # -- persistence (joblib.dump analog, svm.py:30-35) ---------------------
    def save(self, path: str) -> None:
        np.savez(path if path.endswith(".npz") else path + ".npz",
                 kernel=self.kernel, c=self.c, gamma=self.gamma,
                 degree=self.degree, coef0=self.coef0,
                 learning_rate=self.learning_rate, epochs=self.epochs,
                 x_train=self.x_train, dual_coef=self.dual_coef,
                 intercept=self.intercept)

    @classmethod
    def load(cls, path: str) -> "SVMClassifier":
        z = np.load(path if path.endswith(".npz") else path + ".npz",
                    allow_pickle=False)
        m = cls(kernel=str(z["kernel"]), c=float(z["c"]),
                gamma=float(z["gamma"]), degree=int(z["degree"]),
                coef0=float(z["coef0"]),
                learning_rate=float(z["learning_rate"]),
                epochs=int(z["epochs"]))
        m.x_train = z["x_train"]
        m.dual_coef = z["dual_coef"]
        m.intercept = float(z["intercept"])
        return m


def _fold_errors(y_true: np.ndarray, y_pred: np.ndarray
                 ) -> Tuple[float, float, float]:
    """(error, false-positive error, false-negative error) as fractions of
    the validation size — the reference's validate() report."""
    n = len(y_true)
    err = float(np.mean(y_pred != y_true))
    fp = float(np.sum((y_pred == 1) & (y_true == 0))) / n
    fn = float(np.sum((y_pred == 0) & (y_true == 1))) / n
    return err, fp, fn


@dataclass
class ValidationReport:
    fold_errors: List[Tuple[float, float, float]] = field(default_factory=list)

    @property
    def avg_error(self) -> float:
        return float(np.mean([e[0] for e in self.fold_errors]))

    @property
    def avg_fp_error(self) -> float:
        return float(np.mean([e[1] for e in self.fold_errors]))

    @property
    def avg_fn_error(self) -> float:
        return float(np.mean([e[2] for e in self.fold_errors]))

    def cost(self, fp_cost: float = 1.0, fn_cost: float = 1.0) -> float:
        """Misclassification-cost-weighted error (cost-based validation)."""
        return fp_cost * self.avg_fp_error + fn_cost * self.avg_fn_error


def _folds_validate(model: SVMClassifier, x: np.ndarray, y: np.ndarray,
                    vmasks: np.ndarray) -> ValidationReport:
    """All folds in one device program: one Gram matrix shared across folds,
    `vmap` of the trainer over train masks (the BaggedSVM pattern)."""
    xj = jnp.asarray(x, jnp.float32)
    yn = np.asarray(y)
    ypm = jnp.asarray(np.where(yn > 0, 1.0, -1.0), jnp.float32)
    gram = _kernel_matrix(xj, xj, model.kernel, model.gamma, model.degree,
                          model.coef0)
    train = jax.vmap(
        lambda m: _train_kernel_primal(gram, ypm, m, model.c,
                                       model.learning_rate, model.epochs))
    ays, bs = train(jnp.asarray((~vmasks).astype(np.float32)))
    f = np.asarray(gram @ ays.T + bs)                     # [n, folds]
    yb = (yn > 0).astype(np.int64)
    report = ValidationReport()
    for i, vm in enumerate(vmasks):
        pred = (f[vm, i] > 0.0).astype(np.int64)
        report.fold_errors.append(_fold_errors(yb[vm], pred))
    return report


def kfold_validate(model: SVMClassifier, x: np.ndarray, y: np.ndarray,
                   nfold: int) -> ValidationReport:
    """Sequential k-fold (train_kfold_validation_ext, svm.py:53-99):
    validation window slides by len/nfold each fold."""
    n = len(x)
    length = n // nfold
    vmasks = np.zeros((nfold, n), bool)
    for i in range(nfold):
        lo, hi = i * length, (i + 1) * length if i < nfold - 1 else n
        vmasks[i, lo:hi] = True
    return _folds_validate(model, x, y, vmasks)


def rfold_validate(model: SVMClassifier, x: np.ndarray, y: np.ndarray,
                   nfold: int, niter: int, seed: int = 0) -> ValidationReport:
    """Random repeated validation (train_rfold_validation_ext): each
    iteration holds out a random contiguous 1/nfold window."""
    rng = np.random.default_rng(seed)
    n = len(x)
    length = n // nfold
    vmasks = np.zeros((niter, n), bool)
    for i in range(niter):
        lo = int(rng.integers(0, n - length + 1))
        vmasks[i, lo:lo + length] = True
    return _folds_validate(model, x, y, vmasks)


@dataclass
class BaggedSVM:
    """Bootstrap-aggregated SVM ensemble (train_bagging, svm.py:22-38).

    All estimators train in ONE device program: `vmap` of the kernel-primal
    trainer over bootstrap sample masks sharing one Gram matrix.
    """

    base: SVMClassifier
    num_estimators: int = 10
    sample_fraction: float = 0.67
    use_oob: bool = False

    x_train: Optional[np.ndarray] = None
    dual_coefs: Optional[np.ndarray] = None      # [E, n]
    intercepts: Optional[np.ndarray] = None      # [E]
    oob_score_: Optional[float] = None

    def fit(self, x: np.ndarray, y: np.ndarray, seed: int = 0) -> "BaggedSVM":
        b = self.base
        rng = np.random.default_rng(seed)
        n = len(x)
        draw = max(1, int(round(self.sample_fraction * n)))
        # bootstrap with replacement -> per-estimator multiplicity masks
        masks = np.zeros((self.num_estimators, n), np.float32)
        for e in range(self.num_estimators):
            idx, cnt = np.unique(rng.integers(0, n, draw), return_counts=True)
            masks[e, idx] = cnt
        xj = jnp.asarray(x, jnp.float32)
        ypm = jnp.asarray(np.where(np.asarray(y) > 0, 1.0, -1.0), jnp.float32)
        gram = _kernel_matrix(xj, xj, b.kernel, b.gamma, b.degree, b.coef0)
        train = jax.vmap(
            lambda m: _train_kernel_primal(gram, ypm, m, b.c,
                                           b.learning_rate, b.epochs))
        ays, bs = train(jnp.asarray(masks))
        self.x_train = np.asarray(x)
        self.dual_coefs = np.asarray(ays)
        self.intercepts = np.asarray(bs)
        if self.use_oob:
            f = gram @ jnp.asarray(self.dual_coefs).T + jnp.asarray(
                self.intercepts)                         # reuse train Gram
            votes = np.asarray(f.T > 0.0).astype(np.int64)   # [E, n]
            oob = masks == 0                             # [E, n]
            num = np.where(oob, votes, 0).sum(axis=0)
            den = np.maximum(oob.sum(axis=0), 1)
            pred = (num / den) > 0.5
            covered = oob.any(axis=0)
            self.oob_score_ = float(
                np.mean(pred[covered] == (np.asarray(y)[covered] > 0)))
        return self

    def _votes(self, x: np.ndarray) -> np.ndarray:
        b = self.base
        k = _kernel_matrix(jnp.asarray(x, jnp.float32),
                           jnp.asarray(self.x_train), b.kernel, b.gamma,
                           b.degree, b.coef0)
        f = k @ jnp.asarray(self.dual_coefs).T + jnp.asarray(self.intercepts)
        return np.asarray(f.T > 0.0).astype(np.int64)     # [E, nq]

    def predict(self, x: np.ndarray) -> np.ndarray:
        """Majority vote across estimators (predict(), svm.py:167-210)."""
        votes = self._votes(x)
        return (votes.mean(axis=0) > 0.5).astype(np.int64)

    def score(self, x: np.ndarray, y: np.ndarray) -> float:
        return float(np.mean(self.predict(x) == np.asarray(y)))
