"""Association mining: Apriori frequent itemsets + rule generation.

Reference (SURVEY §2.5): org/avenir/association/ — FrequentItemsApriori runs
one MR job per itemset length k (driver loops over k): k=1 emits each item
(FrequentItemsApriori.java:138-150); k>1 loads the frequent (k-1)-itemset
file and extends each itemset with co-occurring items, sorted-key dedup
(:151-195); values are transaction ids (exact support, fia.emit.trans.id) or
counts; the reducer thresholds support = count / fia.total.tans.count
against fia.support.threshold. InfrequentItemMarker.java:41-46 replaces
infrequent items with a marker token after k=1 to shrink later scans.
AssociationRuleMiner.java:44-190 generates antecedent sublists (up to
arm.max.ante.size) of each frequent itemset and keeps rules whose
confidence = support(itemset) / support(antecedent) exceeds
arm.conf.threshold.

TPU-native design: transactions are multi-hot rows of an [N, V] matrix over
the item vocabulary (dictionary-encoded at ingest, like every other
categorical in this framework). Candidate k-itemsets are an [C, V] multi-hot
matrix; "transaction contains candidate" is exactly
`(T @ C.T) == k` — one blocked matmul on the MXU per transaction tile
replaces the Hadoop shuffle. Candidate *generation* stays on the host
(classical Apriori join + subset prune over the frequent (k-1) sets): it is
tiny, irregular, and data-dependent — the wrong shape for XLA — while the
support counting it gates is the N-proportional work and runs on device.
The per-k loop of the reference's driver survives as a host loop; the
frequent-itemset state between rounds stays as a plain file via save/load
(the reference's "model = file between steps" property, SURVEY §5).
"""

from __future__ import annotations

import os

from dataclasses import dataclass, field
from functools import partial
from itertools import combinations
from typing import Dict, Iterable, List, Optional, Sequence, Tuple, Union

import numpy as np
import jax
import jax.numpy as jnp

from avenir_tpu import obs as _obs
from avenir_tpu.native.ingest import SpillScanMixin


# --------------------------------------------------------------------------
# Transaction ingest
# --------------------------------------------------------------------------
def merge_support_counts(*states: "Dict") -> "Dict":
    """The miners' support-merge rule (the ROADMAP open question): sum
    per-candidate support counts keyed by candidate identity across
    shard states. Candidates are keyed canonically (token or
    sorted-token tuples), NOT by per-shard masked ids — shard sources
    discover vocabularies in data order, so only token-space keys align
    across shards. int32-safe by construction: per-shard device folds
    carry int32 counts, and this merge accumulates them as unbounded
    Python ints, so P shards each near the int32 ceiling can never wrap
    the merged total. A candidate absent from a shard simply contributes
    nothing (support 0 there). This is the reducer half of the
    MapReduce combiner/reducer contract (arXiv:1801.09802) the sharded
    mining drivers — and the straggler/redundant-work designs of
    arXiv:1802.03049 — are built on."""
    out: Dict = {}
    for state in states:
        for cand, cnt in state.items():
            out[cand] = out.get(cand, 0) + int(cnt)
    return out


def frequent_tokens(support1: Dict, min_count: float) -> List[str]:
    """The canonical frequent-token frontier after the merged k=1
    round: tokens whose merged support beats the threshold, SORTED —
    the one ordering every merged/sharded driver derives candidates
    (and the per-shard masks) from."""
    return sorted(t for t, cnt in support1.items() if cnt > min_count)


def stream_candidate_support(src: "StreamingTransactionSource",
                             cand_ids: List[Tuple[int, ...]],
                             c_pad: int, block: int = 8192) -> np.ndarray:
    """One streamed support pass over ONE source: candidates (masked
    item-id tuples in `src`'s id space) packed into a [c_pad, words]
    bitset matrix, blocks double-buffered against the donated int32
    device fold. The SINGLE implementation of the N-proportional
    counting — mine_stream, the sharded mine_stream_merged driver and
    the distributed per-k block workers all fold through it, which is
    what makes their counts (and therefore their outputs) identical by
    construction."""
    from avenir_tpu.core.stream import double_buffered
    from avenir_tpu.ops.bitset import (bitset_fold_counts,
                                       pack_index_rows_u32)

    cand_d = jnp.asarray(pack_index_rows_u32(
        cand_ids, src.masked_width, c_pad))
    counts_d = jnp.zeros(c_pad, jnp.int32)
    for packed in double_buffered(src.packed_chunks(block)):
        # host-side span: the donated fold dispatches async, so the
        # duration is dispatch+transfer time, not device occupancy
        t0 = _obs.now()
        counts_d = bitset_fold_counts(
            counts_d, jnp.asarray(packed), cand_d)
        _obs.record("stream.fold", t0, sink="apriori_support")
    return np.asarray(counts_d, np.int64)


def count_token_supports(src: "StreamingTransactionSource",
                         cands: List[Tuple[str, ...]], c_pad: int,
                         block: int = 8192) -> np.ndarray:
    """Support counts of canonical TOKEN-space candidates over ONE
    source, aligned to ``cands``: translate per source via token_code
    (a candidate holding a token this source never saw — or masked out
    — counts 0 without a scan), count the present ones through the one
    :func:`stream_candidate_support` fold. The per-shard body of
    mine_stream_merged AND the sharded per-k worker's block fold."""
    ids = [tuple(src.token_code(t) for t in cd) for cd in cands]
    present = [ci for ci, m in enumerate(ids)
               if all(i >= 0 for i in m)]
    counts = np.zeros(len(cands), np.int64)
    if present:
        shard = stream_candidate_support(
            src, [ids[ci] for ci in present], c_pad, block)
        counts[present] = shard[:len(present)]
    return counts


def collect_token_trans_ids(src: "StreamingTransactionSource",
                            all_sets: List[Tuple[str, ...]], c_pad: int,
                            block: int = 8192) -> List[List[str]]:
    """Per-set exact transaction-id lists over ONE source for the fused
    all-lengths id pass (fia.emit.trans.id): token-space sets translate
    via token_code, row ids come back in THIS source's row order — the
    per-shard body of _collect_trans_ids_merged and the sharded tids
    level's block fold. NOTE: rows come from ``src.chunks`` (the
    id-bearing python feed), so a per-block caller must hand a source
    whose paths ARE its block (a byte slice) — the cache stores no
    ids."""
    from avenir_tpu.ops.bitset import (bitset_contain_mask,
                                       pack_index_rows_u32, pack_rows_u32)

    tids: List[List[str]] = [[] for _ in all_sets]
    ids = [tuple(src.token_code(t) for t in cd) for cd in all_sets]
    present = [ci for ci, m in enumerate(ids)
               if all(i >= 0 for i in m)]
    if not present:
        return tids
    cand_d = jnp.asarray(pack_index_rows_u32(
        [ids[ci] for ci in present], src.masked_width, c_pad))
    for mh, row_ids in src.chunks(block, with_ids=True):
        m = np.asarray(bitset_contain_mask(
            jnp.asarray(pack_rows_u32(mh)), cand_d))
        for pi, ci in enumerate(present):
            for r in np.flatnonzero(m[:len(row_ids), pi]):
                tids[ci].append(str(row_ids[r]))
    return tids


class TransactionSet:
    """Dictionary-encoded transactions: multi-hot uint8 [N, V] + id column.

    Input rows follow the reference's layout (FrequentItemsApriori.java:
    134-150): a transaction id at `trans_id_ord`, `skip_field_count` leading
    non-item fields, every remaining field an item token. A `marker` token
    (InfrequentItemMarker output) is dropped at ingest.
    """

    def __init__(self, multihot: np.ndarray, vocab: List[str],
                 trans_ids: np.ndarray):
        self.multihot = multihot            # uint8 [N, V]
        self.vocab = vocab                  # item id -> token
        self.index = {t: i for i, t in enumerate(vocab)}
        self.trans_ids = trans_ids          # object [N]

    @classmethod
    def from_rows(cls, rows: Sequence[Sequence[str]], trans_id_ord: int = 0,
                  skip_field_count: int = 1,
                  marker: Optional[str] = None) -> "TransactionSet":
        vocab: List[str] = []
        index: Dict[str, int] = {}
        encoded: List[List[int]] = []
        ids: List[str] = []
        for row in rows:
            ids.append(row[trans_id_ord])
            items = []
            for tok in row[skip_field_count:]:
                if tok == "" or (marker is not None and tok == marker):
                    continue
                if tok not in index:
                    index[tok] = len(vocab)
                    vocab.append(tok)
                items.append(index[tok])
            encoded.append(items)
        mh = np.zeros((len(rows), max(len(vocab), 1)), dtype=np.uint8)
        for i, items in enumerate(encoded):
            mh[i, items] = 1
        return cls(mh, vocab, np.array(ids, dtype=object))

    @classmethod
    def from_csv(cls, source: Union[str, Iterable[str]], delim: str = ",",
                 trans_id_ord: int = 0, skip_field_count: int = 1,
                 marker: Optional[str] = None) -> "TransactionSet":
        import io, os
        if isinstance(source, str):
            if os.path.exists(source):
                lines: Iterable[str] = open(source, "r")
            elif "\n" in source or delim in source or source == "":
                lines = io.StringIO(source)
            else:
                raise FileNotFoundError(f"no such transactions file: {source!r}")
        else:
            lines = source
        rows = [
            # trim set matches the native seq_encode / streaming source
            [t.strip(" \t\r") for t in ln.rstrip("\n").split(delim)]
            for ln in lines if ln.strip()
        ]
        if hasattr(lines, "close") and lines is not source:
            lines.close()
        return cls.from_rows(rows, trans_id_ord, skip_field_count, marker)

    def __len__(self) -> int:
        return self.multihot.shape[0]


class StreamingTransactionSource(SpillScanMixin):
    """Re-iterable chunked transaction reader for unbounded-size mining.

    Apriori is inherently multi-pass — the reference runs one MR job per
    itemset length k over the same HDFS input
    (FrequentItemsApriori.java:123-126) — so streaming means each k-pass
    re-scans the file at O(block) host RSS instead of holding the [N, V]
    multi-hot matrix. Pass 1 (scan_items) freezes the item vocabulary and
    per-item supports — natively when the C encoder is built, so no
    per-row Python runs even on the discovery pass. After the k=1 round
    the miner installs the frequent-item mask (mask_items — the ingest
    form of the reference's InfrequentItemMarker), and packed_chunks()
    then yields uint32 BITSET blocks over the frequent vocabulary only:
    V shrinks to the surviving items and each block is ~8x smaller than
    the uint8 multi-hot it replaces."""

    def __init__(self, paths: Sequence[str], delim: str = ",",
                 trans_id_ord: int = 0, skip_field_count: int = 1,
                 marker: Optional[str] = None,
                 block_bytes: int = 64 << 20,
                 spill_cache: bool = True,
                 cache_budget_bytes: Optional[int] = None):
        self.paths = list(paths)
        self.delim = delim
        self.trans_id_ord = trans_id_ord
        self.skip = skip_field_count
        self.marker = marker
        self.block_bytes = block_bytes
        self.spill_cache = spill_cache
        self.cache_budget_bytes = cache_budget_bytes
        self.vocab: List[str] = []
        self.index: Dict[str, int] = {}
        self.n_trans = 0
        self._item_counts: Optional[np.ndarray] = None
        self._kept_ids: Optional[np.ndarray] = None   # orig ids, ascending
        self._remap: Optional[np.ndarray] = None      # orig id -> masked|-1
        self._cache = None            # EncodedBlockCache once pass 1 ran
        self._scan_counts: Optional[np.ndarray] = None
        self._scan_encoder = None

    def _row_blocks(self):
        from avenir_tpu.core.stream import iter_line_blocks, prefetched

        for path in self.paths:
            for lines in prefetched(
                    iter_line_blocks(path, self.block_bytes), depth=1):
                # trim set matches the native seq_encode trim exactly
                # (space/tab/CR): the vocab pass and the native counting
                # pass must agree on token identity
                yield [[t.strip(" \t\r") for t in ln.split(self.delim)]
                       for ln in lines]

    # ------------------------------------------------------------ pass 1
    # (scan lifecycle, SharedScan sink adapter and cache ownership live
    # in native.ingest.SpillScanMixin — one copy for both miner sources)
    @property
    def _scan_marker(self) -> Optional[str]:
        return self.marker

    def _reset_scan_state(self) -> None:
        self.n_trans = 0

    def _scan_result(self) -> Tuple[List[str], np.ndarray, int]:
        return self.vocab, self._item_counts, self.n_trans

    def _note_encoded_rows(self, per_row: np.ndarray, n: int) -> None:
        self.n_trans += n

    def scan_items(self) -> Tuple[List[str], np.ndarray, int]:
        """Pass 1: (vocab, per-item transaction counts, n_trans). An item
        repeated within one transaction counts once (multi-hot algebra).
        The pass also spills each block's region-compacted codes to the
        encoded-block cache (when enabled), so every later per-k scan
        replays encoded blocks instead of re-parsing CSV."""
        if self._item_counts is not None:
            return self.vocab, self._item_counts, self.n_trans
        return self._scan_all()

    def _scan_block(self, data: bytes) -> None:
        """Fold one raw byte block into the pass-1 state (native encoder
        when built, python tokenizer otherwise) and spill its encoded
        form to the cache."""
        from avenir_tpu.native.ingest import (csr_rows,
                                              distinct_row_code_counts)

        if self._scan_encoder is not None:
            out = self._scan_encoder.encode(data)
            if out is None:
                return
            codes, offsets, region, n = out
            self._grow_counts()
            row_of, _ = csr_rows(offsets)
            self._scan_counts += distinct_row_code_counts(
                row_of, codes, region, len(self.vocab))
            if self._cache is not None:
                blk_counts = np.bincount(row_of[region].astype(np.intp),
                                         minlength=n)
                self._cache.add_block(blk_counts, codes[region])
            self.n_trans += n
            return
        rows = [[t.strip(" \t\r") for t in ln.split(self.delim)]
                for ln in data.decode("utf-8", "replace").split("\n")
                if ln.strip()]
        if not rows:
            return
        blk_counts = np.zeros(len(rows), np.int64)
        blk_codes: List[int] = []
        for r, row in enumerate(rows):
            k0 = len(blk_codes)
            for tok in row[self.skip:]:
                if tok == "" or tok == self.marker:
                    continue
                i = self.index.get(tok)
                if i is None:
                    i = len(self.vocab)
                    self.index[tok] = i
                    self.vocab.append(tok)
                blk_codes.append(i)
            blk_counts[r] = len(blk_codes) - k0
        codes = np.asarray(blk_codes, np.int32)
        self._grow_counts()
        row_of = np.repeat(np.arange(len(rows), dtype=np.int32), blk_counts)
        region = np.ones(codes.shape[0], bool)
        self._scan_counts += distinct_row_code_counts(
            row_of, codes, region, len(self.vocab))
        if self._cache is not None:
            self._cache.add_block(blk_counts, codes)
        self.n_trans += len(rows)

    # ----------------------------------------------------- frequent mask
    def mask_items(self, keep_ids: Sequence[int]) -> int:
        """Install the frequent-item vocabulary mask (the ingest analog of
        InfrequentItemMarker.java:41-46): packed_chunks() thereafter
        encodes over ONLY these items, in masked id space 0..len(keep)-1
        (ascending original order, so sorted tuples stay sorted). Returns
        the masked vocabulary width."""
        kept = np.asarray(sorted(keep_ids), np.int32)
        remap = np.full(max(len(self.vocab), 1), -1, np.int32)
        remap[kept] = np.arange(kept.shape[0], dtype=np.int32)
        self._kept_ids, self._remap = kept, remap
        return int(kept.shape[0])

    @property
    def masked_width(self) -> int:
        return (len(self.vocab) if self._kept_ids is None
                else int(self._kept_ids.shape[0]))

    def masked_token(self, masked_id: int) -> str:
        """Token for a masked item id (identity when no mask installed)."""
        if self._kept_ids is None:
            return self.vocab[masked_id]
        return self.vocab[int(self._kept_ids[masked_id])]

    def token_code(self, tok: str) -> int:
        """Candidate-encoding lookup in the packed_chunks() id space
        (masked when a mask is installed); -2 marks a token this source
        never saw / masked out — its candidates count 0 here. Mirrors
        StreamingSequenceSource.token_code so the sharded mining driver
        translates canonical token-space candidates per shard."""
        i = self.index.get(tok)
        if i is None:
            return -2
        if self._remap is not None:
            i = int(self._remap[i])
            if i < 0:
                return -2
        return i

    def _apply_mask(self, r: np.ndarray, c: np.ndarray):
        if self._remap is None:
            return r, c
        m = self._remap[c]
        ok = m >= 0
        return r[ok], m[ok]

    # ------------------------------------------------------- chunk feeds
    def packed_chunks(self, block_rows: int = 8192):
        """Yield uint32 bitset blocks [block_rows, words(V_masked)] over
        the (masked) vocabulary; row tails zero-pad (an all-zero row
        contains no nonempty candidate, so it never counts). Rides the
        native ragged encoder when built — no per-row Python on the
        N-proportional path; the Python fallback packs the same blocks
        from split rows."""
        from avenir_tpu.ops.bitset import pack_rows_u32

        for mh in self._dense_chunks(block_rows):
            yield pack_rows_u32(mh)

    def _dense_chunks(self, block_rows: int):
        """uint8 [block_rows, V_masked] multi-hot blocks (mask applied).
        Replays the encoded-block cache when pass 1 spilled one and the
        sources are unchanged — no CSV read, no re-tokenize; sources
        whose segment the cache's byte budget evicted re-parse natively
        while the survivors keep replaying; otherwise the native (or
        python) re-parse path runs as before."""
        from avenir_tpu.core.stream import prefetched
        from avenir_tpu.native.ingest import (csr_region_mask, csr_rows,
                                              native_seq_ready,
                                              seq_encode_native)

        vm = max(self.masked_width, 1)

        def pages(r, c, n):
            # r is sorted (row_of nondecreasing): each page is a
            # searchsorted slice, not a full-array rescan
            bounds = np.searchsorted(
                r, np.arange(0, n + block_rows, block_rows,
                             dtype=np.int32))
            for page, (lo, hi) in enumerate(zip(bounds[:-1], bounds[1:])):
                mh = np.zeros((block_rows, vm), np.uint8)
                mh[r[lo:hi] - page * block_rows, c[lo:hi]] = 1
                yield mh

        def replay_pages(blk_iter):
            for counts, codes in prefetched(blk_iter, depth=1):
                n = counts.shape[0]
                if n <= 0:
                    continue
                row_of = np.repeat(np.arange(n, dtype=np.int32), counts)
                r, c = self._apply_mask(row_of, codes)
                yield from pages(r, c, n)

        def parse_pages(path, byte_range=None):
            from avenir_tpu.core.stream import iter_byte_blocks

            for data in prefetched(
                    iter_byte_blocks(path, self.block_bytes, byte_range),
                    depth=1):
                # cannot be None: availability + 1-byte delim checked
                codes, offsets = seq_encode_native(
                    data, self.delim, self.vocab)
                n = offsets.shape[0] - 1
                if n <= 0:
                    continue
                # item region only; unknown tokens (-1: ids, marker,
                # empties) drop exactly like the python path
                valid = csr_region_mask(offsets, self.skip,
                                        codes.shape[0])
                np.logical_and(valid, codes >= 0, out=valid)
                row_of, _ = csr_rows(offsets)
                r, c = self._apply_mask(row_of[valid], codes[valid])
                yield from pages(r, c, n)

        if self._cache is not None and self._cache.valid:
            yield from replay_pages(self._cache.blocks())
            return
        if native_seq_ready(self.delim):
            for si, path in enumerate(self.paths):
                if self._cache is None:
                    yield from parse_pages(path)
                    continue
                if self._cache.source_valid(si):
                    yield from replay_pages(self._cache.blocks(si))
                    continue
                delta = self._cache.source_delta(si)
                if delta is not None:
                    # appended source: the committed blocks still
                    # content-match the file's prefix (per-block
                    # fingerprints) — replay them and re-parse only the
                    # appended tail instead of the whole file
                    yield from replay_pages(
                        self._cache.blocks(si, prefix=True))
                    yield from parse_pages(
                        path, (delta, os.path.getsize(path)))
                else:
                    yield from parse_pages(path)
            return

        for mh, _ids in self.chunks(block_rows):
            yield mh

    def chunks(self, block_rows: int = 8192, with_ids: bool = False):
        """Yield (multihot uint8 [block_rows, V_masked], ids) blocks from
        the Python row path — the id-bearing feed (the exact-trans-id
        pass needs per-row ids, which the native CSR encode drops) and
        the no-compiler fallback behind _dense_chunks."""
        vm = max(self.masked_width, 1)

        def emit(rows):
            mh = np.zeros((block_rows, vm), np.uint8)
            ids = []
            for r, row in enumerate(rows):
                if with_ids:
                    ids.append(row[self.trans_id_ord])
                for tok in row[self.skip:]:
                    i = self.index.get(tok)
                    if i is None:
                        continue
                    if self._remap is not None:
                        i = int(self._remap[i])
                        if i < 0:
                            continue
                    mh[r, i] = 1
            return mh, ids

        buf: List[List[str]] = []
        for rows in self._row_blocks():
            buf.extend(rows)
            while len(buf) >= block_rows:
                yield emit(buf[:block_rows])
                buf = buf[block_rows:]
        if buf:
            yield emit(buf)

# --------------------------------------------------------------------------
# Itemset containers (the between-rounds file state)
# --------------------------------------------------------------------------
@dataclass
class ItemSet:
    items: Tuple[str, ...]          # sorted item tokens
    support: float                  # fraction of transactions
    count: int
    trans_ids: Optional[List[str]] = None

    def line(self, delim: str = ",") -> str:
        parts = list(self.items) + [f"{self.support:.6f}"]
        if self.trans_ids is not None:
            parts += list(self.trans_ids)
        return delim.join(parts)


@dataclass
class ItemSetList:
    """Frequent itemsets of one length k (association/ItemSetList.java:34):
    the file handed from round k to round k+1."""
    length: int
    item_sets: List[ItemSet] = field(default_factory=list)

    def save(self, path: str, delim: str = ",") -> None:
        with open(path, "w") as fh:
            for s in self.item_sets:
                fh.write(s.line(delim) + "\n")

    @classmethod
    def load(cls, path: str, length: int, with_trans_ids: bool = False,
             delim: str = ",") -> "ItemSetList":
        sets = []
        with open(path) as fh:
            for ln in fh:
                toks = [t.strip() for t in ln.rstrip("\n").split(delim)]
                if not toks or toks == [""]:
                    continue
                items = tuple(toks[:length])
                support = float(toks[length])
                tids = toks[length + 1:] if with_trans_ids else None
                sets.append(ItemSet(items, support, 0, tids))
        return cls(length, sets)

    def supports(self) -> Dict[Tuple[str, ...], float]:
        return {s.items: s.support for s in self.item_sets}

    def __len__(self) -> int:
        return len(self.item_sets)


# --------------------------------------------------------------------------
# Device support counting
# --------------------------------------------------------------------------
@partial(jax.jit, static_argnames=("k",))
def _contain_counts(trans: jnp.ndarray, cand: jnp.ndarray, k: int):
    """counts[c] = #transactions containing all k items of candidate c.

    trans float32 [B, V] multi-hot tile, cand float32 [C, V] multi-hot.
    The matmul rides the MXU; equality against the static k recovers exact
    set containment."""
    overlap = trans @ cand.T                       # [B, C]
    return jnp.sum(overlap >= k, axis=0, dtype=jnp.int32)


@partial(jax.jit, static_argnames=("k",))
def _contain_mask(trans: jnp.ndarray, cand: jnp.ndarray, k: int):
    return (trans @ cand.T) >= k                   # [B, C] bool


@partial(jax.jit, static_argnames=("k", "block"), donate_argnums=())
def _contain_counts_resident(trans: jnp.ndarray, cand: jnp.ndarray,
                             k: int, block: int):
    """One-call support count over a DEVICE-RESIDENT uint8 multi-hot
    matrix (rows padded to a multiple of `block`): the per-tile loop runs
    as a lax.scan inside the executable, so the whole per-k round costs
    one dispatch instead of N/block host->device transfers — the
    difference between tunnel-latency-bound and MXU-bound mining."""
    n, v = trans.shape
    tiles = trans.reshape(n // block, block, v)

    def step(acc, tile):
        overlap = tile.astype(jnp.float32) @ cand.T        # [B, C]
        return acc + jnp.sum(overlap >= k, axis=0, dtype=jnp.int32), None

    counts, _ = jax.lax.scan(
        step, jnp.zeros((cand.shape[0],), jnp.int32), tiles)
    return counts


def _count_support(multihot: np.ndarray, cand_rows: np.ndarray, k: int,
                   block: int = 8192,
                   want_mask: bool = False):
    """Blocked streaming support count over transaction tiles."""
    n, v = multihot.shape
    c = cand_rows.shape[0]
    counts = np.zeros((c,), dtype=np.int64)
    masks = [] if want_mask else None
    cand_f = jnp.asarray(cand_rows, dtype=jnp.float32)
    for s in range(0, n, block):
        tile = jnp.asarray(multihot[s:s + block], dtype=jnp.float32)
        if want_mask:
            m = np.asarray(_contain_mask(tile, cand_f, k))
            masks.append(m)
            counts += m.sum(axis=0)
        else:
            counts += np.asarray(_contain_counts(tile, cand_f, k), dtype=np.int64)
    if want_mask:
        return counts, np.concatenate(masks, axis=0)
    return counts, None


# --------------------------------------------------------------------------
# Apriori driver
# --------------------------------------------------------------------------
def _generate_candidates(freq_prev: List[Tuple[int, ...]], k: int
                         ) -> List[Tuple[int, ...]]:
    """Classical Apriori join + prune on item-id tuples (host side).

    Equivalent to the reference's extend-with-co-occurring-item + sorted-key
    dedup (FrequentItemsApriori.java:151-195), minus the candidates the
    subset prune can reject early."""
    prev_set = set(freq_prev)
    freq_sorted = sorted(freq_prev)
    cands = []
    for i, a in enumerate(freq_sorted):
        for b in freq_sorted[i + 1:]:
            if a[:-1] != b[:-1]:
                break               # sorted: no more shared (k-2)-prefix
            cand = a + (b[-1],)
            # prune: all (k-1)-subsets must be frequent
            if all(cand[:j] + cand[j + 1:] in prev_set for j in range(k)):
                cands.append(cand)
    return cands


class FrequentItemsApriori:
    """Frequent itemset miner: host per-k loop + device support matmuls.

    Parameters mirror the reference's fia.* keys: support_threshold
    (fia.support.threshold, fraction), max_length (driver loop bound),
    emit_trans_id (fia.emit.trans.id → exact transaction id lists in the
    output, FrequentItemsApriori.java:143-149)."""

    def __init__(self, support_threshold: float, max_length: int = 3,
                 emit_trans_id: bool = False, block: int = 8192):
        self.support_threshold = support_threshold
        self.max_length = max_length
        self.emit_trans_id = emit_trans_id
        self.block = block

    def mine(self, tx: TransactionSet) -> List[ItemSetList]:
        n = len(tx)
        min_count = self.support_threshold * n
        out: List[ItemSetList] = []

        # k = 1: column sums of the multi-hot matrix
        col_counts = self.multihot_item_counts(tx)
        freq_ids: List[Tuple[int, ...]] = [
            (i,) for i in range(len(tx.vocab)) if col_counts[i] > min_count
        ]
        out.append(self._pack(
            tx, freq_ids, 1, [int(col_counts[i]) for (i,) in freq_ids]))

        # one upload, device-resident across all k rounds; zero-padded
        # rows contain no candidate (overlap 0 < k), so they never count
        pad_n = (-n) % self.block
        trans_dev = jnp.asarray(np.pad(tx.multihot, ((0, pad_n), (0, 0))))

        for k in range(2, self.max_length + 1):
            cands = _generate_candidates(freq_ids, k)
            if not cands:
                break
            # pad the candidate axis to a bucket size so recurring rounds
            # reuse the compiled executable; zero candidate rows count 0
            c_pad = max(64, 1 << (len(cands) - 1).bit_length())
            cand_rows = np.zeros((c_pad, tx.multihot.shape[1]),
                                 dtype=np.float32)
            for ci, items in enumerate(cands):
                cand_rows[ci, list(items)] = 1.0
            counts = np.asarray(_contain_counts_resident(
                trans_dev, jnp.asarray(cand_rows), k, self.block))[:len(cands)]
            kept = [(c, int(cnt)) for c, cnt in zip(cands, counts)
                    if cnt > min_count]
            if not kept:
                break
            freq_ids = [c for c, _ in kept]
            out.append(self._pack(tx, freq_ids, k, [cnt for _, cnt in kept]))
        return out

    def mine_stream(self, src: StreamingTransactionSource
                    ) -> List[ItemSetList]:
        """mine() at unbounded input size: one streamed scan per itemset
        length k (the reference's one-MR-job-per-k driver loop,
        FrequentItemsApriori.java:123-126).

        The N-proportional counting is a blocked BIT-PACKED device fold:
        after the k=1 pass the frequent-item mask shrinks the vocabulary
        (InfrequentItemMarker at ingest), chunks arrive as uint32 bitsets
        (~8x less block RSS than uint8 multi-hot), and the popcount
        containment kernel takes candidates of any length — one compiled
        executable serves every round, and the exact-transaction-id pass
        runs ONCE over the kept sets of ALL lengths fused into a single
        candidate matrix instead of one streamed scan per k. Chunk
        encode/pack double-buffers against the device fold, whose int32
        carry is DONATED (ops.bitset.bitset_fold_counts) — per-k rounds
        dispatch asynchronously with one host pull at the end. Per-k
        re-scans replay the pass-1 encoded-block cache when the sources
        are unchanged (see EncodedBlockCache) instead of re-parsing."""
        vocab, col_counts, n = src.scan_items()
        min_count = self.support_threshold * n

        # k = 1 from the scan; install the frequent-item mask so every
        # later block encodes over the surviving vocabulary only.
        # Masked ids are ranks of the ascending original ids, so sorted
        # candidate tuples stay sorted under the remap.
        freq1 = [i for i in range(len(vocab)) if col_counts[i] > min_count]
        vm = src.mask_items(freq1)
        rounds: List[Tuple[int, List[Tuple[int, ...]], List[int]]] = [
            (1, [(m,) for m in range(vm)],
             [int(col_counts[i]) for i in freq1])]

        freq_ids: List[Tuple[int, ...]] = rounds[0][1]
        for k in range(2, self.max_length + 1):
            cands = _generate_candidates(freq_ids, k)
            if not cands:
                break
            # pad the candidate axis to a bucket size so recurring rounds
            # reuse the compiled executable; zero candidate rows count 0
            c_pad = max(64, 1 << (len(cands) - 1).bit_length())
            counts = self._stream_support(src, cands, c_pad)
            kept = [(c, int(cnt)) for c, cnt in zip(cands, counts[:len(cands)])
                    if cnt > min_count]
            if not kept:
                break
            freq_ids = [c for c, _ in kept]
            rounds.append((k, freq_ids, [cnt for _, cnt in kept]))

        tids = self._collect_trans_ids(src, rounds) \
            if self.emit_trans_id else None
        out: List[ItemSetList] = []
        at = 0
        for k, ids_k, counts_k in rounds:
            out.append(self._pack_stream(
                src, ids_k, k, counts_k,
                tids[at:at + len(ids_k)] if tids is not None else None))
            at += len(ids_k)
        return out

    def _stream_support(self, src: StreamingTransactionSource,
                        cand_ids: List[Tuple[int, ...]], c_pad: int
                        ) -> np.ndarray:
        """One streamed support pass over ONE source — the module-level
        :func:`stream_candidate_support` at this miner's block size."""
        return stream_candidate_support(src, cand_ids, c_pad, self.block)

    def _merged_rounds(self, support1: Dict, n: int, count_fn):
        """The per-k control loop of the MERGED mining drivers over
        canonical token-space candidates: threshold the merged k=1
        supports, generate each level's candidates, count them through
        ``count_fn(k, cands, c_pad) -> int64 [len(cands)]``, prune, and
        stop on an empty frontier. Shared by mine_stream_merged (counts
        per shard source in-process) and the sharded per-k driver
        (counts per ledger block across worker processes) — ONE loop,
        so their kept sets and counts agree by construction."""
        min_count = self.support_threshold * n
        freq_toks = frequent_tokens(support1, min_count)
        rounds: List[Tuple[int, List[Tuple[str, ...]], List[int]]] = [
            (1, [(t,) for t in freq_toks],
             [int(support1[t]) for t in freq_toks])]

        freq_sets: List[Tuple[str, ...]] = rounds[0][1]
        for k in range(2, self.max_length + 1):
            cands = _generate_candidates(freq_sets, k)
            if not cands:
                break
            c_pad = max(64, 1 << (len(cands) - 1).bit_length())
            counts = count_fn(k, cands, c_pad)
            kept = [(cd, int(cnt)) for cd, cnt in zip(cands, counts)
                    if cnt > min_count]
            if not kept:
                break
            freq_sets = [cd for cd, _ in kept]
            rounds.append((k, freq_sets, [cnt for _, cnt in kept]))
        return rounds

    def _pack_merged_rounds(self, rounds, n: int,
                            tids: Optional[List[List[str]]] = None
                            ) -> List[ItemSetList]:
        """Merged rounds -> per-length ItemSetLists (sorted sets, global
        support fractions) — the artifact-shaping tail shared by
        mine_stream_merged and the sharded per-k driver."""
        out: List[ItemSetList] = []
        at = 0
        for k, sets_k, counts_k in rounds:
            sets = []
            for ci, cd in enumerate(sets_k):
                sets.append(ItemSet(
                    tuple(sorted(cd)), counts_k[ci] / n, int(counts_k[ci]),
                    tids[at + ci] if tids is not None else None))
            sets.sort(key=lambda s: s.items)
            out.append(ItemSetList(k, sets))
            at += len(sets_k)
        return out

    def mine_stream_merged(self, sources: Sequence[StreamingTransactionSource]
                           ) -> List[ItemSetList]:
        """mine_stream() over P shard sources with the support-merge
        algebra: each per-k round counts every candidate independently
        per shard (the SAME _stream_support fold mine_stream drives) and
        merges the counts via merge_support_counts, thresholding against
        the GLOBAL transaction count — so the mined output is
        byte-identical to a single mine_stream over the concatenated
        shards (integer counts partition exactly across row-aligned
        shards; the shard-merge auditor re-proves this every round).

        Candidates live in canonical token space here — per-shard masked
        ids don't align across shards (vocab discovery order is data
        order) — and translate per shard via token_code; a candidate
        with a token some shard never saw counts 0 there without a scan.
        fia.emit.trans.id concatenates per-shard id lists in shard
        order, which IS corpus order for byte-range shards."""
        srcs = list(sources)
        if len(srcs) == 1:
            return self.mine_stream(srcs[0])
        scans = [src.scan_items() for src in srcs]
        n = sum(s[2] for s in scans)
        min_count = self.support_threshold * n
        support1 = merge_support_counts(
            *[{vocab[i]: int(counts[i]) for i in range(len(vocab))}
              for vocab, counts, _n in scans])
        freq_toks = frequent_tokens(support1, min_count)
        for src in srcs:
            src.mask_items([src.index[t] for t in freq_toks
                            if t in src.index])

        def count_level(k, cands, c_pad):
            counts = np.zeros(len(cands), np.int64)
            for src in srcs:
                counts += count_token_supports(src, cands, c_pad,
                                               self.block)
            return counts

        rounds = self._merged_rounds(support1, n, count_level)
        tids = self._collect_trans_ids_merged(srcs, rounds) \
            if self.emit_trans_id else None
        return self._pack_merged_rounds(rounds, n, tids)

    def _collect_trans_ids_merged(self, srcs, rounds) -> List[List[str]]:
        """The exact-trans-id pass of the sharded driver: one fused
        all-lengths scan PER SHARD (collect_token_trans_ids),
        per-candidate id lists concatenated in shard order (= corpus
        order for byte-range shards)."""
        all_sets = [cd for _k, sets_k, _c in rounds for cd in sets_k]
        tids: List[List[str]] = [[] for _ in all_sets]
        if not all_sets:
            return tids
        c_pad = max(64, 1 << (len(all_sets) - 1).bit_length())
        for src in srcs:
            shard = collect_token_trans_ids(src, all_sets, c_pad,
                                            self.block)
            for ci in range(len(all_sets)):
                tids[ci].extend(shard[ci])
        return tids

    def _collect_trans_ids(self, src: StreamingTransactionSource,
                           rounds) -> List[List[str]]:
        """ONE extra streamed pass for fia.emit.trans.id: the kept sets of
        every length fuse into a single packed candidate matrix (the
        popcount kernel needs no per-length dispatch), so exact per-set
        transaction id lists cost one scan total, not one per k."""
        from avenir_tpu.ops.bitset import (bitset_contain_mask,
                                           pack_index_rows_u32, pack_rows_u32)

        all_sets = [ids_t for _k, ids_k, _c in rounds for ids_t in ids_k]
        if not all_sets:
            return []
        vm = src.masked_width
        c_pad = max(64, 1 << (len(all_sets) - 1).bit_length())
        cand_d = jnp.asarray(pack_index_rows_u32(all_sets, vm, c_pad))
        tids: List[List[str]] = [[] for _ in all_sets]
        for mh, ids in src.chunks(self.block, with_ids=True):
            m = np.asarray(bitset_contain_mask(
                jnp.asarray(pack_rows_u32(mh)), cand_d))
            for ci in range(len(all_sets)):
                for r in np.flatnonzero(m[:len(ids), ci]):
                    tids[ci].append(str(ids[r]))
        return tids

    def _pack_stream(self, src: StreamingTransactionSource,
                     freq_ids: List[Tuple[int, ...]], k: int,
                     counts: List[int],
                     tids: Optional[List[List[str]]] = None) -> ItemSetList:
        if not freq_ids:
            return ItemSetList(k, [])
        n = src.n_trans
        sets = []
        for ci, ids_t in enumerate(freq_ids):
            tokens = tuple(sorted(src.masked_token(i) for i in ids_t))
            sets.append(ItemSet(tokens, counts[ci] / n, int(counts[ci]),
                                tids[ci] if tids is not None else None))
        sets.sort(key=lambda s: s.items)
        return ItemSetList(k, sets)

    def _pack(self, tx: TransactionSet, freq_ids: List[Tuple[int, ...]],
              k: int, counts: List[int]) -> ItemSetList:
        if not freq_ids:
            return ItemSetList(k, [])
        n = len(tx)
        mask = None
        if self.emit_trans_id:
            # the only case needing a second device pass: per-transaction
            # membership masks for the surviving frequent sets
            cand_rows = np.zeros((len(freq_ids), tx.multihot.shape[1]),
                                 np.uint8)
            for ci, items in enumerate(freq_ids):
                cand_rows[ci, list(items)] = 1
            _, mask = _count_support(
                tx.multihot, cand_rows, k, self.block, want_mask=True)
        sets = []
        for ci, ids in enumerate(freq_ids):
            tokens = tuple(sorted(tx.vocab[i] for i in ids))
            tids = (
                [str(t) for t in tx.trans_ids[mask[:, ci]]]
                if self.emit_trans_id else None
            )
            sets.append(ItemSet(tokens, counts[ci] / n, int(counts[ci]), tids))
        sets.sort(key=lambda s: s.items)
        return ItemSetList(k, sets)

    @staticmethod
    def multihot_item_counts(tx: TransactionSet) -> np.ndarray:
        return tx.multihot.astype(np.int64).sum(axis=0)


# --------------------------------------------------------------------------
# Infrequent item marker
# --------------------------------------------------------------------------
class InfrequentItemMarker:
    """Replace infrequent items with a marker token after the k=1 round
    (InfrequentItemMarker.java:41-46) so later scans shrink."""

    def __init__(self, frequent_items: Iterable[str], marker: str = "*",
                 skip_field_count: int = 1):
        self.frequent = set(frequent_items)
        self.marker = marker
        self.skip = skip_field_count

    def mark_row(self, row: Sequence[str]) -> List[str]:
        out = list(row[:self.skip])
        for tok in row[self.skip:]:
            out.append(tok if tok in self.frequent else self.marker)
        return out

    def mark(self, rows: Iterable[Sequence[str]]) -> List[List[str]]:
        return [self.mark_row(r) for r in rows]


# --------------------------------------------------------------------------
# Rule mining
# --------------------------------------------------------------------------
@dataclass
class AssociationRule:
    antecedent: Tuple[str, ...]
    consequent: Tuple[str, ...]
    confidence: float
    support: float                  # support of the full itemset
    lift: float = float("nan")

    def line(self) -> str:
        return (",".join(self.antecedent) + " -> " + ",".join(self.consequent)
                + f" ({self.confidence:.4f})")


class AssociationRuleMiner:
    """Rules from frequent itemsets (AssociationRuleMiner.java:94-190):
    antecedent = each sublist up to max_ante_size, confidence =
    support(itemset) / support(antecedent), kept when above the threshold
    (arm.conf.threshold). Lift (vs the consequent's marginal support) is
    added when the consequent's support is known."""

    def __init__(self, conf_threshold: float, max_ante_size: int = 3):
        self.conf_threshold = conf_threshold
        self.max_ante_size = max_ante_size

    def mine(self, item_set_lists: Sequence[ItemSetList]
             ) -> List[AssociationRule]:
        supports: Dict[Tuple[str, ...], float] = {}
        for isl in item_set_lists:
            supports.update(isl.supports())
        rules: List[AssociationRule] = []
        for isl in item_set_lists:
            if isl.length < 2:
                continue
            for s in isl.item_sets:
                items = s.items
                for size in range(1, min(self.max_ante_size, len(items) - 1) + 1):
                    for ante in combinations(items, size):
                        ante_sup = supports.get(tuple(sorted(ante)))
                        if ante_sup is None or ante_sup <= 0:
                            continue
                        conf = s.support / ante_sup
                        if conf > self.conf_threshold:
                            cons = tuple(t for t in items if t not in ante)
                            cons_sup = supports.get(tuple(sorted(cons)))
                            lift = (conf / cons_sup) if cons_sup else float("nan")
                            rules.append(AssociationRule(
                                ante, cons, conf, s.support, lift))
        rules.sort(key=lambda r: (-r.confidence, r.antecedent, r.consequent))
        return rules
