"""Streaming reinforcement learners: the Storm/Redis layer rebuilt.

Reference (SURVEY §2.7): org/avenir/reinforce/ — an abstract
ReinforcementLearner (batch-of-actions select + reward intake,
ReinforcementLearner.java:35-166) with ten concrete learners created by
name via ReinforcementLearnerFactory.java:35-63, driven per event by a
Storm bolt that pulls queued rewards and writes selected actions to Redis
(ReinforcementLearnerBolt.java:93-125, RedisSpout.java:86-100).

This module keeps the exact learner hierarchy, factory names, and config
keys, as in-process state machines:

  intervalEstimator        histogram upper-confidence bound with decaying
                           confidence limit (IntervalEstimatorLearner.java:80-127)
  sampsonSampler           Thompson sampling by bootstrap from observed
                           rewards (SampsonSamplerLearner.java)
  optimisticSampsonSampler sampled reward floored at the action mean
  randomGreedy             ε-greedy with none/linear/logLinear ε decay
  upperConfidenceBoundOne  UCB1: avg + sqrt(2 ln t / n)
  upperConfidenceBoundTwo  UCB2 epochs: avg + sqrt((1+α)ln(e t/τ)/2τ)
  softMax                  Boltzmann with linear/logLinear temp decay
  actionPursuit            probability pursuit of the best action
  rewardComparison         preference vs drifting reference reward
  exponentialWeight        EXP3

Design note (TPU stance): a streaming learner advances one event at a time
over O(A) scalars — device dispatch would cost more than the math, so the
per-event path stays host-side numpy. The N-proportional twin — one round
over many groups — is the device-vectorized kernel set in
avenir_tpu.models.bandits; GroupedLearners below fans a shared-config
learner per group the way ReinforcementLearnerGroup.java:30 does, and the
streaming loop in avenir_tpu.streaming replaces the Storm topology with an
async host loop (SURVEY §2.12 "Storm bolts → JAX streaming loop").
"""

from __future__ import annotations

import math
import os
import warnings
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np


class Action:
    """Action with trial/reward bookkeeping (reinforce/Action.java:24)."""

    def __init__(self, action_id: str):
        self.id = action_id
        self.trial_count = 0
        self.total_reward = 0

    def select(self) -> None:
        self.trial_count += 1

    def reward(self, r: int) -> None:
        self.total_reward += r

    def __repr__(self) -> str:
        return f"Action({self.id}, trials={self.trial_count})"


class _Stat:
    """Running count/sum/avg (chombo SimpleStat role)."""

    __slots__ = ("count", "total")

    def __init__(self):
        self.count = 0
        self.total = 0.0

    def add(self, v: float) -> None:
        self.count += 1
        self.total += v

    @property
    def avg(self) -> float:
        return self.total / self.count if self.count else 0.0


class ReinforcementLearner:
    """Base: action set, batch select, min-trial forcing, reward intake
    (ReinforcementLearner.java:35-166)."""

    def __init__(self, action_ids: Sequence[str], config: Dict):
        self.actions = [Action(a) for a in action_ids]
        self.action_index = {a.id: i for i, a in enumerate(self.actions)}
        self.min_trial = int(config.get("min.trial", -1))
        self.batch_size = int(config.get("batch.size", 1))
        self.reward_scale = int(config.get("reward.scale", 1))
        self.total_trial_count = 0
        self.reward_stats: Dict[str, _Stat] = {}
        self.rewarded = False
        self.rng = np.random.default_rng(int(config.get("seed", 0)))

    # ----------------------------------------------------------- selection
    def next_actions(self) -> List[Action]:
        return [self.next_action() for _ in range(self.batch_size)]

    def next_action(self) -> Action:
        raise NotImplementedError

    def set_reward(self, action_id: str, reward: int) -> None:
        raise NotImplementedError

    def get_stat(self) -> str:
        return ""

    # ------------------------------------------------------------- helpers
    def find_action(self, action_id: str) -> Action:
        return self.actions[self.action_index[action_id]]

    def find_action_with_min_trial(self) -> Action:
        return min(self.actions, key=lambda a: a.trial_count)

    def select_action_based_on_min_trial(self) -> Optional[Action]:
        """Force round-robin until every action has min.trial trials
        (ReinforcementLearner.selectActionBasedOnMinTrial)."""
        if self.min_trial > 0:
            a = self.find_action_with_min_trial()
            if a.trial_count <= self.min_trial:
                return a
        return None

    def find_best_action(self) -> Action:
        best, best_r = self.actions[0], -1.0
        for a in self.actions:
            st = self.reward_stats.get(a.id)
            if st is not None and st.avg > best_r:
                best, best_r = a, st.avg
        return best

    def _random_action(self) -> Action:
        return self.actions[int(self.rng.integers(len(self.actions)))]

    # ----------------------------------------------------- checkpoint state
    _STATE_SKIP = {"actions", "action_index", "reward_stats", "rng", "config"}

    @staticmethod
    def _encode_state(v):
        """JSON-safe recursive encoding: numpy scalars coerce to Python,
        and int dict keys (histogram bins — possibly np.int64 from reward
        arithmetic) get an explicit marker so decode restores them as ints,
        not the strings JSON would silently make them."""
        enc_one = ReinforcementLearner._encode_state
        if isinstance(v, dict):
            enc = {str(k): enc_one(x) for k, x in v.items()}
            if v and all(isinstance(k, (int, np.integer))
                         and not isinstance(k, bool) for k in v):
                return {"__intkeys__": enc}
            return enc
        if isinstance(v, (list, tuple)):
            return [enc_one(x) for x in v]
        if isinstance(v, np.integer):
            return int(v)
        if isinstance(v, np.floating):
            return float(v)
        if isinstance(v, np.bool_):
            return bool(v)
        return v

    @staticmethod
    def _decode_state(v):
        if isinstance(v, dict):
            if set(v) == {"__intkeys__"}:
                return {int(k): ReinforcementLearner._decode_state(x)
                        for k, x in v["__intkeys__"].items()}
            if set(v) == {"__ndarray__", "dtype"}:
                return np.asarray(v["__ndarray__"], dtype=v["dtype"])
            return {k: ReinforcementLearner._decode_state(x)
                    for k, x in v.items()}
        if isinstance(v, list):
            return [ReinforcementLearner._decode_state(x) for x in v]
        return v

    def save_state(self, path: str) -> None:
        """Checkpoint the learner to JSON: per-action trial/reward counts,
        reward stats, and every numeric attribute of the concrete learner
        (weights, preferences, decayed epsilons, ...). The reference keeps
        this state only inside the Storm bolt's JVM (SURVEY §5 — Redis
        holds queues, not models); a file checkpoint makes the streaming
        loop resumable."""
        import json

        extra = {}
        for k, v in self.__dict__.items():
            if k in self._STATE_SKIP:
                continue
            if isinstance(v, np.ndarray):
                extra[k] = {"__ndarray__": v.tolist(), "dtype": str(v.dtype)}
            else:
                # anything JSON-representable is state worth carrying:
                # scalars, lists, and the dict-valued evidence the samplers
                # keep (reward_samples, histograms, epoch counts, ...)
                enc = self._encode_state(v)
                try:
                    json.dumps(enc)
                except (TypeError, ValueError):
                    # an incomplete checkpoint must be visible, not silent:
                    # resume would otherwise quietly lose this state
                    warnings.warn(
                        f"checkpoint skipping non-serializable state {k!r} "
                        f"of {type(self).__name__}")
                    continue
                extra[k] = enc
        state = {
            "learner": type(self).__name__,
            "actions": [[a.id, int(a.trial_count), self._encode_state(
                a.total_reward)] for a in self.actions],
            "reward_stats": {aid: [int(st.count), float(st.total)]
                             for aid, st in self.reward_stats.items()},
            "extra": extra,
        }
        # atomic replace: a failed dump must not destroy the previous
        # checkpoint at this path
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(state, fh)
        os.replace(tmp, path)

    def load_state(self, path: str) -> "ReinforcementLearner":
        """Restore a checkpoint written by save_state into this (same-type,
        same-action-set) learner."""
        import json

        with open(path) as fh:
            state = json.load(fh)
        if state["learner"] != type(self).__name__:
            raise ValueError(
                f"checkpoint is for {state['learner']}, not {type(self).__name__}")
        by_id = {a[0]: a for a in state["actions"]}
        for a in self.actions:
            if a.id not in by_id:
                raise ValueError(f"checkpoint missing action {a.id!r}")
            _, a.trial_count, a.total_reward = by_id[a.id]
        self.reward_stats = {}
        for aid, (count, total) in state["reward_stats"].items():
            st = _Stat()
            st.count, st.total = count, total
            self.reward_stats[aid] = st
        for k, v in state["extra"].items():
            self.__dict__[k] = self._decode_state(v)
        return self


# ---------------------------------------------------------------------------
# Learners
# ---------------------------------------------------------------------------
class RandomGreedyLearner(ReinforcementLearner):
    """ε-greedy with decaying ε (RandomGreedyLearner.java:31)."""

    def __init__(self, action_ids, config):
        super().__init__(action_ids, config)
        self.random_selection_prob = float(config.get("random.selection.prob", 0.5))
        self.prob_red_algorithm = config.get("prob.reduction.algorithm", "linear")
        self.prob_reduction_constant = float(config.get("prob.reduction.constant", 1.0))
        self.min_prob = float(config.get("min.prob", -1.0))
        for a in self.actions:
            self.reward_stats[a.id] = _Stat()

    def next_action(self) -> Action:
        self.total_trial_count += 1
        action = self.select_action_based_on_min_trial()
        if action is None:
            t = self.total_trial_count
            if self.prob_red_algorithm == "none":
                p = self.random_selection_prob
            elif self.prob_red_algorithm == "linear":
                p = self.random_selection_prob * self.prob_reduction_constant / t
            elif self.prob_red_algorithm == "logLinear":
                p = (self.random_selection_prob * self.prob_reduction_constant
                     * math.log(t) / t) if t > 1 else self.random_selection_prob
            else:
                raise ValueError(
                    f"invalid prob reduction algorithm: {self.prob_red_algorithm}")
            if self.min_prob > 0:
                p = max(p, self.min_prob)
            if self.rng.random() < p:
                action = self._random_action()
            else:
                action = self.find_best_action()
        action.select()
        return action

    def set_reward(self, action_id: str, reward: int) -> None:
        self.reward_stats[action_id].add(reward)
        self.find_action(action_id).reward(reward)


class UpperConfidenceBoundOneLearner(ReinforcementLearner):
    """UCB1: avg + sqrt(2 ln t / n); untried actions win immediately
    (UpperConfidenceBoundOneLearner.java:31)."""

    def __init__(self, action_ids, config):
        super().__init__(action_ids, config)
        self.reward_scale = int(config.get("reward.scale", 100))
        for a in self.actions:
            self.reward_stats[a.id] = _Stat()

    def _score(self, a: Action) -> float:
        if a.trial_count == 0:
            return float("inf")
        return (self.reward_stats[a.id].avg
                + math.sqrt(2.0 * math.log(self.total_trial_count)
                            / a.trial_count))

    def next_action(self) -> Action:
        self.total_trial_count += 1
        action = self.select_action_based_on_min_trial()
        if action is None:
            action = max(self.actions, key=self._score)
        action.select()
        return action

    def set_reward(self, action_id: str, reward: int) -> None:
        self.reward_stats[action_id].add(reward / self.reward_scale)
        self.find_action(action_id).reward(reward)


class UpperConfidenceBoundTwoLearner(ReinforcementLearner):
    """UCB2: epoch-committed UCB with τ = (1+α)^epochs
    (UpperConfidenceBoundTwoLearner.java:31)."""

    def __init__(self, action_ids, config):
        super().__init__(action_ids, config)
        self.reward_scale = int(config.get("reward.scale", 100))
        self.alpha = float(config.get("ucb2.alpha", 0.1))
        self.num_epochs = {a.id: 0 for a in self.actions}
        self.current: Optional[Action] = None
        self.epoch_size = 0
        self.epoch_trial_count = 0
        for a in self.actions:
            self.reward_stats[a.id] = _Stat()

    def next_action(self) -> Action:
        self.total_trial_count += 1
        action = self.select_action_based_on_min_trial()
        if action is None:
            if self.current is not None and self.epoch_trial_count < self.epoch_size:
                action = self.current
                self.epoch_trial_count += 1
            else:
                if self.current is not None:
                    self.num_epochs[self.current.id] += 1
                best, best_score = None, -float("inf")
                for a in self.actions:
                    if a.trial_count == 0:
                        score = float("inf")
                    else:
                        tao = (1.0 + self.alpha) ** self.num_epochs[a.id] \
                            if self.num_epochs[a.id] else 1.0
                        bonus = ((1 + self.alpha)
                                 * math.log(math.e * self.total_trial_count / tao)
                                 / (2 * tao))
                        score = self.reward_stats[a.id].avg + math.sqrt(max(bonus, 0.0))
                    if score > best_score:
                        best, best_score = a, score
                action = best
                ec = self.num_epochs[action.id]
                self.epoch_size = max(1, round(
                    (1.0 + self.alpha) ** (ec + 1) - (1.0 + self.alpha) ** ec))
                self.epoch_trial_count = 0
                self.current = action
        action.select()
        return action

    def set_reward(self, action_id: str, reward: int) -> None:
        self.reward_stats[action_id].add(reward / self.reward_scale)
        self.find_action(action_id).reward(reward)


class SampsonSamplerLearner(ReinforcementLearner):
    """Thompson sampling by bootstrap: sample one observed reward per action
    (uniform prior draw below min.sample.size), argmax
    (SampsonSamplerLearner.java:33)."""

    def __init__(self, action_ids, config):
        super().__init__(action_ids, config)
        self.min_sample_size = int(config.get("min.sample.size", 10))
        self.max_reward = int(config.get("max.reward", 100))
        self.reward_samples: Dict[str, List[int]] = {a.id: [] for a in self.actions}

    def enforce(self, action_id: str, reward: float) -> float:
        return reward

    def next_action(self) -> Action:
        self.total_trial_count += 1
        best_id, best_r = None, -1.0
        for a in self.actions:
            samples = self.reward_samples[a.id]
            if len(samples) > self.min_sample_size:
                r = float(samples[int(self.rng.integers(len(samples)))])
                r = self.enforce(a.id, r)
            else:
                r = self.rng.random() * self.max_reward
            if r > best_r:
                best_id, best_r = a.id, r
        action = self.find_action(best_id)
        action.select()
        return action

    def set_reward(self, action_id: str, reward: int) -> None:
        self.reward_samples[action_id].append(reward)
        self.find_action(action_id).reward(reward)


class OptimisticSampsonSamplerLearner(SampsonSamplerLearner):
    """Sampled reward floored at the action's mean
    (OptimisticSampsonSamplerLearner.java:30)."""

    def enforce(self, action_id: str, reward: float) -> float:
        samples = self.reward_samples[action_id]
        mean = sum(samples) / len(samples) if samples else 0.0
        return max(reward, mean)


class IntervalEstimatorLearner(ReinforcementLearner):
    """Histogram upper-confidence-bound with a decaying confidence limit
    (IntervalEstimatorLearner.java:80-127): random until every action has
    min.reward.distr.sample observations, then pick the max upper percentile
    bound at the current confidence limit; the limit steps down every
    confidence.limit.reduction.round.interval trials."""

    def __init__(self, action_ids, config):
        super().__init__(action_ids, config)
        self.bin_width = int(config["bin.width"])
        self.confidence_limit = int(config["confidence.limit"])
        self.min_confidence_limit = int(config["min.confidence.limit"])
        self.cur_confidence_limit = self.confidence_limit
        self.reduction_step = int(config["confidence.limit.reduction.step"])
        self.reduction_interval = int(
            config["confidence.limit.reduction.round.interval"])
        self.min_distr_sample = int(config["min.reward.distr.sample"])
        self.histograms: Dict[str, Dict[int, int]] = {
            a.id: {} for a in self.actions}
        self.sample_counts: Dict[str, int] = {a.id: 0 for a in self.actions}
        self.last_round = 1
        self.low_sample = True
        self.random_select_count = 0
        self.intv_est_select_count = 0

    def _upper_bound(self, action_id: str) -> float:
        """Value at the cur_confidence_limit upper percentile of the binned
        reward distribution (chombo HistogramStat.getConfidenceBounds role)."""
        hist = self.histograms[action_id]
        total = self.sample_counts[action_id]
        if total == 0:
            return 0.0
        upper_pct = (100.0 + self.cur_confidence_limit) / 2.0
        target = total * upper_pct / 100.0
        cum = 0
        for b in sorted(hist):
            cum += hist[b]
            if cum >= target:
                return (b + 1) * self.bin_width
        return (max(hist) + 1) * self.bin_width

    def _adjust_conf_limit(self) -> None:
        if self.cur_confidence_limit > self.min_confidence_limit:
            red_step = (self.total_trial_count - self.last_round) \
                // self.reduction_interval
            if red_step > 0:
                self.cur_confidence_limit = max(
                    self.cur_confidence_limit - red_step * self.reduction_step,
                    self.min_confidence_limit)
                self.last_round = self.total_trial_count

    def next_action(self) -> Action:
        self.total_trial_count += 1
        if self.low_sample:
            self.low_sample = any(
                self.sample_counts[a.id] < self.min_distr_sample
                for a in self.actions)
            if not self.low_sample:
                self.last_round = self.total_trial_count
        if self.low_sample:
            action = self._random_action()
            self.random_select_count += 1
        else:
            self._adjust_conf_limit()
            action = max(self.actions, key=lambda a: self._upper_bound(a.id))
            self.intv_est_select_count += 1
        action.select()
        return action

    def set_reward(self, action_id: str, reward: int) -> None:
        if action_id not in self.histograms:
            raise ValueError(f"invalid action: {action_id}")
        b = reward // self.bin_width
        self.histograms[action_id][b] = self.histograms[action_id].get(b, 0) + 1
        self.sample_counts[action_id] += 1
        self.find_action(action_id).reward(reward)

    def get_stat(self) -> str:
        return (f"randomSelectCount:{self.random_select_count} "
                f"intvEstSelectCount:{self.intv_est_select_count}")


class SoftMaxLearner(ReinforcementLearner):
    """Boltzmann selection with linear/logLinear temperature decay
    (SoftMaxLearner.java:32); distribution recomputed on new reward."""

    def __init__(self, action_ids, config):
        super().__init__(action_ids, config)
        self.temp_constant = float(config.get("temp.constant", 100.0))
        self.min_temp_constant = float(config.get("min.temp.constant", -1.0))
        self.temp_red_algorithm = config.get("temp.reduction.algorithm", "linear")
        self.probs = np.full(len(self.actions), 1.0 / len(self.actions))
        for a in self.actions:
            self.reward_stats[a.id] = _Stat()

    def next_action(self) -> Action:
        self.total_trial_count += 1
        action = self.select_action_based_on_min_trial()
        if action is None:
            if self.rewarded:
                avg = np.array([self.reward_stats[a.id].avg for a in self.actions])
                # temp underflows to 0 under the compounding decay schedule;
                # the zero-temperature limit is argmax selection, not NaN
                t = max(self.temp_constant, 1e-12)
                e = np.exp((avg - avg.max()) / t)
                self.probs = e / e.sum()
                self.rewarded = False
            action = self.actions[
                int(self.rng.choice(len(self.actions), p=self.probs))]
            soft_max_round = self.total_trial_count - max(self.min_trial, 0)
            if soft_max_round > 1:
                if self.temp_red_algorithm == "linear":
                    self.temp_constant /= soft_max_round
                elif self.temp_red_algorithm == "logLinear":
                    self.temp_constant *= math.log(soft_max_round) / soft_max_round
                if 0 < self.min_temp_constant and \
                        self.temp_constant < self.min_temp_constant:
                    self.temp_constant = self.min_temp_constant
                self.temp_constant = max(self.temp_constant, 0.0)
        action.select()
        return action

    def set_reward(self, action_id: str, reward: int) -> None:
        self.reward_stats[action_id].add(reward)
        self.find_action(action_id).reward(reward)
        self.rewarded = True


class ActionPursuitLearner(ReinforcementLearner):
    """Pursuit: shift selection probability toward the best-avg action
    (ActionPursuitLearner.java:32)."""

    def __init__(self, action_ids, config):
        super().__init__(action_ids, config)
        self.learning_rate = float(config.get("pursuit.learning.rate", 0.05))
        self.probs = np.full(len(self.actions), 1.0 / len(self.actions))
        for a in self.actions:
            self.reward_stats[a.id] = _Stat()

    def next_action(self) -> Action:
        self.total_trial_count += 1
        if self.rewarded:
            best = self.find_best_action()
            bi = self.action_index[best.id]
            lr = self.learning_rate
            self.probs = self.probs - lr * self.probs
            self.probs[bi] += lr
            self.probs /= self.probs.sum()
            self.rewarded = False
        action = self.actions[
            int(self.rng.choice(len(self.actions), p=self.probs))]
        action.select()
        return action

    def set_reward(self, action_id: str, reward: int) -> None:
        self.reward_stats[action_id].add(reward)
        self.find_action(action_id).reward(reward)
        self.rewarded = True


class RewardComparisonLearner(ReinforcementLearner):
    """Preference learning vs a drifting reference reward
    (RewardComparisonLearner.java:32): on reward, pref += rate*(mean - ref),
    ref += refRate*(mean - ref); selection ∝ exp(pref)."""

    def __init__(self, action_ids, config):
        super().__init__(action_ids, config)
        self.preference_change_rate = float(
            config.get("preference.change.rate", 0.01))
        self.ref_reward_change_rate = float(
            config.get("reference.reward.change.rate", 0.01))
        self.ref_reward = float(config.get("intial.reference.reward", 100.0))
        self.prefs = np.zeros(len(self.actions))
        self.probs = np.full(len(self.actions), 1.0 / len(self.actions))
        for a in self.actions:
            self.reward_stats[a.id] = _Stat()

    def next_action(self) -> Action:
        self.total_trial_count += 1
        if self.rewarded:
            e = np.exp(self.prefs - self.prefs.max())
            self.probs = e / e.sum()
            self.rewarded = False
        action = self.actions[
            int(self.rng.choice(len(self.actions), p=self.probs))]
        action.select()
        return action

    def set_reward(self, action_id: str, reward: int) -> None:
        st = self.reward_stats[action_id]
        st.add(reward)
        self.find_action(action_id).reward(reward)
        mean = st.avg
        i = self.action_index[action_id]
        self.prefs[i] += self.preference_change_rate * (mean - self.ref_reward)
        self.ref_reward += self.ref_reward_change_rate * (mean - self.ref_reward)
        self.rewarded = True


class ExponentialWeightLearner(ReinforcementLearner):
    """EXP3 (ExponentialWeightLearner.java:32): p = (1-γ)w/Σw + γ/K,
    w *= exp(γ (r/p)/K) on reward. distr.constant is γ ∈ (0, 1]."""

    def __init__(self, action_ids, config):
        super().__init__(action_ids, config)
        self.gamma = float(config.get("distr.constant", 0.1))
        self.weights = np.ones(len(self.actions))
        self.probs = np.full(len(self.actions), 1.0 / len(self.actions))

    def next_action(self) -> Action:
        self.total_trial_count += 1
        if self.rewarded:
            k = len(self.actions)
            w = self.weights / self.weights.sum()
            self.probs = (1.0 - self.gamma) * w + self.gamma / k
            self.probs /= self.probs.sum()
            self.rewarded = False
        action = self.actions[
            int(self.rng.choice(len(self.actions), p=self.probs))]
        action.select()
        return action

    def set_reward(self, action_id: str, reward: int) -> None:
        self.find_action(action_id).reward(reward)
        i = self.action_index[action_id]
        scaled = reward / self.reward_scale
        k = len(self.actions)
        self.weights[i] *= math.exp(
            min(self.gamma * (scaled / max(self.probs[i], 1e-12)) / k, 700.0))
        # renormalize: only weight ratios matter, and unbounded growth
        # overflows to inf/NaN on long streams
        self.weights /= self.weights.max()
        self.rewarded = True


# ---------------------------------------------------------------------------
# Factory + groups
# ---------------------------------------------------------------------------
_LEARNERS: Dict[str, Callable] = {
    "intervalEstimator": IntervalEstimatorLearner,
    "sampsonSampler": SampsonSamplerLearner,
    "optimisticSampsonSampler": OptimisticSampsonSamplerLearner,
    "randomGreedy": RandomGreedyLearner,
    "upperConfidenceBoundOne": UpperConfidenceBoundOneLearner,
    "upperConfidenceBoundTwo": UpperConfidenceBoundTwoLearner,
    "softMax": SoftMaxLearner,
    "actionPursuit": ActionPursuitLearner,
    "rewardComparison": RewardComparisonLearner,
    "exponentialWeight": ExponentialWeightLearner,
}


def create_learner(learner_type: str, action_ids: Sequence[str],
                   config: Dict) -> ReinforcementLearner:
    """ReinforcementLearnerFactory.create (same type names,
    ReinforcementLearnerFactory.java:35-63)."""
    if learner_type not in _LEARNERS:
        raise ValueError(f"invalid learner type: {learner_type}")
    return _LEARNERS[learner_type](action_ids, config)


class GroupedLearners:
    """One learner per group id, shared config
    (ReinforcementLearnerGroup.java:30)."""

    def __init__(self, learner_type: str, action_ids: Sequence[str],
                 config: Dict):
        self.learner_type = learner_type
        self.action_ids = list(action_ids)
        self.config = dict(config)
        self.learners: Dict[str, ReinforcementLearner] = {}

    def get(self, group_id: str) -> ReinforcementLearner:
        if group_id not in self.learners:
            cfg = dict(self.config)
            cfg["seed"] = int(self.config.get("seed", 0)) + len(self.learners)
            self.learners[group_id] = create_learner(
                self.learner_type, self.action_ids, cfg)
        return self.learners[group_id]
