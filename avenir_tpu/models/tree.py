"""Decision tree / random forest: level-wise builder with tensorized splits.

Reference semantics (org.avenir.tree, SURVEY §2.3/§3.4):
- DecisionTreeBuilder is an *iterative MR job*, one tree level per run: the
  mapper routes every record through every candidate split predicate of
  every candidate attribute, emitting (path-so-far, splitId:predicate) keys;
  the reducer accumulates per-(path, split, predicate) class histograms and
  picks the min weighted-entropy/gini split per parent
  (DecisionTreeBuilder.java:258-347, :440-576). State between levels is a
  DecisionPathList JSON file rotated by resource/detr.sh:34-41.
- SplitManager enumerates candidate splits: numeric attributes partition
  [min,max] into up to maxSplit segments at splitScanInterval boundaries
  (SplitManager.java:284-391); categoricals enumerate set partitions into
  2..maxSplit groups (:397-561). Predicates serialize as "attr op value
  [otherBound]" / "attr in a:b:c" strings.
- Stopping: maxDepth / minPopulation / minInfoGain
  (DecisionPathStoppingStrategy.java:57-70). Random forest = first-pass
  sampling (with/without replacement) + per-level random attribute selection
  (DecisionTreeBuilder.java:200-236, :353-369).

TPU design: candidate splits are static (schema-driven), so each split is a
record->segment mapping computed ONCE as an int8 matrix [n, n_splits]; a
tree level is then a single one-hot einsum producing the histogram tensor
[leaves, splits, segments, classes] — no predicate branching, no shuffle.
The host picks best splits / applies stopping (tiny tensors) and updates the
on-device leaf assignment by gathering the winning split's segment column.
Random forest reuses the same segment matrix across trees with per-tree row
weights (bootstrap counts) and attribute masks.

Model format: DecisionPathList-compatible JSON (jackson field names), so
reference decPathOut.txt files and ours are interchangeable.
"""

from __future__ import annotations

import itertools
import json
import math
import os
from dataclasses import dataclass, field
from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from avenir_tpu.core.dataset import Dataset
from avenir_tpu.core.schema import FeatureField, FeatureSchema
from avenir_tpu.utils.metrics import ConfusionMatrix

ROOT_PATH = "$root"


def _np_bits_entropy(counts: np.ndarray, axis: int = -1) -> np.ndarray:
    """Host twin of ops.infotheory.bits_entropy for the builder's tiny
    per-level stat tensors — an eager device dispatch per level/leaf costs
    more than the arithmetic (remote-chip dispatch latency)."""
    tot = counts.sum(axis=axis, keepdims=True)
    p = counts / np.maximum(tot, 1e-12)
    h = -np.sum(np.where(p > 0, p * np.log(np.maximum(p, 1e-12)), 0.0), axis=axis)
    return h / np.log(2.0)


def _np_gini(counts: np.ndarray, axis: int = -1) -> np.ndarray:
    tot = counts.sum(axis=axis, keepdims=True)
    p = counts / np.maximum(tot, 1e-12)
    return 1.0 - np.sum(p * p, axis=axis)

# ---------------------------------------------------------------------------
# candidate split enumeration (host; SplitManager semantics)
# ---------------------------------------------------------------------------


@dataclass
class Predicate:
    """One predicate of one split segment ("attr op value [other]" form)."""

    attribute: int
    operator: str                       # ge / lt / in  (segment predicates)
    value: Optional[float] = None
    other_bound: Optional[float] = None
    cat_values: List[str] = field(default_factory=list)
    is_int: bool = True

    def to_string(self) -> str:
        if self.operator == "in":
            return f"{self.attribute} in " + ":".join(self.cat_values)
        fmt = (lambda v: str(int(v))) if self.is_int else (lambda v: str(v))
        s = f"{self.attribute} {self.operator} {fmt(self.value)}"
        if self.other_bound is not None:
            s += f" {fmt(self.other_bound)}"
        return s

    def to_json(self) -> Dict:
        obj: Dict = {"attribute": self.attribute, "operator": self.operator,
                     "predicateStr": self.to_string()}
        if self.operator == "in":
            obj["categoricalValues"] = list(self.cat_values)
        elif self.is_int:
            obj["valueInt"] = int(self.value)
            if self.other_bound is not None:
                obj["otherBoundInt"] = int(self.other_bound)
        else:
            obj["valueDbl"] = float(self.value)
            if self.other_bound is not None:
                obj["otherBoundDbl"] = float(self.other_bound)
        return obj


@dataclass
class CandidateSplit:
    """One candidate split of one attribute into `n_segments` segments.

    `segment_of` maps a raw column (numpy) to segment ids; `predicates[s]`
    is the predicate describing segment s."""

    attribute: int
    split_id: int
    n_segments: int
    predicates: List[Predicate]
    _kind: str = "numeric"
    _bounds: Optional[np.ndarray] = None        # numeric: inner boundaries
    _group_of: Optional[np.ndarray] = None      # categorical: code -> group

    def segment_of(self, col: np.ndarray) -> np.ndarray:
        if self._kind == "numeric":
            return np.searchsorted(self._bounds, col, side="right").astype(np.int8)
        return self._group_of[col.astype(np.int64)].astype(np.int8)


def _numeric_splits(fld: FeatureField, max_split: int) -> List[List[float]]:
    """All partitions of [min, max] into 2..max_split segments with
    boundaries at splitScanInterval steps (SplitManager.java:284-391)."""
    lo, hi = fld.min, fld.max
    interval = fld.split_scan_interval or fld.bucket_width
    if lo is None or hi is None or not interval:
        return []
    points = []
    p = lo + interval
    while p < hi - 1e-9:
        points.append(p)
        p += interval
    out: List[List[float]] = []
    for nseg in range(2, max_split + 1):
        for combo in itertools.combinations(points, nseg - 1):
            out.append(list(combo))
    return out


def _set_partitions(items: Sequence[str], max_groups: int,
                    cap: int = 128) -> List[List[List[str]]]:
    """Partitions of a category set into 2..max_groups groups
    (SplitManager.java:397-561), capped to avoid blow-up."""
    n = len(items)
    results: List[List[List[str]]] = []
    # enumerate by group-assignment vectors in canonical form
    seen = set()
    max_groups = min(max_groups, n)

    def assignments(prefix, next_group):
        if len(results) >= cap:
            return
        if len(prefix) == n:
            ngroups = next_group
            if 2 <= ngroups <= max_groups:
                key = tuple(prefix)
                if key not in seen:
                    seen.add(key)
                    groups: List[List[str]] = [[] for _ in range(ngroups)]
                    for i, g in enumerate(prefix):
                        groups[g].append(items[i])
                    results.append(groups)
            return
        for g in range(next_group + 1):
            if g > max_groups - 1:
                continue
            assignments(prefix + [g], max(next_group, g + 1))

    assignments([], 0)
    return results


def enumerate_splits(schema: FeatureSchema,
                     cat_partition_cap: int = 128) -> List[CandidateSplit]:
    """All candidate splits of all feature attributes, in stable order."""
    splits: List[CandidateSplit] = []
    sid = 0
    for fld in schema.feature_fields:
        max_split = fld.max_split or 2
        if fld.is_numeric:
            for bounds in _numeric_splits(fld, max_split):
                preds = []
                is_int = fld.data_type == "int"
                for s in range(len(bounds) + 1):
                    if s == 0:
                        preds.append(Predicate(fld.ordinal, "lt", bounds[0],
                                               is_int=is_int))
                    elif s == len(bounds):
                        preds.append(Predicate(fld.ordinal, "ge", bounds[-1],
                                               is_int=is_int))
                    else:
                        preds.append(Predicate(fld.ordinal, "ge", bounds[s - 1],
                                               other_bound=bounds[s], is_int=is_int))
                splits.append(CandidateSplit(
                    fld.ordinal, sid, len(bounds) + 1, preds,
                    _kind="numeric", _bounds=np.asarray(bounds),
                ))
                sid += 1
        elif fld.is_categorical and len(fld.cardinality) >= 2:
            for groups in _set_partitions(fld.cardinality, max_split,
                                          cap=cat_partition_cap):
                group_of = np.zeros(len(fld.cardinality), np.int64)
                preds = []
                index = fld.cardinality_index()
                for g, members in enumerate(groups):
                    for m in members:
                        group_of[index[m]] = g
                    preds.append(Predicate(fld.ordinal, "in",
                                           cat_values=list(members)))
                splits.append(CandidateSplit(
                    fld.ordinal, sid, len(groups), preds,
                    _kind="categorical", _group_of=group_of,
                ))
                sid += 1
    return splits


# ---------------------------------------------------------------------------
# the level histogram kernel
# ---------------------------------------------------------------------------


@partial(jax.jit, static_argnames=("n_leaves", "n_splits", "smax", "k"))
def _level_histogram(leaf_id, seg_matrix, labels, weights,
                     n_leaves: int, n_splits: int, smax: int, k: int):
    """counts[l, s, seg, c] for all leaves x splits x segments x classes in
    one segment_sum — the whole MR shuffle of one tree level."""
    # combined key: ((leaf * n_splits + split) * smax + segment) * k + class
    base = (leaf_id.astype(jnp.int32) * n_splits)[:, None] + jnp.arange(n_splits)[None, :]
    key = (base * smax + seg_matrix.astype(jnp.int32)) * k + labels[:, None]
    flat = jax.ops.segment_sum(
        jnp.broadcast_to(weights[:, None], key.shape).reshape(-1),
        key.reshape(-1),
        num_segments=n_leaves * n_splits * smax * k,
    )
    return flat.reshape(n_leaves, n_splits, smax, k)


@partial(jax.jit, static_argnames=())
def _advance_leaves(leaf_id, seg_matrix, best_split_of_leaf, child_offset):
    """new_leaf = child_offset[leaf] + segment under the leaf's chosen split;
    leaves without a split (stopped/unsplit) keep a fixed id via offset -1."""
    split = best_split_of_leaf[leaf_id]                       # [n]
    seg = jnp.take_along_axis(
        seg_matrix, jnp.maximum(split, 0)[:, None], axis=1
    )[:, 0].astype(jnp.int32)
    off = child_offset[leaf_id]
    return jnp.where(split >= 0, off + seg, leaf_id)


@partial(jax.jit, static_argnames=("n_leaves", "n_splits", "smax", "k"))
def _level_histogram_forest(leaf_ids, seg_matrix, labels, weights,
                            n_leaves: int, n_splits: int, smax: int, k: int):
    """[T, L, NS, S, K]: every tree's level histogram in ONE dispatch.

    The forest's trees differ only in leaf routing and bootstrap row
    weights; the segment matrix and labels are shared, so vmapping over
    (leaf_ids, weights) turns T histogram round-trips per level into one —
    the per-level dispatch latency (the reference's one-MR-job-per-level
    cost, detr.sh:34-54) stops multiplying by the tree count."""
    return jax.vmap(
        lambda lid, w: _level_histogram(lid, seg_matrix, labels, w,
                                        n_leaves, n_splits, smax, k)
    )(leaf_ids, weights)


@jax.jit
def _advance_leaves_forest(leaf_ids, seg_matrix, best_split_of_leaf,
                           child_offset):
    """Vmapped _advance_leaves over the tree axis ([T, n] leaf ids)."""
    return jax.vmap(
        lambda lid, b, c: _advance_leaves(lid, seg_matrix, b, c)
    )(leaf_ids, best_split_of_leaf, child_offset)


# ---------------------------------------------------------------------------
# model: DecisionPathList-compatible
# ---------------------------------------------------------------------------


@dataclass
class DecisionPath:
    predicates: List[Predicate]        # empty -> root
    population: int
    info_content: float
    stopped: bool
    class_val_pr: Dict[str, float]

    def to_json(self) -> Dict:
        return {
            "predicates": [p.to_json() for p in self.predicates] or None,
            "population": int(self.population),
            "infoContent": float(self.info_content),
            "stopped": bool(self.stopped),
            "classValPr": {k: float(v) for k, v in self.class_val_pr.items()},
        }


class DecisionPathList:
    """The JSON tree model (reference tree/DecisionPathList.java format)."""

    def __init__(self, paths: List[DecisionPath]):
        self.paths = paths

    def to_json(self) -> Dict:
        return {"decisionPaths": [p.to_json() for p in self.paths]}

    def save(self, path: str) -> None:
        with open(path, "w") as fh:
            json.dump(self.to_json(), fh, indent=1)

    @classmethod
    def from_json(cls, obj: Dict) -> "DecisionPathList":
        paths = []
        for p in obj["decisionPaths"]:
            preds = []
            for pr in (p.get("predicates") or []):
                op = pr["operator"]
                if op == "in":
                    pred = Predicate(pr["attribute"], "in",
                                     cat_values=pr.get("categoricalValues", []))
                elif "valueInt" in pr and pr.get("valueInt") is not None:
                    pred = Predicate(pr["attribute"], op,
                                     value=pr["valueInt"],
                                     other_bound=pr.get("otherBoundInt"),
                                     is_int=True)
                else:
                    pred = Predicate(pr["attribute"], op,
                                     value=pr.get("valueDbl"),
                                     other_bound=pr.get("otherBoundDbl"),
                                     is_int=False)
                preds.append(pred)
            paths.append(DecisionPath(
                preds, p.get("population", 0), p.get("infoContent", 0.0),
                p.get("stopped", False), p.get("classValPr", {}) or {},
            ))
        return cls(paths)

    @classmethod
    def load(cls, path: str) -> "DecisionPathList":
        with open(path) as fh:
            return cls.from_json(json.load(fh))

    # ------------------------------------------------------------ prediction
    def predict(self, ds: Dataset, class_values: List[str]) -> np.ndarray:
        """Route every record down its matching path; argmax classValPr."""
        n = len(ds)
        pred = np.zeros(n, np.int32)
        assigned = np.zeros(n, bool)
        for path in self.paths:
            mask = np.ones(n, bool)
            for pr in path.predicates:
                col = ds.column(pr.attribute)
                if pr.operator == "in":
                    fld = ds.schema.field_by_ordinal(pr.attribute)
                    codes = {fld.cardinality_index()[v] for v in pr.cat_values
                             if v in fld.cardinality_index()}
                    mask &= np.isin(col.astype(np.int64), list(codes))
                else:
                    x = col.astype(np.float64)
                    if pr.operator == "ge":
                        m = x >= pr.value
                        if pr.other_bound is not None:
                            m &= x < pr.other_bound
                    elif pr.operator == "lt":
                        m = x < pr.value
                        if pr.other_bound is not None:
                            m &= x >= pr.other_bound
                    elif pr.operator == "gt":
                        m = x > pr.value
                        if pr.other_bound is not None:
                            m &= x <= pr.other_bound
                    else:  # le
                        m = x <= pr.value
                        if pr.other_bound is not None:
                            m &= x > pr.other_bound
                    mask &= m
            if path.class_val_pr:
                best = max(path.class_val_pr.items(), key=lambda kv: kv[1])[0]
                ci = class_values.index(best)
                take = mask & ~assigned
                pred[take] = ci
                assigned |= mask
        return pred


# ---------------------------------------------------------------------------
# device path evaluation (tensorized predict)
# ---------------------------------------------------------------------------

_OP_CODE = {"ge": 0, "lt": 1, "gt": 2, "le": 3}


@partial(jax.jit, static_argnames=())
def _path_match_kernel(x_num, x_cat, kind, col, op, val, other, member):
    """matches[n, T, P]: does row n satisfy every predicate of path P of
    tree T. One batched comparison routes all rows through all paths'
    predicates at once — the device twin of the reference's pass-through
    classify (DecisionTreeBuilder.java:700-705) without the per-path host
    loop.

    x_num f32 [n, An], x_cat i32 [n, Ac]; predicate tables [T, P, D]
    (+ member [T, P, D, B]); kind 0 = unused slot (always true)."""
    xn = x_num[:, None, None, None, :]            # [n,1,1,1,An]
    xv = jnp.take_along_axis(
        jnp.broadcast_to(xn, xn.shape[:3] + (1, xn.shape[-1])),
        jnp.maximum(col, 0)[None, ..., None], axis=-1)[..., 0]   # [n,T,P,D]
    v, o = val[None], other[None]
    ge = xv >= v
    lt = xv < v
    gt = xv > v
    le = xv <= v
    has_other = jnp.isfinite(o)
    num_ok = jnp.select(
        [op[None] == 0, op[None] == 1, op[None] == 2],
        [ge & jnp.where(has_other, xv < o, True),
         lt & jnp.where(has_other, xv >= o, True),
         gt & jnp.where(has_other, xv <= o, True)],
        le & jnp.where(has_other, xv > o, True),
    )
    code = jnp.take_along_axis(
        jnp.broadcast_to(x_cat[:, None, None, None, :],
                         (x_cat.shape[0],) + col.shape + (x_cat.shape[1],)),
        jnp.maximum(col, 0)[None, ..., None], axis=-1)[..., 0]   # [n,T,P,D]
    cat_ok = jnp.take_along_axis(
        jnp.broadcast_to(member[None],
                         (x_cat.shape[0],) + member.shape),
        jnp.clip(code, 0, member.shape[-1] - 1)[..., None], axis=-1)[..., 0]
    ok = jnp.where(kind[None] == 1, num_ok,
                   jnp.where(kind[None] == 2, cat_ok, True))
    return jnp.all(ok, axis=-1)                   # [n, T, P]


class DevicePathEvaluator:
    """Tensorized application of one or more DecisionPathList models.

    Compiles the trees' predicate chains into padded tables [T, P, D]
    (trees x paths x chain depth) so prediction is one jitted kernel:
    every row x every path evaluates as a batched comparison, first
    matching path in path order wins (the host predict's assignment
    order), and a forest majority-votes across the tree axis."""

    def __init__(self, trees: Sequence[DecisionPathList],
                 schema: FeatureSchema, class_values: List[str]):
        self.schema = schema
        self.class_values = class_values
        num_fields = [f for f in schema.feature_fields if f.is_numeric]
        cat_fields = [f for f in schema.feature_fields if f.is_categorical]
        self.num_fields, self.cat_fields = num_fields, cat_fields
        num_col = {f.ordinal: i for i, f in enumerate(num_fields)}
        cat_col = {f.ordinal: i for i, f in enumerate(cat_fields)}
        bmax = max((len(f.cardinality) for f in cat_fields), default=1)
        t = len(trees)
        p = max((len(tr.paths) for tr in trees), default=1) or 1
        d = max((len(pa.predicates) for tr in trees for pa in tr.paths),
                default=1) or 1
        kind = np.zeros((t, p, d), np.int8)
        col = np.zeros((t, p, d), np.int32)
        op = np.zeros((t, p, d), np.int8)
        val = np.zeros((t, p, d), np.float32)
        other = np.full((t, p, d), np.nan, np.float32)
        member = np.ones((t, p, d, bmax), bool)
        path_class = np.zeros((t, p), np.int32)
        path_valid = np.zeros((t, p), bool)
        for ti, tr in enumerate(trees):
            for pi, pa in enumerate(tr.paths):
                if pa.class_val_pr:
                    best = max(pa.class_val_pr.items(), key=lambda kv: kv[1])[0]
                    path_class[ti, pi] = class_values.index(best)
                    path_valid[ti, pi] = True
                for di, pr in enumerate(pa.predicates):
                    if pr.operator == "in":
                        kind[ti, pi, di] = 2
                        col[ti, pi, di] = cat_col[pr.attribute]
                        fld = schema.field_by_ordinal(pr.attribute)
                        idx = fld.cardinality_index()
                        row = np.zeros(bmax, bool)
                        for v in pr.cat_values:
                            if v in idx:
                                row[idx[v]] = True
                        member[ti, pi, di] = row
                    else:
                        kind[ti, pi, di] = 1
                        col[ti, pi, di] = num_col[pr.attribute]
                        op[ti, pi, di] = _OP_CODE[pr.operator]
                        val[ti, pi, di] = pr.value
                        if pr.other_bound is not None:
                            other[ti, pi, di] = pr.other_bound
        self.tables = tuple(jnp.asarray(a) for a in
                            (kind, col, op, val, other, member))
        self.path_class = jnp.asarray(path_class)
        self.path_valid = jnp.asarray(path_valid)
        self.n_trees = t

    def _features(self, ds: Dataset):
        # a dummy column keeps the gather axes non-empty for schemas with
        # no numeric (or no categorical) features; kind masks it out
        x_num = np.stack(
            [ds.column(f.ordinal).astype(np.float32) for f in self.num_fields],
            axis=1) if self.num_fields else np.zeros((len(ds), 1), np.float32)
        x_cat = np.stack(
            [ds.column(f.ordinal).astype(np.int32) for f in self.cat_fields],
            axis=1) if self.cat_fields else np.zeros((len(ds), 1), np.int32)
        # host arrays: per_tree_predict transfers one row block at a time,
        # so device memory stays bounded at any corpus size
        return x_num, x_cat

    def per_tree_predict(self, ds: Dataset,
                         row_block: int = 262_144) -> np.ndarray:
        """[n, T] predicted class codes, first matching path in path order
        (rows matching no valid path predict class 0, as the host loop).
        Rows evaluate in `row_block` chunks: the kernel's broadcast
        intermediates are O(rows x trees x paths x depth), so blocking
        keeps device memory bounded at any corpus size."""
        x_num, x_cat = self._features(ds)
        out = []
        for s in range(0, len(ds), row_block):
            matches = _path_match_kernel(jnp.asarray(x_num[s:s + row_block]),
                                         jnp.asarray(x_cat[s:s + row_block]),
                                         *self.tables)
            matches = matches & self.path_valid[None]
            first = jnp.argmax(matches, axis=-1)                # [b, T]
            pred = jnp.take_along_axis(
                jnp.broadcast_to(self.path_class[None], matches.shape),
                first[..., None], axis=-1)[..., 0]
            any_match = matches.any(axis=-1)
            out.append(np.asarray(
                jnp.where(any_match, pred, 0).astype(jnp.int32)))
        return np.concatenate(out) if out else np.zeros((0, self.n_trees),
                                                        np.int32)

    def predict(self, ds: Dataset) -> np.ndarray:
        """[n] class codes: single tree pass-through, or majority vote
        across trees (RandomForestBuilder.predict semantics)."""
        per_tree = self.per_tree_predict(ds)
        if self.n_trees == 1:
            return per_tree[:, 0]
        k = len(self.class_values)
        votes = np.zeros((per_tree.shape[0], k), np.int64)
        rows = np.arange(per_tree.shape[0], dtype=np.int32)
        for t in range(per_tree.shape[1]):
            votes[rows, per_tree[:, t]] += 1
        return votes.argmax(axis=1).astype(np.int32)


# ---------------------------------------------------------------------------
# builder
# ---------------------------------------------------------------------------


class DecisionTreeBuilder:
    """dtb.* job equivalent: level-wise tree growth, all state in-process."""

    def __init__(
        self,
        schema: FeatureSchema,
        split_algorithm: str = "entropy",          # or giniIndex
        max_depth: int = 3,
        min_info_gain: float = -1.0,
        min_population: int = -1,
        stopping_strategy: str = "maxDepth",
        attr_selection_strategy: str = "notUsedYet",
        cat_partition_cap: int = 128,
        seed: int = 0,
    ):
        self.schema = schema
        self.algo = split_algorithm
        self.max_depth = max_depth
        self.min_info_gain = min_info_gain
        self.min_population = min_population
        self.stopping = stopping_strategy
        self.attr_strategy = attr_selection_strategy
        self.class_values = schema.class_values()
        self.splits = enumerate_splits(schema, cat_partition_cap)
        self.smax = max((s.n_segments for s in self.splits), default=2)
        self.rng = np.random.default_rng(seed)

    # ------------------------------------------------------------------- fit
    def fit(self, ds: Dataset, row_weights: Optional[np.ndarray] = None,
            mesh=None) -> DecisionPathList:
        """Build the tree. With `mesh`, the row tensors shard over the mesh
        and every per-level histogram reduction runs SPMD — XLA inserts the
        psum the reference's shuffle performed (zero-weight rows pad to
        shard divisibility, so counts are exact)."""
        n = len(ds)
        k = len(self.class_values)
        ns = len(self.splits)
        seg = np.stack(
            [sp.segment_of(np.asarray(ds.column(sp.attribute))) for sp in self.splits],
            axis=1,
        ).astype(np.int8)                                     # [n, NS]
        labels = ds.labels()
        w_host = (row_weights.astype(np.float32) if row_weights is not None
                  else np.ones(n, np.float32))
        if mesh is not None:
            from avenir_tpu.parallel.mesh import shard_rows

            seg_d = shard_rows(mesh, seg)
            labels_d = shard_rows(mesh, labels)
            w = shard_rows(mesh, w_host)          # pad rows weigh 0
            leaf_id = shard_rows(mesh, np.zeros(len(ds), np.int32))
        else:
            seg_d = jnp.asarray(seg)
            labels_d = jnp.asarray(labels)
            w = jnp.asarray(w_host)
            leaf_id = jnp.zeros(n, jnp.int32)

        # host-side tree state: leaf -> (predicate chain, used attrs)
        leaves: List[Dict] = [{"preds": [], "used": set(), "stopped": False}]

        for depth in range(self.max_depth):
            if not self._active_leaves(leaves):
                break
            # pad the leaf axis to the next power of two: n_leaves is a
            # static (compile-time) dimension, and letting it take every
            # integer value would recompile the histogram per level and
            # per tree (each compile costs tens of seconds on a remote
            # chip); padded segment ids receive no rows
            lpad = 1 << (len(leaves) - 1).bit_length()
            counts = np.asarray(_level_histogram(
                leaf_id, seg_d, labels_d, w, lpad, ns, self.smax, k
            ))[: len(leaves)]                                 # [L, NS, S, K]
            best_split_of_leaf, child_offset, new_leaves = self._grow_level(
                leaves, counts, lpad)
            if not new_leaves:
                break
            # materialize finished leaves for paths that stopped this level
            leaf_id = _advance_leaves(
                leaf_id, seg_d,
                jnp.asarray(best_split_of_leaf), jnp.asarray(child_offset),
            )
            # children get smax slots per split parent; re-index leaves
            leaves = leaves + new_leaves

        counts_final = np.asarray(_level_histogram(
            leaf_id, seg_d, labels_d, w,
            1 << (len(leaves) - 1).bit_length(), max(ns, 1), self.smax, k
        ))[: len(leaves)] if ns else None
        return self._emit_paths(leaves, counts_final)

    @staticmethod
    def _active_leaves(leaves: List[Dict]) -> List[int]:
        return [i for i, lf in enumerate(leaves)
                if not lf["stopped"] and "split" not in lf]

    def _grow_level(self, leaves: List[Dict], counts: np.ndarray, lpad: int
                    ) -> Tuple[np.ndarray, np.ndarray, List[Dict]]:
        """Host-side split selection for one level, given the [L, NS, S, K]
        class histogram of every (leaf, candidate split, segment). Returns
        (best_split_of_leaf [lpad], child_offset [lpad], new_leaves);
        mutates `leaves` entries (split chosen / stopped)."""
        k = len(self.class_values)
        ns = len(self.splits)
        impurity_fn = (_np_bits_entropy if self.algo in ("entropy", "infoGain")
                       else _np_gini)
        seg_tot = counts.sum(axis=3)                      # [L, NS, S]
        leaf_tot = seg_tot.sum(axis=2)                    # [L, NS] (same per split)

        # weighted impurity per (leaf, split)
        imp = impurity_fn(counts, axis=-1)                # [L,NS,S]
        wimp = (seg_tot * imp).sum(axis=2) / np.maximum(leaf_tot, 1e-9)

        # lpad-sized for the same compile-stability reason as counts
        best_split_of_leaf = np.full(lpad, -1, np.int32)
        child_offset = np.full(lpad, -1, np.int32)
        new_leaves: List[Dict] = []

        for li in self._active_leaves(leaves):
            lf = leaves[li]
            pop = float(leaf_tot[li].max())
            # class counts of this leaf: any split column's segment-sum
            cls_counts = (counts[li, 0].sum(axis=0) if ns
                          else np.zeros(k, np.float64))
            node_imp = float(impurity_fn(cls_counts))

            allowed = self._allowed_splits(lf)
            if pop <= 0 or not allowed or node_imp <= 0.0:
                # pure nodes cannot improve; splitting them only burns
                # device passes and bloats the path list
                lf["stopped"] = True
                continue
            cand = wimp[li, allowed]
            bi = int(allowed[int(np.argmin(cand))])
            gain = node_imp - float(wimp[li, bi])

            # stopping strategies (DecisionPathStoppingStrategy.java:57-70;
            # maxDepth is enforced by the level-loop bound itself)
            stop = False
            if self.stopping == "minInfoGain" and self.min_info_gain >= 0:
                stop = gain < self.min_info_gain
            elif self.stopping == "minPopulation" and self.min_population >= 0:
                stop = pop < self.min_population
            if stop:
                lf["stopped"] = True
                continue

            sp = self.splits[bi]
            best_split_of_leaf[li] = bi
            child_offset[li] = len(leaves) + len(new_leaves)
            for s in range(self.smax):
                if s < sp.n_segments:
                    new_leaves.append({
                        "preds": lf["preds"] + [sp.predicates[s]],
                        "used": lf["used"] | {sp.attribute},
                        "stopped": False,
                    })
                else:
                    # pad children so child ids stay contiguous per leaf;
                    # never emitted as paths (no rows can route here)
                    new_leaves.append({"preds": lf["preds"], "used": lf["used"],
                                       "stopped": True, "pad": True})
            lf["split"] = bi           # parent becomes an internal node
        return best_split_of_leaf, child_offset, new_leaves

    def _emit_paths(self, leaves: List[Dict],
                    counts_final: Optional[np.ndarray]) -> DecisionPathList:
        """Final paths: any leaf never split, with class distribution from
        the final level histogram."""
        k = len(self.class_values)
        model_paths: List[DecisionPath] = []
        for li, lf in enumerate(leaves):
            if "split" in lf or lf.get("pad"):
                continue                   # internal node / padded child slot
            cls_counts = (
                counts_final[li, 0].sum(axis=0)
                if counts_final is not None else np.zeros(k, np.float64)
            )
            tot = cls_counts.sum()
            if tot <= 0 and lf["preds"]:
                continue                   # padded/empty child
            pr = {
                self.class_values[c]: (float(cls_counts[c]) / tot if tot else 0.0)
                for c in range(k)
            }
            info = float(
                (_np_bits_entropy if self.algo in ("entropy", "infoGain")
                 else _np_gini)(cls_counts))
            model_paths.append(DecisionPath(
                lf["preds"], int(tot), info, True, pr
            ))
        return DecisionPathList(model_paths)

    def _allowed_splits(self, leaf: Dict) -> List[int]:
        strat = self.attr_strategy
        used = leaf["used"]
        attrs = sorted({sp.attribute for sp in self.splits})
        if strat == "all":
            chosen = set(attrs)
        elif strat == "notUsedYet":
            # exhausted attributes stop the leaf rather than re-splitting on
            # an already-used attribute (which yields duplicate predicates)
            chosen = set(a for a in attrs if a not in used)
        elif strat == "randomAll":
            m = max(1, int(math.sqrt(len(attrs))))
            chosen = set(self.rng.choice(attrs, size=m, replace=False).tolist())
        elif strat == "randomNotUsedYet":
            avail = [a for a in attrs if a not in used]
            if not avail:
                return []
            m = max(1, int(math.sqrt(len(avail))))
            chosen = set(self.rng.choice(avail, size=m, replace=False).tolist())
        else:
            chosen = set(attrs)
        return [i for i, sp in enumerate(self.splits) if sp.attribute in chosen]


# ---------------------------------------------------------------------------
# random forest
# ---------------------------------------------------------------------------


class RandomForestBuilder:
    """RF = trees over bootstrap row weights + random attribute selection
    (reference first-iteration sampling DecisionTreeBuilder.java:200-236 with
    sub.sampling.strategy withReplace/withoutReplace)."""

    def __init__(
        self,
        schema: FeatureSchema,
        num_trees: int = 10,
        sampling: str = "withReplace",
        sample_rate: float = 0.7,
        seed: int = 0,
        **tree_kwargs,
    ):
        self.schema = schema
        self.num_trees = num_trees
        self.sampling = sampling
        self.sample_rate = sample_rate
        self.seed = seed
        tree_kwargs.setdefault("attr_selection_strategy", "randomNotUsedYet")
        self.tree_kwargs = tree_kwargs
        self.trees: List[DecisionPathList] = []
        self.class_values = schema.class_values()
        self._evaluator: Optional[DevicePathEvaluator] = None

    def fit(self, ds: Dataset) -> "RandomForestBuilder":
        """All trees grow together, one batched device call per level:
        trees share the (segment matrix, labels) upload and differ only in
        bootstrap weights and leaf routing, so the whole forest costs
        max_depth histogram+advance dispatches instead of
        num_trees x (max_depth x 2 + 1) round trips."""
        n = len(ds)
        rng = np.random.default_rng(self.seed)
        self.trees = []
        self._evaluator = None
        ws = np.empty((self.num_trees, n), np.float32)
        for t in range(self.num_trees):
            if self.sampling == "withReplace":
                idx = rng.integers(0, n, n)
                ws[t] = np.bincount(idx, minlength=n).astype(np.float32)
            elif self.sampling == "withoutReplace":
                ws[t] = (rng.random(n) < self.sample_rate).astype(np.float32)
            else:
                ws[t] = 1.0
        builders = [
            DecisionTreeBuilder(self.schema, seed=self.seed + t,
                                **self.tree_kwargs)
            for t in range(self.num_trees)
        ]
        b0 = builders[0]
        ns, k, smax = len(b0.splits), len(b0.class_values), b0.smax
        seg = np.stack(
            [sp.segment_of(np.asarray(ds.column(sp.attribute)))
             for sp in b0.splits], axis=1,
        ).astype(np.int8)
        seg_d = jnp.asarray(seg)
        labels_d = jnp.asarray(ds.labels())
        ws_d = jnp.asarray(ws)
        leaf_ids = jnp.zeros((self.num_trees, n), jnp.int32)
        leaves_t: List[List[Dict]] = [
            [{"preds": [], "used": set(), "stopped": False}]
            for _ in range(self.num_trees)
        ]

        for depth in range(b0.max_depth):
            if not any(DecisionTreeBuilder._active_leaves(lv)
                       for lv in leaves_t):
                break
            lpad = 1 << (max(len(lv) for lv in leaves_t) - 1).bit_length()
            counts_all = np.asarray(_level_histogram_forest(
                leaf_ids, seg_d, labels_d, ws_d, lpad, ns, smax, k))
            bests, offsets = [], []
            any_new = False
            for t, b in enumerate(builders):
                best, child, new_l = b._grow_level(
                    leaves_t[t], counts_all[t][: len(leaves_t[t])], lpad)
                if new_l:
                    any_new = True
                    leaves_t[t] = leaves_t[t] + new_l
                bests.append(best)
                offsets.append(child)
            if not any_new:
                break
            leaf_ids = _advance_leaves_forest(
                leaf_ids, seg_d, jnp.asarray(np.stack(bests)),
                jnp.asarray(np.stack(offsets)))

        lpad = 1 << (max(len(lv) for lv in leaves_t) - 1).bit_length()
        counts_fin = np.asarray(_level_histogram_forest(
            leaf_ids, seg_d, labels_d, ws_d, lpad, max(ns, 1), smax, k
        )) if ns else None
        self.trees = [
            b._emit_paths(
                leaves_t[t],
                counts_fin[t][: len(leaves_t[t])]
                if counts_fin is not None else None)
            for t, b in enumerate(builders)
        ]
        return self

    def predict(self, ds: Dataset, device: bool = False) -> np.ndarray:
        """Majority vote across trees. device=True routes every row
        through every tree's paths as one batched kernel
        (DevicePathEvaluator) instead of the host per-path loop."""
        if device:
            if self._evaluator is None:
                self._evaluator = DevicePathEvaluator(
                    self.trees, self.schema, self.class_values)
            return self._evaluator.predict(ds)
        k = len(self.class_values)
        votes = np.zeros((len(ds), k), np.int64)
        rows = np.arange(len(ds), dtype=np.int32)
        for tree in self.trees:
            pred = tree.predict(ds, self.class_values)
            votes[rows, pred] += 1
        return votes.argmax(axis=1).astype(np.int32)

    def validate(self, ds: Dataset, pos_class: int = 1) -> ConfusionMatrix:
        cm = ConfusionMatrix(self.class_values, pos_class=pos_class)
        cm.add(ds.labels(), self.predict(ds))
        return cm


class DataPartitioner:
    """Physically partition rows by the best candidate split — the dap.* MR
    job (tree/DataPartitioner.java:59-131): pick the top split of the given
    (or best) attribute, then write each segment's rows to
    `<base>/split=<splitId>/segment=<j>/data` files for the next pipeline
    stage."""

    def __init__(self, schema: FeatureSchema, algorithm: str = "giniIndex",
                 split_attribute: Optional[int] = None,
                 cat_partition_cap: int = 128):
        self.schema = schema
        self.algorithm = algorithm
        self.split_attribute = split_attribute
        self.cat_partition_cap = cat_partition_cap

    def best_split(self, ds: Dataset) -> Tuple[CandidateSplit, float]:
        from avenir_tpu.models.explore import ClassPartitionGenerator

        attrs = ([self.split_attribute]
                 if self.split_attribute is not None else None)
        cpg = ClassPartitionGenerator(ds, attributes=attrs,
                                      algorithm=self.algorithm,
                                      cat_partition_cap=self.cat_partition_cap)
        return cpg.best_split()

    def partition(self, ds: Dataset, base_path: str,
                  delim: str = ",") -> List[str]:
        """Returns the written `.../segment=j/data` file paths in segment
        order (empty segments still get an empty file, as one reducer per
        segment would)."""
        split, _ = self.best_split(ds)
        seg = split.segment_of(np.asarray(ds.column(split.attribute)))
        paths = []
        for j in range(split.n_segments):
            d = os.path.join(base_path, f"split={split.split_id}",
                             f"segment={j}")
            os.makedirs(d, exist_ok=True)
            p = os.path.join(d, "data")
            sub = ds.take(np.nonzero(seg == j)[0])
            with open(p, "w") as fh:
                fh.write(sub.to_csv(delim) if len(sub) else "")
            paths.append(p)
        return paths
