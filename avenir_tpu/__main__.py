"""CLI: `python -m avenir_tpu <jobName> --conf <props> IN... OUT`.

The `hadoop jar avenir.jar <ToolClass> -Dconf.path=<props> IN OUT` surface
(resource/detr.sh:52, knn.sh:76) without the JVM: job names or full
reference Tool class names are accepted.
"""

import sys

from avenir_tpu.runner import run_from_cli


def main() -> None:
    """Console-script entry (`avenir-tpu ...` after pip install)."""
    run_from_cli(sys.argv[1:])


if __name__ == "__main__":
    main()
