"""In-process multi-tenant job server over the shared scan.

Three layers, each consuming machinery earlier PRs proved correct:

- **Batching scheduler** — concurrent submissions land in per-tenant
  FIFO queues; the scheduler picks the highest-priority head (FIFO
  aging guarantees a starving tenant's head eventually outranks every
  newcomer) and folds every other tenant's COMPATIBLE queued prefix
  into the same dispatch: one ``runner.run_shared`` SharedScan pass —
  N tenants, one disk read + one parse per chunk. Compatibility is
  :func:`compat_key` (same corpus, same scan kind, same block size /
  delimiter / schema), the exact preconditions ``run_shared`` enforces;
  identical requests (same job + conf digest + corpus) coalesce into
  one execution whose artifact is copied per requester. Append-refresh
  requests batch the same way through the fused incremental driver
  (``runner.run_incremental_shared``): one delta scan, per-job
  restored carries.
- **Warm state** — the process is resident, so jit-compiled fold
  executables stay cached across requests for free (the
  ``Server:CompileHits`` counter proves it per dispatch). The
  :class:`WarmStore` additionally pins the multi-pass miners'
  still-open sources — their committed ``EncodedBlockCache`` spill
  segments — under an explicit byte budget (LRU whole-entry drops:
  the warm gate demands full replay validity), so a repeat mining
  request over an
  unchanged corpus replays encoded blocks with ZERO CSV parses; and it
  manages the per-(job, corpus) incremental checkpoint state dirs as a
  bounded on-disk cache, so refresh requests restore a carry instead
  of re-scanning.
- **Admission controller** — every dispatch is priced in bytes BEFORE
  it runs (:func:`price_request_bytes`: graftlint-mem's
  ``footprint_model``/``combined_footprint`` over the corpus stats);
  a dispatch whose prediction plus the in-flight predictions would
  breach the configured ceiling (default 3GB, the repo's standing RSS
  budget) is HELD until running work completes, and one that could
  never fit fails fast with :class:`AdmissionError` instead of
  wedging the queue. The gate is the VALIDATED model, not a live RSS
  reading: a resident CPython process's RSS is sticky (freed arenas
  stay resident and get reused, not returned), so gating on live RSS
  would double-count every completed job and eventually hold or
  reject everything. Live RSS is still sampled and reported
  (``stats()["rss_bytes"]``), and ``bench_scaling.server_tripwire``
  asserts the measured served-phase peak stays under budget + slack —
  the empirical check that the model-priced gate actually bounds the
  process.

Thread shape (the graftlint --flow contract): one scheduler thread +
``workers`` executor threads, all bound and joined on ``shutdown()``
with liveness verified after a bounded join; every ``queue.get`` polls
with a timeout and re-checks the shutdown flag; shared stats mutate
under one lock.

Results are byte-identical to the solo-job runner by construction —
the server only ever executes through the registered runner paths
(``run_job`` / ``run_shared`` / ``run_incremental`` /
``run_incremental_shared`` / ``run_warm_miner``), whose equivalence
the shared-scan and merge auditors re-prove every round.
"""

from __future__ import annotations

import json
import os
import queue
import shutil
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from avenir_tpu import obs as _obs
from avenir_tpu.core.atomic import publish_json
from avenir_tpu.obs.histogram import LatencyHistogram

#: default admission ceiling: the repo's standing 3GB RSS budget
#: (tools/stream_scale_check.py asserts it at every 100M-row anchor)
DEFAULT_BUDGET_BYTES = 3 << 30
#: default byte budget of the pinned miner-source caches
DEFAULT_WARM_BUDGET_BYTES = 256 << 20
#: default byte budget of the managed checkpoint state dirs
DEFAULT_CHECKPOINT_BUDGET_BYTES = 1 << 30
#: admission reserve for jobs the footprint model does not cover
DEFAULT_RESERVE_BYTES = 256 << 20
#: a queue head older than this is boosted past every priority — the
#: FIFO aging that keeps one tenant from starving the rest
DEFAULT_STARVATION_MS = 2000.0
#: scheduler/worker poll granularity: bounds how long a loop can block
#: before re-checking the shutdown flag
_POLL_SECS = 0.05
#: shutdown() bound on joining each thread; one alive past this is
#: wedged and is reported, not ignored (the LearnerStream.stop contract)
_JOIN_SECS = 10.0

#: miner jobs the warm-source layer can serve with zero CSV parses
_MINER_JOBS = ("frequentItemsApriori", "candidateGenerationWithSelfJoin")


class AdmissionError(RuntimeError):
    """A request's priced footprint can never fit the byte budget."""


class ServerClosed(RuntimeError):
    """submit() after shutdown(), or shutdown() cancelled the request."""


@dataclass
class JobRequest:
    """One tenant's job submission.

    ``mode``: "run" executes the job cold (shared-scan batched when
    compatible peers are queued); "refresh" serves it through the
    incremental delta-scan driver against the server's managed
    checkpoint store (O(delta) after an append). ``priority``: higher
    dispatches first, FIFO within a tenant, aging-boosted against
    starvation. ``state_dir`` overrides the managed checkpoint dir for
    refresh requests. ``nonce`` is the CLIENT's namespace token: the
    spool transport writes the result to ``<nonce>.<name>`` so two
    clients reusing one filename stem can never overwrite each other's
    results (the server itself never interprets it)."""

    job: str
    conf: object
    inputs: List[str]
    output: str
    tenant: str = "default"
    priority: int = 0
    mode: str = "run"
    state_dir: Optional[str] = None
    nonce: Optional[str] = None
    req_id: str = field(default_factory=lambda: uuid.uuid4().hex[:12])


class Ticket:
    """A submitted request's handle: ``result(timeout)`` blocks until
    the server served (or failed) the request. The served
    :class:`~avenir_tpu.runner.JobResult` carries the ``Server:*``
    counters next to the job's own."""

    def __init__(self, request: JobRequest):
        self.request = request
        self.submitted_at = time.perf_counter()
        self._done = threading.Event()
        self._result = None
        self._error: Optional[BaseException] = None
        # scheduler bookkeeping (written before dispatch, read after
        # completion — the done event orders the accesses)
        self._held_ms = 0.0
        self._held_since: Optional[float] = None
        self._dispatched_at: Optional[float] = None
        self._completed_at: Optional[float] = None
        self._ckey: Optional[tuple] = None
        self._ekey: Optional[tuple] = None
        self._canonical: Optional[str] = None
        self._price_memo: Optional[tuple] = None

    @property
    def done(self) -> bool:
        return self._done.is_set()

    def result(self, timeout: Optional[float] = None):
        if not self._done.wait(timeout):
            raise TimeoutError(
                f"request {self.request.req_id} not served in {timeout}s")
        if self._error is not None:
            raise self._error
        return self._result

    def _complete(self, result=None, error: Optional[BaseException] = None
                  ) -> None:
        self._result = result
        self._error = error
        self._completed_at = time.perf_counter()
        self._done.set()


# --------------------------------------------------------------------------
# compatibility / pricing
# --------------------------------------------------------------------------
def _scoped(job: str, conf):
    from avenir_tpu.runner import _job_cfg

    return _job_cfg(job, conf)


def compat_key(request: JobRequest) -> Optional[tuple]:
    """The batching key: two requests with EQUAL keys can ride one
    SharedScan pass (same mode, same corpus, same scan kind, same
    stream block size, same field delimiter, and — for Dataset folds —
    the same schema file: exactly the preconditions
    ``runner.run_shared`` / ``run_incremental_shared`` enforce). None
    for jobs with no registered stream fold — those never batch."""
    from avenir_tpu.runner import stream_fold_names

    canonical, _prefix, cfg = _scoped(request.job, request.conf)
    if canonical not in stream_fold_names():
        return None
    from avenir_tpu.core.keys import compat_tuple
    from avenir_tpu.runner import stream_fold_ops

    ops = stream_fold_ops(canonical)
    schema = None
    if ops.kind == "dataset":
        schema = cfg.get("feature.schema.file.path")
        if not schema:
            return None               # will fail at run; never batch it
    return compat_tuple(request.mode, request.inputs, ops.kind,
                        cfg.get_float("stream.block.size.mb", 64.0),
                        cfg.field_delim_regex, schema)


def _exec_key(request: JobRequest) -> tuple:
    """Identical-execution key: requests agreeing on it produce (by
    determinism of the runner paths) byte-identical artifacts, so the
    server runs ONE and copies the files per requester.

    key-covered: all — conf_digest folds every non-neutral property.
    """
    from avenir_tpu.core.keys import conf_digest, key_site

    key_site("exec.coalesce")
    canonical, _prefix, cfg = _scoped(request.job, request.conf)
    return (request.mode, canonical, conf_digest(cfg),
            tuple(os.path.abspath(p) for p in request.inputs))


def price_request_bytes(requests: Sequence[JobRequest],
                        reserve_bytes: int = DEFAULT_RESERVE_BYTES) -> int:
    """Predicted peak incremental host bytes of dispatching `requests`
    as one group — the admission oracle. Streamed jobs price through
    graftlint-mem's analytic model (``combined_footprint``: ingest
    terms paid once across the fused group, per-job state terms
    summed); jobs without a model, or a corpus that cannot be sampled,
    price at the flat `reserve_bytes` — admission must always have a
    number, so the fallback is conservative, never an exception."""
    from avenir_tpu.core.schema import FeatureSchema
    from avenir_tpu.runner import stream_fold_names

    streamed: List[Tuple[str, object]] = []       # (canonical, cfg)
    flat = 0
    for req in requests:
        canonical, _prefix, cfg = _scoped(req.job, req.conf)
        if canonical in stream_fold_names():
            streamed.append((canonical, cfg))
        else:
            flat += int(reserve_bytes)
    if not streamed:
        return flat
    try:
        from avenir_tpu.analysis.mem import combined_footprint, corpus_stats
        from avenir_tpu.core.stream import prefetch_depth

        cfg0 = streamed[0][1]
        block_mb = cfg0.get_float("stream.block.size.mb", 64.0)
        depth = prefetch_depth(cfg0)
        if cfg0.get_bool("stream.autotune", False):
            # price what the runner will RUN: an autotuned dispatch
            # overlays the profile's knobs AFTER admission, so the
            # oracle must price the overlaid block/depth, not the
            # static conf — otherwise a tuned-up block size runs at
            # several times its admitted bytes. A bad profile prices
            # at the static values (and the run fails loudly on it).
            try:
                from avenir_tpu import tune

                jobs = sorted(c for c, _cfg in streamed)
                prof = tune.ProfileStore(tune.resolve_dir(
                    cfg0, requests[0].inputs)).load(
                    "+".join(jobs), tune.corpus_digest(requests[0].inputs))
                knobs = dict((prof or {}).get("knobs") or {})
                block_mb = float(knobs.get("stream.block.size.mb",
                                           block_mb))
                depth = int(knobs.get("stream.prefetch.depth", depth))
            except Exception:
                pass
        block = int(block_mb * (1 << 20))
        paths = [p for p in requests[0].inputs if os.path.exists(p)]
        stats = corpus_stats(paths, delim=cfg0.field_delim_regex) \
            if paths else None
        schema = None
        schema_path = cfg0.get("feature.schema.file.path")
        if schema_path:
            schema = FeatureSchema.from_file(schema_path)
        est = combined_footprint([c for c, _cfg in streamed], block,
                                 schema, stats, prefetch_depth=depth)
        return flat + int(est.total_bytes)
    except Exception:
        return flat + int(reserve_bytes) * len(streamed)


def _process_rss_bytes() -> int:
    """Current (not peak) resident bytes of this process, via
    /proc/self/statm; 0 where /proc is unavailable (admission then
    prices against the budget alone)."""
    try:
        with open("/proc/self/statm") as fh:
            return int(fh.read().split()[1]) * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError):
        return 0


class _Admission:
    """Byte-budget admission bookkeeping. All methods are called with
    the server lock held; the controller itself keeps no lock.

    The gate is the priced predictions alone (module docstring): live
    RSS in a resident CPython process double-counts freed-but-still-
    resident memory, so `rss_probe` (the /proc reading by default) is
    only surfaced through stats as observability, never consulted for
    an admit/hold/reject decision."""

    def __init__(self, budget_bytes: int, reserve_bytes: int,
                 rss_probe: Callable[[], int] = _process_rss_bytes):
        self.budget = int(budget_bytes)
        self.reserve = int(reserve_bytes)
        self.rss_probe = rss_probe
        self.inflight_bytes = 0
        self.inflight_batches = 0
        self.peak_priced_bytes = 0

    def admit(self, priced: int) -> bool:
        """True (and accounted) when the in-flight predictions + this
        dispatch's prediction fit the budget."""
        total = self.inflight_bytes + priced
        if total > self.budget:
            return False
        self.inflight_bytes += priced
        self.inflight_batches += 1
        self.peak_priced_bytes = max(self.peak_priced_bytes, total)
        return True

    def can_ever_fit(self, priced: int) -> bool:
        """False when the dispatch exceeds the budget even with nothing
        else in flight — holding it would wedge the queue forever."""
        return priced <= self.budget

    def release(self, priced: int) -> None:
        self.inflight_bytes -= priced
        self.inflight_batches -= 1


# --------------------------------------------------------------------------
# warm state
# --------------------------------------------------------------------------
class WarmStore:
    """Pinned cross-request state: miner sources (their committed
    encoded-block caches) under a byte budget, and the managed
    per-(job, corpus) incremental checkpoint dirs under another.

    Pinned sources evict least-recently-used first, whole entries only
    — including the newest when it alone exceeds the budget. Partial
    (segment-wise) trimming is deliberately NOT done: the warm gate
    ``cache_ready`` demands every source replay in full, so a trimmed
    entry could never serve warm again and would just pin dead bytes.
    Checkpoint dirs evict oldest-used whole (a dropped dir only costs
    the next refresh a cold scan — the incremental driver's documented
    fallback)."""

    def __init__(self, byte_budget: int = DEFAULT_WARM_BUDGET_BYTES,
                 checkpoint_budget: int = DEFAULT_CHECKPOINT_BUDGET_BYTES,
                 state_root: Optional[str] = None):
        self.byte_budget = int(byte_budget)
        self.checkpoint_budget = int(checkpoint_budget)
        self._lock = threading.Lock()
        self._sources: Dict[tuple, object] = {}
        self._last_used: Dict[tuple, float] = {}
        self._dir_inuse: Dict[str, int] = {}
        self._own_root = state_root is None
        if state_root is None:
            import tempfile

            state_root = tempfile.mkdtemp(prefix="avenir_server_state_")
        self.state_root = state_root
        os.makedirs(state_root, exist_ok=True)
        self.hits = 0
        self.misses = 0

    # ----------------------------------------------------- miner sources
    @staticmethod
    def source_key(canonical: str, inputs: Sequence[str], cfg) -> tuple:
        """Warm identity of a miner source: the scan-shaping config
        (delimiter, skipped meta fields, infrequent-item marker,
        transaction-id ordinal) plus the corpus paths. Mining
        parameters (support threshold, max length) deliberately
        EXCLUDED — pass 1 does not depend on them, so one warm source
        serves any mining request over the corpus. The trans-id ordinal
        IS included: the source bakes it in, and an apriori request
        emitting trans ids from a different column must miss, not
        silently serve ids read from the pinned source's column.

        key-covered: fia.support.threshold fia.item.set.length
        fia.max.item.set.length stream.block.size.mb — mining
        parameters shape pass 2 only, and the block size shapes the
        scan's tiling, never the parsed rows a warm source replays."""
        from avenir_tpu.core.keys import source_tuple

        return source_tuple(canonical, inputs,
                            cfg.field_delim_regex,
                            cfg.get_int("skip.field.count", 1),
                            cfg.get("infreq.item.marker"),
                            cfg.get_int("tans.id.ord", 0))

    def lookup(self, key: tuple):
        """EXCLUSIVE checkout of the pinned, still-content-valid source
        for `key`, or None. The entry is REMOVED from the store while
        checked out — miner sources carry mutable per-request scan
        state (item masks, replay cursors), so two workers must never
        mine one source concurrently, and eviction must never close a
        source mid-mine; the server pins it back when the request
        completes. Validity is the cache's own per-block content gate
        (``cache_ready``): any corpus change drops the entry — a warm
        hit can never serve stale counts."""
        with self._lock:
            src = self._sources.pop(key, None)
            self._last_used.pop(key, None)
            if src is None:
                self.misses += 1
                return None
            if not src.cache_ready():
                src.close()
                self.misses += 1
                return None
            self.hits += 1
            return src

    def pin(self, key: tuple, src) -> None:
        with self._lock:
            old = self._sources.pop(key, None)
            if old is not None and old is not src \
                    and not getattr(old, "cache_durable", False):
                # a durable entry (sidecar handle) shares its on-disk
                # state with the replacement — same key, same directory
                # — so closing it here would rmtree what we are pinning
                old.close()
            if not src.cache_ready():
                src.close()               # nothing replayable to pin
                return
            self._sources[key] = src
            self._last_used[key] = time.perf_counter()
            self._enforce_budget()

    def _enforce_budget(self) -> None:
        # LRU whole-entry drops, including the newest entry when it
        # alone exceeds the budget: a segment-trimmed source can never
        # serve warm again (cache_ready demands EVERY source replay in
        # full), so trimming would just pin dead, unservable bytes
        # against the budget
        total = sum(s.cache_nbytes for s in self._sources.values())
        order = sorted(self._sources, key=lambda k: self._last_used[k])
        while total > self.byte_budget and order:
            key = order.pop(0)
            src = self._sources.pop(key)
            self._last_used.pop(key, None)
            total -= src.cache_nbytes
            src.close()

    # -------------------------------------------------- checkpoint dirs
    def checkpoint_dir(self, canonical: str, inputs: Sequence[str]) -> str:
        """The managed state dir a refresh request's checkpoints live
        in — deterministic per (job, corpus), under the server's state
        root, so repeated refreshes of one corpus restore each other's
        carries (the runner's own digest recipe, different root). The
        dir is marked IN USE until :meth:`release_dir`, so concurrent
        budget enforcement can never rmtree a dir another worker is
        actively checkpointing into."""
        from avenir_tpu.core.keys import state_digest

        digest = state_digest(canonical, inputs)
        path = os.path.join(self.state_root, f"{canonical}_{digest}")
        with self._lock:
            self._dir_inuse[path] = self._dir_inuse.get(path, 0) + 1
            self._touch_dir(path)
        return path

    def release_dir(self, path: str) -> None:
        """End the in-use hold :meth:`checkpoint_dir` took (refcounted:
        concurrent refreshes of one corpus share the dir)."""
        with self._lock:
            n = self._dir_inuse.get(path, 0) - 1
            if n <= 0:
                self._dir_inuse.pop(path, None)
            else:
                self._dir_inuse[path] = n

    def _touch_dir(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)
        self._dir_used = getattr(self, "_dir_used", {})
        self._dir_used[path] = time.perf_counter()
        total = 0
        sizes: Dict[str, int] = {}
        for d in list(self._dir_used):
            n = _dir_bytes(d)
            sizes[d] = n
            total += n
        order = sorted(self._dir_used, key=lambda d: self._dir_used[d])
        while total > self.checkpoint_budget and len(order) > 1:
            victim = order.pop(0)
            if victim == path or self._dir_inuse.get(victim):
                continue              # never evict a dir being served
            total -= sizes.get(victim, 0)
            self._dir_used.pop(victim, None)
            shutil.rmtree(victim, ignore_errors=True)

    def stats(self) -> Dict[str, float]:
        with self._lock:
            return {
                "pinned_sources": float(len(self._sources)),
                "pinned_bytes": float(sum(
                    s.cache_nbytes for s in self._sources.values())),
                "hits": float(self.hits),
                "misses": float(self.misses),
            }

    def close(self) -> None:
        with self._lock:
            for src in self._sources.values():
                # durable entries (sidecar handles) outlive the server:
                # shutdown drops the PIN, not the on-disk cache — only
                # budget eviction / staleness deletes a sidecar
                if not getattr(src, "cache_durable", False):
                    src.close()
            self._sources.clear()
            self._last_used.clear()
        if self._own_root:
            shutil.rmtree(self.state_root, ignore_errors=True)


def _dir_bytes(path: str) -> int:
    total = 0
    try:
        for name in os.listdir(path):
            try:
                total += os.path.getsize(os.path.join(path, name))
            except OSError:
                pass
    except OSError:
        pass
    return total


# --------------------------------------------------------------------------
# compile-warmth probe
# --------------------------------------------------------------------------
def _fold_kernel_cache_size() -> int:
    """Total compiled-executable count across the streamed fold kernels
    (utils.metrics.jit_cache_size): a dispatch that leaves this
    unchanged ran entirely on warm compiles — the ``Server:CompileHits``
    evidence that residency amortizes jit cost."""
    from avenir_tpu.utils.metrics import jit_cache_size

    total = 0
    for mod, names in (("avenir_tpu.models.naive_bayes",
                        ("_fold_batch_kernel",)),
                       ("avenir_tpu.models.sequence",
                        ("_subseq_fold_kernel", "_subseq_support_kernel")),
                       ("avenir_tpu.ops.bitset", ("bitset_fold_counts",))):
        try:
            m = __import__(mod, fromlist=list(names))
        except Exception:
            continue
        for name in names:
            n = jit_cache_size(getattr(m, name, None))
            if n > 0:
                total += n
    return total


# --------------------------------------------------------------------------
# the server
# --------------------------------------------------------------------------
@dataclass
class _Batch:
    """One admitted dispatch: `primaries` execute (one spec each),
    `dups[i]` receive copies of primary i's artifact. ``batch_id`` is
    the dispatch-clock ordinal — the linkage attr every per-request
    span carries so a trace groups requests back into their batch."""

    tickets: List[Ticket]
    dups: List[List[Ticket]]
    mode: str
    streamable: bool
    priced_bytes: int
    dispatched_at: float
    batch_id: int = 0


class JobServer:
    """The resident multi-tenant analytics server (module docstring has
    the architecture). Construct, ``submit()`` (queues are live
    immediately), ``start()`` the scheduler/workers, ``drain()``,
    ``shutdown()``. Submitting before start() is the deterministic way
    to form a batch from an already-full queue."""

    def __init__(self, budget_bytes: int = DEFAULT_BUDGET_BYTES,
                 workers: int = 2,
                 warm_budget_bytes: int = DEFAULT_WARM_BUDGET_BYTES,
                 checkpoint_budget_bytes: int = DEFAULT_CHECKPOINT_BUDGET_BYTES,
                 reserve_bytes: int = DEFAULT_RESERVE_BYTES,
                 max_batch: int = 6,
                 starvation_ms: float = DEFAULT_STARVATION_MS,
                 state_root: Optional[str] = None,
                 pricer: Optional[Callable] = None,
                 rss_probe: Callable[[], int] = _process_rss_bytes,
                 metrics_path: Optional[str] = None,
                 metrics_interval_s: float = 2.0,
                 autotune_dir: Optional[str] = None,
                 autotune_balance_ratio: float = 4.0):
        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        self._queues: Dict[str, List[Ticket]] = {}
        self._seq = 0
        self._order: Dict[str, int] = {}          # req_id -> arrival seq
        self._dispatchq: "queue.Queue[_Batch]" = queue.Queue(
            maxsize=max(workers, 1) * 2)
        self._shutdown = threading.Event()
        self._started = False
        self._closed = False
        self._threads: List[threading.Thread] = []
        self._admission = _Admission(budget_bytes, reserve_bytes,
                                     rss_probe=rss_probe)
        # the autotune wiring (avenir_tpu.tune): an `autotune_dir` is a
        # profile-store root — the pricer gains the residual-learned
        # correction factor (clamped >= 1.0: the validated model stays
        # the admission FLOOR, the learned factor can only add
        # conservatism) and the scheduler consults per-job measured
        # fold-cost means when composing batches
        self._autotune_dir = autotune_dir
        self._balance_ratio = float(autotune_balance_ratio)
        self._fold_costs: Dict[tuple, Optional[float]] = {}
        self._fold_costs_at = 0.0
        if pricer is None and autotune_dir:
            from avenir_tpu import tune

            pricer = tune.make_tuned_pricer(autotune_dir,
                                            base=price_request_bytes)
        # the admission oracle: price_request_bytes (graftlint-mem's
        # footprint model) unless a test/operator injects its own
        self._pricer = pricer or price_request_bytes
        self.warm = WarmStore(warm_budget_bytes, checkpoint_budget_bytes,
                              state_root)
        self.max_batch = max(int(max_batch), 1)
        self.workers = max(int(workers), 1)
        self.starvation_s = float(starvation_ms) / 1000.0
        self._stats: Dict[str, float] = {
            "submitted": 0, "served": 0, "failed": 0, "batches": 0,
            "batched_requests": 0, "coalesced": 0, "admission_holds": 0,
            "warm_hits": 0, "compile_warm_dispatches": 0,
        }
        self._dispatch_clock = 0
        # streaming latency histograms (avenir_tpu.obs.histogram): the
        # distribution view the old last-value-only scalars could not
        # give — fed per finished request / per dispatched batch,
        # surfaced in stats(), metrics.json and the per-result
        # Server:*P50/P99 counters
        self._hists: Dict[str, LatencyHistogram] = {
            "queue_wait_ms": LatencyHistogram(),
            "admission_held_ms": LatencyHistogram(),
            "dispatch_ms": LatencyHistogram(),
        }
        self._started_at = time.perf_counter()
        # drain state (the network edge's /healthz answer): begin_drain
        # gates NEW submissions while in-flight work finishes
        self._draining = False
        # live metrics surface: when set, the scheduler atomic-renames a
        # metrics.json snapshot here every `metrics_interval_s`
        self.metrics_path = metrics_path
        self.metrics_interval_s = float(metrics_interval_s)
        self._metrics_written_at = 0.0
        # the online scoring half (server/score.py), built on first use:
        # query traffic shares the process, not the batch queues
        self._score_plane = None

    # ------------------------------------------------------------ public
    def __enter__(self) -> "JobServer":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()

    def submit(self, request: JobRequest) -> Ticket:
        """Queue one request; returns its :class:`Ticket`. Raises
        KeyError for an unknown job name and :class:`ServerClosed`
        after shutdown — validation the tenant gets synchronously."""
        from avenir_tpu.runner import _job_cfg

        canonical, _prefix, _cfg = _job_cfg(request.job, request.conf)
        if request.mode not in ("run", "refresh"):
            raise ValueError(f"unknown request mode {request.mode!r}")
        ticket = Ticket(request)
        # keys computed once, outside the lock: the scheduler consults
        # them every pass and conf-file parsing must not ride the lock
        ticket._ckey = compat_key(request)
        ticket._ekey = _exec_key(request)
        ticket._canonical = canonical
        with self._work:
            if self._closed:
                raise ServerClosed("server is shut down")
            if self._draining:
                raise ServerClosed("server is draining")
            self._seq += 1
            self._order[request.req_id] = self._seq
            self._queues.setdefault(request.tenant, []).append(ticket)
            self._stats["submitted"] += 1
            self._work.notify_all()
        return ticket

    def start(self) -> "JobServer":
        with self._lock:
            if self._started or self._closed:
                return self
            self._started = True
        # every started thread is appended to _threads and joined (with
        # a liveness check) in shutdown() — the graftlint --flow
        # joinable-worker contract
        t = threading.Thread(target=self._scheduler_loop,
                             name="avenir-server-scheduler")
        t.start()
        self._threads.append(t)
        for i in range(self.workers):
            t = threading.Thread(target=self._worker_loop,
                                 name=f"avenir-server-worker-{i}")
            t.start()
            self._threads.append(t)
        return self

    def drain(self, timeout: float = 300.0) -> None:
        """Block until every queued request is served (or failed)."""
        deadline = time.perf_counter() + timeout
        with self._work:
            while self._pending_locked():
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    raise TimeoutError(
                        f"server did not drain within {timeout}s "
                        f"({self._pending_locked()} requests pending)")
                self._work.wait(min(remaining, _POLL_SECS * 4))

    def shutdown(self, drain: bool = True, timeout: float = 300.0) -> None:
        """Stop the server: optionally drain, then join every thread
        (bounded; a worker alive past the bound raises — a wedged
        thread must be reported, never leaked silently), cancel any
        still-queued requests with :class:`ServerClosed`, and close
        the warm store. A drain timeout still tears everything down
        (threads signalled + joined, queued tickets cancelled, warm
        store closed) before the TimeoutError surfaces — a timed-out
        shutdown must never leak the server's threads."""
        drain_err: Optional[BaseException] = None
        if drain and self._started and not self._closed:
            try:
                self.drain(timeout)
            except TimeoutError as exc:
                drain_err = exc
        with self._work:
            self._closed = True
            self._work.notify_all()
        self._shutdown.set()
        threads, self._threads = self._threads, []
        wedged: List[str] = []
        for t in threads:
            t.join(_JOIN_SECS)
            if t.is_alive():
                # keep tearing down: queued tickets must still be
                # cancelled and the warm store closed even when one
                # worker is wedged — clients blocked in result() on a
                # never-dispatched request would otherwise hang forever
                wedged.append(t.name)
        leftovers: List[Ticket] = []
        with self._work:
            for q in self._queues.values():
                leftovers.extend(q)
                q.clear()
        while True:                   # batches the workers never pulled
            try:
                batch = self._dispatchq.get_nowait()
            except queue.Empty:
                break
            leftovers.extend(batch.tickets)
            leftovers.extend(d for ds in batch.dups for d in ds)
        for ticket in leftovers:
            ticket._complete(error=ServerClosed(
                "server shut down before the request was served"))
        # the score plane drains before the final snapshot so its last
        # window's latencies make it into metrics.json
        plane, self._score_plane = self._score_plane, None
        if plane is not None:
            plane.close()
        # final snapshot: a short --once spool session must still leave
        # a fresh metrics.json behind even when no interval tick fired
        try:
            self.write_metrics()
        except OSError:
            pass
        self.warm.close()
        if wedged:
            raise RuntimeError(
                f"server thread(s) {', '.join(wedged)} failed to stop "
                f"within {_JOIN_SECS}s")
        if drain_err is not None:
            raise drain_err

    def stats(self) -> Dict:
        with self._lock:
            out = dict(self._stats)
            out["inflight_bytes"] = float(self._admission.inflight_bytes)
            out["peak_priced_bytes"] = float(
                self._admission.peak_priced_bytes)
            out["budget_bytes"] = float(self._admission.budget)
            # advisory observability, never an admission input (the
            # _Admission docstring has the why)
            out["rss_bytes"] = float(self._admission.rss_probe())
            # latency distributions (not scalars): {name: {count, mean,
            # min, max, p50, p95, p99}} per histogram — the tail view
            # the last-value Server:* counters could never give
            out["hists"] = {name: h.summary()
                            for name, h in self._hists.items()}
        out.update({f"warm_{k}": v for k, v in self.warm.stats().items()})
        return out

    # ----------------------------------------------------- score plane
    def score_plane(self, **kwargs):
        """The online scoring half (server/score.py), lazily built so
        job-only servers never pay its dispatcher thread. kwargs
        (budget_bytes / window_ms / batch_max) only apply to the
        first, constructing call; shutdown() drains and joins it."""
        with self._lock:
            if self._score_plane is None:
                from avenir_tpu.server.score import ScorePlane
                self._score_plane = ScorePlane(**kwargs)
            return self._score_plane

    # ------------------------------------------------------- edge hooks
    def price(self, requests: Sequence[JobRequest]) -> int:
        """The admission oracle's prediction for `requests` as one
        group — the number the network edge sheds against BEFORE
        enqueueing (the same pricer the scheduler admits with, so the
        edge and the admission controller can never disagree on what a
        request costs)."""
        return int(self._pricer(list(requests), self._admission.reserve))

    def queue_depth(self, tenant: Optional[str] = None) -> int:
        """Currently queued (not yet dispatched) request count — one
        tenant's, or every tenant's summed. The edge's per-tenant depth
        bound reads this."""
        with self._lock:
            if tenant is not None:
                return len(self._queues.get(tenant, ()))
            return sum(len(q) for q in self._queues.values())

    @property
    def budget_bytes(self) -> int:
        return self._admission.budget

    @property
    def draining(self) -> bool:
        return self._draining

    def begin_drain(self) -> None:
        """Stop accepting NEW submissions (submit raises ServerClosed)
        while queued and in-flight work keeps serving — the graceful-
        drain half of SIGTERM handling; ``drain()``/``shutdown()``
        still finish the session."""
        with self._work:
            self._draining = True
            self._work.notify_all()

    # ------------------------------------------------- live metrics surface
    def metrics_snapshot(self) -> Dict:
        """The live operator snapshot (``metrics.json`` schema —
        docs/observability.md pins it): queue depths per tenant,
        in-flight priced bytes vs budget, warm-store occupancy, served/
        batch counters, and the latency histogram summaries (the
        server's queue-wait/held/dispatch hists plus the process-global
        obs hists like ``chunk_latency_ms``)."""
        with self._lock:
            queues = {tenant: len(q)
                      for tenant, q in self._queues.items() if q}
            inflight = {
                "priced_bytes": int(self._admission.inflight_bytes),
                "peak_priced_bytes": int(self._admission.peak_priced_bytes),
                "budget_bytes": int(self._admission.budget),
                "batches": int(self._admission.inflight_batches),
            }
            stats = {k: float(v) for k, v in self._stats.items()}
            hists = {name: h.summary()
                     for name, h in self._hists.items()}
            # the sparse bucket form next to the summaries: summaries
            # cannot be combined after the fact, buckets merge exactly
            # (LatencyHistogram.merge), so the fleet roll-up and
            # `python -m avenir_tpu stats a.json b.json` fold per-host
            # snapshots into one distribution instead of approximating
            raw = {name: h.to_dict() for name, h in self._hists.items()}
        # process-global streaming hists (chunk_latency_ms etc.) ride
        # along; the server's own names win on collision
        for name, summary in _obs.hist_summaries().items():
            hists.setdefault(name, summary)
            if name not in raw:
                h = _obs.hist(name)       # a merged copy, race-free
                if h is not None:
                    raw[name] = h.to_dict()
        # score-plane per-model hists join BOTH forms, so the fleet
        # roll-up (obs.report.merge_snapshots) folds per-host score
        # latency distributions exactly, same as the batch hists
        plane = self._score_plane
        score = None
        if plane is not None:
            hists.update(plane.hist_summaries())
            raw.update(plane.hists_raw())
            score = plane.snapshot()
        return {"ts_unix": time.time(),
                "uptime_s": round(time.perf_counter() - self._started_at,
                                  3),
                "queues": queues,
                "inflight": inflight,
                "warm": self.warm.stats(),
                "stats": stats,
                "hists": hists,
                "hists_raw": raw,
                "score": score,
                "draining": self._draining,
                "trace": {"spans": len(_obs.recorder()),
                          "dropped_spans": _obs.recorder().dropped,
                          "enabled": _obs.enabled()}}

    def write_metrics(self, path: Optional[str] = None) -> Optional[str]:
        """Atomically write the snapshot to `path` (default
        ``self.metrics_path``); tmp + ``os.replace`` so a reader
        (``python -m avenir_tpu stats``) never sees a torn file.
        Returns the path written, or None when no path is configured."""
        path = path or self.metrics_path
        if not path:
            return None
        return publish_json(self.metrics_snapshot(), path)

    def _maybe_write_metrics(self) -> None:
        """Scheduler-loop tick: refresh the snapshot at most every
        ``metrics_interval_s`` seconds. Snapshot errors are swallowed —
        the metrics surface is observability, never a reason to stop
        serving."""
        if not self.metrics_path:
            return
        now = time.perf_counter()
        if now - self._metrics_written_at < self.metrics_interval_s:
            return
        self._metrics_written_at = now
        try:
            self.write_metrics()
        except OSError:
            pass

    # ------------------------------------------------- scheduler internals
    def _pending_locked(self) -> int:
        queued = sum(len(q) for q in self._queues.values())
        return queued + self._admission.inflight_batches \
            + self._dispatchq.qsize()

    def _head_rank(self, ticket: Ticket, now: float) -> tuple:
        """Sort key of a queue head. Fresh heads rank by priority (then
        global FIFO); a head older than the starvation bound ranks
        ABOVE every fresh one and — crucially — by ARRIVAL among the
        starved, not by priority: a tenant flooding high-priority work
        can delay another tenant's request by at most the starvation
        bound plus the queue ahead of it at submit time, never
        indefinitely."""
        starved = (now - ticket.submitted_at) >= self.starvation_s
        seq = self._order[ticket.request.req_id]
        if starved:
            return (0, seq, 0)
        return (1, -ticket.request.priority, seq)

    def _pick_batch_locked(self) -> Optional[_Batch]:
        now = time.perf_counter()
        heads = [q[0] for q in self._queues.values() if q]
        if not heads:
            return None
        seed = min(heads, key=lambda t: self._head_rank(t, now))
        key = seed._ckey
        # assemble: seed first, then every tenant's longest COMPATIBLE
        # queued prefix (stopping a tenant's prefix at the first
        # incompatible or conflicting request preserves its FIFO
        # order); identical executions coalesce — the first of each
        # exec key is the primary, the rest receive artifact copies
        primaries: List[Ticket] = [seed]
        dups: List[List[Ticket]] = [[]]
        seen: Dict[tuple, int] = {seed._ekey: 0}
        jobs_in_batch = {seed._canonical}
        if key is not None:
            for tenant in sorted(self._queues):
                for ticket in self._queues[tenant]:
                    if ticket is seed:
                        continue
                    n = len(primaries) + sum(len(d) for d in dups)
                    if n >= self.max_batch:
                        break
                    if ticket._ckey != key:
                        break
                    if ticket._ekey in seen:
                        dups[seen[ticket._ekey]].append(ticket)
                        continue
                    if ticket._canonical in jobs_in_batch:
                        # same job under a different conf cannot share
                        # one scan; stop the prefix so FIFO holds
                        break
                    if not self._batch_balanced_locked(primaries, ticket):
                        # fold-cost imbalance (autotune profiles): a
                        # shared chunk waits on the SUM of its sinks'
                        # folds, so batching a cheap fold behind one
                        # measured far more expensive costs the cheap
                        # job more latency than the shared ingest saves
                        # — stop the prefix, FIFO holds, it dispatches
                        # in its own batch
                        break
                    jobs_in_batch.add(ticket._canonical)
                    seen[ticket._ekey] = len(primaries)
                    primaries.append(ticket)
                    dups.append([])
        # memoized on the seed per batch composition: a held batch is
        # re-assembled every scheduler pass, and re-sampling the corpus
        # head 20x/sec while holding would be pure waste. The one first
        # pricing of a composition does ride the lock, but corpus_stats
        # is a bounded head sample — submit() stalls are bounded small,
        # not O(corpus)
        memo_key = tuple(t.request.req_id for t in primaries)
        memo = getattr(seed, "_price_memo", None)
        if memo is not None and memo[0] == memo_key:
            priced = memo[1]
        else:
            priced = self._pricer([t.request for t in primaries],
                                  self._admission.reserve)
            seed._price_memo = (memo_key, priced)
        if not self._admission.admit(priced):
            if self._admission.inflight_batches == 0 \
                    and not self._admission.can_ever_fit(priced):
                for ticket in primaries + [d for ds in dups for d in ds]:
                    self._remove_locked(ticket)
                    ticket._complete(error=AdmissionError(
                        f"request priced at {priced} bytes can never fit "
                        f"the {self._admission.budget}-byte budget"))
                self._stats["failed"] += len(primaries) \
                    + sum(len(d) for d in dups)
                return None
            # count the TRANSITION into held, not every 20Hz re-check
            # of a batch that stays held
            if primaries[0]._held_since is None:
                self._stats["admission_holds"] += 1
            for ticket in primaries:
                if ticket._held_since is None:
                    ticket._held_since = now
            return None
        now = time.perf_counter()
        for ticket in primaries + [d for ds in dups for d in ds]:
            self._remove_locked(ticket)
            if ticket._held_since is not None:
                ticket._held_ms += (now - ticket._held_since) * 1000.0
                ticket._held_since = None
            ticket._dispatched_at = now
        self._dispatch_clock += 1
        self._stats["batches"] += 1
        n = len(primaries) + sum(len(d) for d in dups)
        self._stats["batched_requests"] += n if n > 1 else 0
        self._stats["coalesced"] += sum(len(d) for d in dups)
        return _Batch(primaries, dups, seed.request.mode,
                      key is not None, priced, now,
                      batch_id=self._dispatch_clock)

    def _remove_locked(self, ticket: Ticket) -> None:
        q = self._queues.get(ticket.request.tenant)
        if q is not None and ticket in q:
            q.remove(ticket)
        self._order.pop(ticket.request.req_id, None)

    # ------------------------------------------------ autotune composition
    def _fold_cost_locked(self, canonical: Optional[str],
                          inputs: Sequence[str]) -> Optional[float]:
        """Measured mean per-chunk fold cost (ms) of one (job, corpus)
        from the autotune profile store, memoized with a short TTL so
        the scheduler never re-reads tiny JSON files 20x/sec under the
        lock. None = unmeasured (always batches)."""
        if not self._autotune_dir or canonical is None:
            return None
        now = time.perf_counter()
        if now - self._fold_costs_at > 5.0:
            self._fold_costs.clear()
            self._fold_costs_at = now
        from avenir_tpu.tune import ProfileStore, corpus_digest

        key = (canonical, corpus_digest(inputs))
        if key not in self._fold_costs:
            self._fold_costs[key] = ProfileStore(
                self._autotune_dir).fold_cost_ms(canonical, key[1])
        return self._fold_costs[key]

    def _batch_balanced_locked(self, primaries: List[Ticket],
                               candidate: Ticket) -> bool:
        """True when the candidate's measured fold cost sits inside the
        batch's fold-cost band (tune.batch_balanced). Trivially true
        without an autotune dir or without measurements — the balancer
        must never refuse work it simply hasn't profiled."""
        if not self._autotune_dir:
            return True
        from avenir_tpu.tune import batch_balanced

        costs = [self._fold_cost_locked(t._canonical, t.request.inputs)
                 for t in primaries]
        return batch_balanced(
            costs,
            self._fold_cost_locked(candidate._canonical,
                                   candidate.request.inputs),
            ratio=self._balance_ratio)

    def _scheduler_loop(self) -> None:
        while not self._shutdown.is_set():
            self._maybe_write_metrics()
            with self._work:
                batch = self._pick_batch_locked()
                if batch is None:
                    self._work.wait(_POLL_SECS)
                    continue
            while True:
                try:
                    self._dispatchq.put(batch, timeout=_POLL_SECS)
                    batch = None
                    break
                except queue.Full:
                    if self._shutdown.is_set():
                        break
            if batch is not None:
                # shutdown fired while the dispatch queue was full: the
                # batch was already admitted and its tickets removed
                # from the per-tenant queues, so the shutdown sweep
                # cannot see them — cancel and release here or clients
                # blocked in result() hang forever
                with self._work:
                    self._admission.release(batch.priced_bytes)
                    self._work.notify_all()
                for t in batch.tickets + [d for ds in batch.dups
                                          for d in ds]:
                    t._complete(error=ServerClosed(
                        "server shut down before the request was served"))

    # --------------------------------------------------- worker internals
    def _worker_loop(self) -> None:
        while True:
            try:
                batch = self._dispatchq.get(timeout=_POLL_SECS)
            except queue.Empty:
                if self._shutdown.is_set():
                    return
                continue
            try:
                self._execute(batch)
            finally:
                with self._work:
                    self._admission.release(batch.priced_bytes)
                    self._work.notify_all()

    def _execute(self, batch: _Batch) -> None:
        compiles_before = _fold_kernel_cache_size()
        try:
            results, warm_hit = self._run_batch(batch)
        except BaseException as exc:  # noqa: BLE001 — reported per ticket
            for ticket in batch.tickets + [d for ds in batch.dups
                                           for d in ds]:
                ticket._complete(error=exc)
            with self._lock:
                self._stats["failed"] += len(batch.tickets) \
                    + sum(len(d) for d in batch.dups)
            return
        compile_hit = 1.0 if _fold_kernel_cache_size() == compiles_before \
            else 0.0
        n = len(batch.tickets) + sum(len(d) for d in batch.dups)
        dispatch_ms = (time.perf_counter() - batch.dispatched_at) * 1000.0
        with self._lock:
            self._hists["dispatch_ms"].add(dispatch_ms)
        _obs.record("server.dispatch", batch.dispatched_at,
                    batch=batch.batch_id, mode=batch.mode, requests=n,
                    jobs=",".join(t._canonical or t.request.job
                                  for t in batch.tickets))
        for i, ticket in enumerate(batch.tickets):
            res = results[i]
            self._finish_ticket(ticket, res, batch, n, compile_hit,
                                warm_hit)
            for dup in batch.dups[i]:
                self._finish_ticket(
                    dup, _copy_result(res, ticket.request, dup.request),
                    batch, n, compile_hit, warm_hit)
        with self._lock:
            self._stats["served"] += n
            if compile_hit:
                self._stats["compile_warm_dispatches"] += 1
            if warm_hit:
                self._stats["warm_hits"] += 1

    def _finish_ticket(self, ticket: Ticket, res, batch: _Batch,
                       batch_n: int, compile_hit: float,
                       warm_hit: float) -> None:
        now = time.perf_counter()
        dispatched = ticket._dispatched_at or now
        wait_ms = (dispatched - ticket.submitted_at) * 1000.0
        held_ms = ticket._held_ms
        # the per-request scalars (unchanged keys/semantics) now ALSO
        # feed the server-level histograms, whose p50/p99 ride along on
        # every result — a tenant sees the fleet-wide tail next to its
        # own sample
        with self._lock:
            qh = self._hists["queue_wait_ms"].add(wait_ms)
            ah = self._hists["admission_held_ms"].add(held_ms)
            q50, q99 = qh.quantile(50), qh.quantile(99)
            h50, h99 = ah.quantile(50), ah.quantile(99)
        res.counters["Server:QueueWaitMs"] = round(wait_ms, 3)
        res.counters["Server:BatchSize"] = float(batch_n)
        res.counters["Server:CompileHits"] = compile_hit
        res.counters["Server:AdmissionHeldMs"] = round(held_ms, 3)
        res.counters["Server:WarmHit"] = warm_hit
        res.counters["Server:QueueWaitP50Ms"] = round(q50, 3)
        res.counters["Server:QueueWaitP99Ms"] = round(q99, 3)
        res.counters["Server:AdmissionHeldP50Ms"] = round(h50, 3)
        res.counters["Server:AdmissionHeldP99Ms"] = round(h99, 3)
        # the request's span trail: queued -> (held) -> dispatched ->
        # finished, all linked to the batch by its dispatch ordinal
        req = ticket.request
        link = dict(req_id=req.req_id, tenant=req.tenant,
                    job=ticket._canonical or req.job,
                    batch=batch.batch_id)
        if _obs.enabled():
            _obs.recorder().record(
                "server.queued", ticket.submitted_at,
                max(dispatched - ticket.submitted_at, 0.0), attrs=link)
        if held_ms > 0:
            self._obs_record_held(dispatched, held_ms, link)
        _obs.record("server.request", ticket.submitted_at, mode=req.mode,
                    batch_size=batch_n, **link)
        ticket._complete(result=res)

    @staticmethod
    def _obs_record_held(dispatched: float, held_ms: float,
                         link: Dict) -> None:
        # a held batch is re-checked until it admits, so the hold ends
        # exactly at dispatch: reconstruct t0 from the accumulated hold
        if _obs.enabled():
            t0 = dispatched - held_ms / 1000.0
            _obs.recorder().record("server.held", t0, held_ms / 1000.0,
                                   attrs=link)

    def _conf_with_tune_dir(self, conf):
        """The request conf with the server's `autotune_dir` spliced in
        as `stream.autotune.dir` (unless the tenant set one) — so the
        profiles the RUNNER writes land in the store the server's
        pricer and batch balancer READ. Digest-neutral (the runner's
        conf digest skips autotune control keys), so injection never
        invalidates a tenant's checkpoints. Properties-file confs pass
        through untouched: the file is the tenant's contract."""
        if not self._autotune_dir:
            return conf
        from avenir_tpu.core.config import JobConfig

        if isinstance(conf, dict):
            if "stream.autotune.dir" in conf:
                return conf
            return {**conf, "stream.autotune.dir": self._autotune_dir}
        if isinstance(conf, JobConfig):
            if conf.get("stream.autotune.dir"):
                return conf
            props = dict(conf.props)
            props["stream.autotune.dir"] = self._autotune_dir
            return JobConfig(props, conf.prefix)
        return conf

    def _run_batch(self, batch: _Batch) -> Tuple[List, float]:
        """Execute primaries through the registered runner paths;
        (one JobResult per primary index-aligned, warm-hit flag)."""
        from avenir_tpu.runner import (run_incremental_shared, run_job,
                                       run_shared)

        reqs = [t.request for t in batch.tickets]
        inputs = reqs[0].inputs
        if batch.mode == "refresh":
            state_dirs = {}
            managed: List[str] = []
            self._checkout_sidecars(reqs)
            try:
                for req in reqs:
                    canonical = _scoped(req.job, req.conf)[0]
                    sd = req.state_dir
                    if not sd:
                        sd = self.warm.checkpoint_dir(canonical,
                                                      req.inputs)
                        managed.append(sd)
                    state_dirs[canonical] = sd
                shared = run_incremental_shared(
                    [(r.job, self._conf_with_tune_dir(r.conf),
                      r.output) for r in reqs], inputs,
                    state_dirs=state_dirs)
            finally:
                for sd in managed:
                    self.warm.release_dir(sd)
                self._pin_sidecars(reqs)
            return [shared[_scoped(r.job, r.conf)[0]] for r in reqs], 0.0
        if not batch.streamable:
            return [run_job(reqs[0].job,
                            self._conf_with_tune_dir(reqs[0].conf),
                            reqs[0].inputs, reqs[0].output)], 0.0
        # warm miner fast path: a lone mining request over a corpus
        # whose pinned source is still content-valid replays encoded
        # blocks — zero CSV parses
        if len(reqs) == 1:
            res = self._try_warm_miner(reqs[0])
            if res is not None:
                return [res], 1.0
        captured: Dict[str, object] = {}

        def fold_hook(canonical: str, fold) -> None:
            if canonical in _MINER_JOBS:
                fold.keep_sources = True
                captured[canonical] = fold

        self._checkout_sidecars(reqs)
        try:
            try:
                shared = run_shared(
                    [(r.job, self._conf_with_tune_dir(r.conf), r.output)
                     for r in reqs],
                    inputs, fold_hook=fold_hook)
            except BaseException:
                # a fold marked keep_sources holds its source (and spill
                # cache) open for pinning; on a failed batch nothing will
                # pin it — close here or a resident server leaks an fd
                # and on-disk cache segments per failed request
                for fold in captured.values():
                    src = getattr(fold, "src", None)
                    if src is not None:
                        try:
                            src.close()
                        except Exception:  # noqa: BLE001 — teardown
                            pass
                raise
            for canonical, fold in captured.items():
                req = next(r for r in reqs
                           if _scoped(r.job, r.conf)[0] == canonical)
                cfg = _scoped(req.job, req.conf)[2]
                self.warm.pin(
                    WarmStore.source_key(canonical, req.inputs, cfg),
                    fold.src)
        finally:
            # checked-out sidecar entries MUST return to the warm
            # store's byte accounting even when the batch raises —
            # mirrors the refresh branch (pin is advisory-safe)
            self._pin_sidecars(reqs)
        return [shared[_scoped(r.job, r.conf)[0]] for r in reqs], 0.0

    def _sidecar_keys(self, reqs):
        """(key, path, dirpath) for every input sidecar a streamed batch
        could touch, resolved from each request's own config — the dir
        name bakes in schema/delimiter/block size, so two jobs over the
        same file with different parse configs pin distinct entries.

        key-covered: all — the dir basename is the sidecar view digest.
        """
        from avenir_tpu.core.keys import key_site
        from avenir_tpu.native import sidecar as sc
        from avenir_tpu.runner import _schema, stream_fold_ops

        key_site("warm.sidecar.pin")
        out = []
        seen = set()
        for req in reqs:
            try:
                canonical, _prefix, cfg = _scoped(req.job, req.conf)
                ops = stream_fold_ops(canonical)
                opts = sc.opts_from_cfg(cfg)
                if opts is None:
                    continue
                block = int(cfg.get_float("stream.block.size.mb",
                                          64.0) * (1 << 20))
                delim = cfg.field_delim_regex
                for path in req.inputs:
                    if ops.kind == "dataset":
                        dirpath = sc.dataset_dir(opts, path, _schema(cfg),
                                                 delim, block)
                    else:
                        dirpath = sc.bytes_dir(
                            opts, path, delim,
                            cfg.get_int("skip.field.count", 1), block)
                    key = ("sidecar", os.path.abspath(path),
                           os.path.basename(dirpath))
                    if key in seen:
                        continue
                    seen.add(key)
                    out.append((key, path, dirpath))
            except Exception:  # noqa: BLE001 — advisory resolution
                continue
        return out

    def _checkout_sidecars(self, reqs) -> None:
        """Exclusively check pinned sidecar entries out of the warm
        store for the duration of a streamed batch so a concurrent
        budget squeeze cannot rmtree a directory the scan is replaying.
        The checked-out handles are deliberately dropped (a handle owns
        no fd); _pin_sidecars() re-registers fresh ones afterwards."""
        try:
            for key, _path, _dirpath in self._sidecar_keys(reqs):
                self.warm.lookup(key)
        except Exception:  # noqa: BLE001 — advisory
            pass

    def _pin_sidecars(self, reqs) -> None:
        """After a streamed batch, pin each input's (now-written)
        sidecar under the warm store's byte budget.  Eviction calls
        SidecarHandle.close(), which deletes the directory — the
        sidecar is a bounded cache, and the server is its landlord."""
        try:
            from avenir_tpu.native import sidecar as sc
            for key, path, dirpath in self._sidecar_keys(reqs):
                handle = sc.SidecarHandle(path, dirpath)
                if handle.cache_ready():
                    self.warm.pin(key, handle)
        except Exception:  # noqa: BLE001 — advisory
            pass

    def _try_warm_miner(self, req: JobRequest):
        from avenir_tpu.runner import run_warm_miner

        canonical, _prefix, cfg = _scoped(req.job, req.conf)
        if canonical not in _MINER_JOBS:
            return None
        key = WarmStore.source_key(canonical, req.inputs, cfg)
        src = self.warm.lookup(key)       # exclusive checkout
        if src is None:
            return None
        try:
            res = run_warm_miner(req.job, req.conf, req.inputs,
                                 req.output, src)
        except BaseException:
            src.close()                   # mid-mine state: never re-pin
            raise
        self.warm.pin(key, src)
        return res


def _copy_result(res, primary: JobRequest, dup: JobRequest):
    """A coalesced requester's JobResult: the primary's artifact files
    copied under the duplicate's output path (byte-identical by
    construction), counters duplicated so the Server:* injection stays
    per-ticket."""
    from avenir_tpu.runner import JobResult

    outputs: List[str] = []
    primary_out = os.path.abspath(primary.output)
    dup_out = os.path.abspath(dup.output)
    for src_path in res.outputs:
        sp = os.path.abspath(src_path)
        if sp == primary_out:
            target = dup_out
        else:
            rel = os.path.relpath(sp, primary_out)
            target = os.path.join(dup_out, rel)
        os.makedirs(os.path.dirname(target) or ".", exist_ok=True)
        shutil.copyfile(sp, target)
        outputs.append(target)
    return JobResult(res.name, dict(res.counters), outputs, res.payload)
