"""Resident analytics job server: the analytics-as-a-service surface.

The repo's jobs used to be batch invocations — every request paid
process startup, jit compile and a full corpus scan. The server keeps
ONE resident process accepting concurrent submissions and makes them
fast by sharing work: a batching scheduler groups compatible requests
into one SharedScan pass (``runner.run_shared`` /
``runner.run_incremental_shared``), a warm-state layer pins compiled
executables, encoded-block caches and fold-state checkpoints across
requests, and an admission controller prices every request in bytes
(graftlint-mem's footprint model) before it runs so the process never
breaches its RSS budget. See docs/DESIGN.md "The job server".
"""

from avenir_tpu.server.jobserver import (AdmissionError, JobRequest,
                                         JobServer, ServerClosed, Ticket,
                                         compat_key, price_request_bytes)
from avenir_tpu.server.spool import serve_main, serve_spool, serve_stream

__all__ = ["AdmissionError", "JobRequest", "JobServer", "ServerClosed",
           "Ticket", "compat_key", "price_request_bytes", "serve_main",
           "serve_spool", "serve_stream"]
