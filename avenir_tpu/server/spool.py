"""Request spool: drive a resident JobServer with no network in the loop.

Two hermetic transports, both JSON request objects with the
:class:`~avenir_tpu.server.jobserver.JobRequest` fields
(``{"job", "conf", "inputs", "output", "tenant", "priority", "mode"}``):

- **stream** — JSON lines on an input stream (stdin for the CLI), one
  result JSON line per request on the output stream, in submission
  order. EOF drains and exits: ``echo '{...}' | python -m avenir_tpu
  serve --stdin`` is a complete hermetic session, which is how tier-1
  drives the server end to end.
- **spool directory** — tenants atomically drop ``*.json`` request
  files into ``<spool>/in/`` (write elsewhere + rename, the usual
  maildir discipline); the server claims each by renaming it into
  ``<spool>/work/``, serves it, and writes the result to
  ``<spool>/out/<name>``. ``--once`` processes what is spooled, drains
  and exits; without it the loop polls until the process is signalled.

The CLI: ``python -m avenir_tpu serve [--stdin | --spool DIR] [--once]
[--budget-mb N] [--workers N] [--warm-budget-mb N] [--state-root DIR]``.
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Dict, List, Optional, Tuple

from avenir_tpu.server.jobserver import (DEFAULT_BUDGET_BYTES,
                                         DEFAULT_WARM_BUDGET_BYTES,
                                         JobRequest, JobServer, Ticket)

#: spool poll granularity (seconds)
_SPOOL_POLL_SECS = 0.1


def request_from_json(obj: Dict) -> JobRequest:
    """A JobRequest from one spool/stream JSON object; unknown fields
    are rejected so a typo'd key fails loudly instead of silently
    running with a default."""
    known = {"job", "conf", "inputs", "output", "tenant", "priority",
             "mode", "state_dir", "req_id"}
    extra = set(obj) - known
    if extra:
        raise ValueError(f"unknown request field(s): {sorted(extra)}")
    kwargs = dict(obj)
    kwargs.setdefault("conf", {})
    kwargs.setdefault("output", "")
    return JobRequest(**kwargs)


def result_to_json(ticket: Ticket) -> Dict:
    """The served (or failed) ticket as one result JSON object."""
    out = {"req_id": ticket.request.req_id,
           "tenant": ticket.request.tenant,
           "job": ticket.request.job}
    try:
        res = ticket.result(timeout=0)
        out.update({"ok": True, "name": res.name,
                    "counters": res.counters, "outputs": res.outputs})
    except BaseException as exc:  # noqa: BLE001 — the result IS the report
        out.update({"ok": False, "error": f"{type(exc).__name__}: {exc}"})
    return out


def serve_stream(server: JobServer, in_stream, out_stream,
                 drain_timeout: float = 86_400.0) -> int:
    """JSON-lines transport: submit every request line, drain, emit one
    result line per request in submission order. Returns the count of
    failed requests (the CLI exit code). The drain bound defaults to a
    day, not the server's 5-minute test-scale default — a session over
    a real corpus legitimately runs for many minutes, and a timeout
    here cancels every in-flight request."""
    tickets: List[Ticket] = []
    for line in in_stream:
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        try:
            tickets.append(server.submit(request_from_json(
                json.loads(line))))
        except Exception as exc:  # noqa: BLE001 — reported in-band
            failed = Ticket(JobRequest(job="<unparsed>", conf={},
                                       inputs=[], output=""))
            failed._complete(error=exc)
            tickets.append(failed)
    server.drain(timeout=drain_timeout)
    failures = 0
    for ticket in tickets:
        row = result_to_json(ticket)
        failures += 0 if row["ok"] else 1
        out_stream.write(json.dumps(row) + "\n")
    out_stream.flush()
    return failures


def spool_dirs(spool: str) -> Tuple[str, str, str]:
    """(in, work, out) subdirectories of a spool root, created."""
    paths = tuple(os.path.join(spool, d) for d in ("in", "work", "out"))
    for p in paths:
        os.makedirs(p, exist_ok=True)
    return paths


def _claim(in_dir: str, work_dir: str) -> List[Tuple[str, str]]:
    """Atomically claim every spooled request file: (name, work path)
    pairs. A rename that loses a race (another claimer, a writer still
    renaming in) is skipped, never an error."""
    claimed = []
    try:
        names = sorted(os.listdir(in_dir))
    except OSError:
        return []
    for name in names:
        if not name.endswith(".json"):
            continue
        src = os.path.join(in_dir, name)
        dst = os.path.join(work_dir, name)
        try:
            os.replace(src, dst)
        except OSError:
            continue
        claimed.append((name, dst))
    return claimed


def serve_spool(server: JobServer, spool: str, once: bool = False,
                should_stop=None) -> int:
    """Filesystem-spool transport (module docstring). Runs in the
    CALLER's thread — the server owns all worker threads — polling the
    in/ directory, submitting claims, and writing each completed
    ticket's result file as it finishes. Returns the failed-request
    count accumulated over the session."""
    in_dir, work_dir, out_dir = spool_dirs(spool)
    pending: List[Tuple[str, Ticket]] = []
    failures = 0
    while True:
        for name, work_path in _claim(in_dir, work_dir):
            try:
                with open(work_path) as fh:
                    req = request_from_json(json.load(fh))
                pending.append((name, server.submit(req)))
            except Exception as exc:  # noqa: BLE001 — reported in-band
                failed = Ticket(JobRequest(job="<unparsed>", conf={},
                                           inputs=[], output=""))
                failed._complete(error=exc)
                pending.append((name, failed))
        still = []
        for name, ticket in pending:
            if not ticket.done:
                still.append((name, ticket))
                continue
            row = result_to_json(ticket)
            failures += 0 if row["ok"] else 1
            tmp = os.path.join(out_dir, name + ".tmp")
            with open(tmp, "w") as fh:
                json.dump(row, fh, indent=1)
            os.replace(tmp, os.path.join(out_dir, name))
            try:
                os.remove(os.path.join(work_dir, name))
            except OSError:
                pass
        pending = still
        # only *.json files count as spooled work: a stray temp or dotfile
        # in in/ must not keep --once alive forever
        try:
            spooled = any(n.endswith(".json") for n in os.listdir(in_dir))
        except OSError:
            spooled = False
        drained = not pending and not spooled
        if once and drained:
            return failures
        if should_stop is not None and should_stop() and drained:
            return failures
        time.sleep(_SPOOL_POLL_SECS)


def serve_main(argv) -> int:
    """`python -m avenir_tpu serve ...` — build the server from flags,
    run one transport session, shut down cleanly."""
    import argparse

    ap = argparse.ArgumentParser(prog="avenir_tpu serve")
    group = ap.add_mutually_exclusive_group(required=True)
    group.add_argument("--stdin", action="store_true",
                       help="JSON-lines requests on stdin, results on "
                            "stdout; EOF drains and exits")
    group.add_argument("--spool", default=None,
                       help="spool directory: requests in <dir>/in, "
                            "results in <dir>/out")
    ap.add_argument("--once", action="store_true",
                    help="spool mode: serve what is spooled, drain, exit")
    ap.add_argument("--budget-mb", type=float,
                    default=DEFAULT_BUDGET_BYTES / (1 << 20),
                    help="admission RSS ceiling (default 3072)")
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--warm-budget-mb", type=float,
                    default=DEFAULT_WARM_BUDGET_BYTES / (1 << 20),
                    help="pinned encoded-block cache budget (default 256)")
    ap.add_argument("--state-root", default=None,
                    help="managed incremental-checkpoint root (default: "
                         "a per-session temp dir)")
    ap.add_argument("--metrics", default=None,
                    help="metrics.json snapshot path (default: "
                         "<spool>/metrics.json in spool mode; off for "
                         "--stdin unless given)")
    ap.add_argument("--metrics-interval", type=float, default=2.0,
                    help="seconds between metrics.json refreshes "
                         "(default 2)")
    args = ap.parse_args(argv)
    metrics_path = args.metrics
    if metrics_path is None and args.spool:
        os.makedirs(args.spool, exist_ok=True)
        metrics_path = os.path.join(args.spool, "metrics.json")
    server = JobServer(budget_bytes=int(args.budget_mb * (1 << 20)),
                       workers=args.workers,
                       warm_budget_bytes=int(
                           args.warm_budget_mb * (1 << 20)),
                       state_root=args.state_root,
                       metrics_path=metrics_path,
                       metrics_interval_s=args.metrics_interval)
    server.start()
    try:
        if args.stdin:
            failures = serve_stream(server, sys.stdin, sys.stdout)
        else:
            failures = serve_spool(server, args.spool, once=args.once)
    finally:
        server.shutdown()
    print(json.dumps({"server": "done", "failed": failures,
                      "stats": server.stats()}), file=sys.stderr)
    return 1 if failures else 0
