"""Request spool: drive a resident JobServer with no network in the loop.

Two hermetic transports, both JSON request objects with the
:class:`~avenir_tpu.server.jobserver.JobRequest` fields
(``{"job", "conf", "inputs", "output", "tenant", "priority", "mode"}``):

- **stream** — JSON lines on an input stream (stdin for the CLI), one
  result JSON line per request on the output stream, in submission
  order. EOF drains and exits: ``echo '{...}' | python -m avenir_tpu
  serve --stdin`` is a complete hermetic session, which is how tier-1
  drives the server end to end.
- **spool directory** — tenants atomically drop ``*.json`` request
  files into ``<spool>/in/`` (write elsewhere + rename, the usual
  maildir discipline); the server claims each by renaming it into
  ``<spool>/work/``, serves it, and writes the result to
  ``<spool>/out/<name>``. ``--once`` processes what is spooled, drains
  and exits; without it the loop polls until the process is signalled.
  A claimed file whose bytes cannot parse as JSON is moved to
  ``<spool>/dead/`` with a ``.reason`` file (:func:`dead_letter`) —
  never re-claimable, so a torn request cannot crash-loop a restarted
  host — while the in-band failure row still goes out.

Result namespacing: a request may carry a client ``nonce`` token; its
result then lands at ``<spool>/out/<nonce>.<name>`` instead of
``<spool>/out/<name>``, so two clients reusing one filename stem can
never overwrite each other's results (claimed work files are likewise
uniquified, so a re-submitted stem never clobbers one mid-serve).

The CLI: ``python -m avenir_tpu serve [--stdin | --spool DIR |
--listen HOST:PORT] [--once] [--budget-mb N] [--workers N]
[--warm-budget-mb N] [--state-root DIR]``. Spool and listen sessions
treat SIGTERM/SIGINT as graceful drain: stop accepting, finish
in-flight work, write the final metrics.json, exit 0.
"""

from __future__ import annotations

import json
import os
import re
import sys
import threading
import time
import uuid
from typing import Callable, Dict, List, Optional, Tuple

from avenir_tpu.core.atomic import (publish_bytes, publish_json,
                                    sched_point, sweep_stale_tmps)
from avenir_tpu.server.jobserver import (DEFAULT_BUDGET_BYTES,
                                         DEFAULT_WARM_BUDGET_BYTES,
                                         JobRequest, JobServer, Ticket)

#: spool poll granularity (seconds)
_SPOOL_POLL_SECS = 0.1
#: a client nonce is a filename-safe token — it becomes a result-file
#: prefix, so path separators and dots-at-the-front must be impossible
_NONCE_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._-]{0,63}$")


def request_from_json(obj: Dict) -> JobRequest:
    """A JobRequest from one spool/stream JSON object; unknown fields
    are rejected so a typo'd key fails loudly instead of silently
    running with a default."""
    known = {"job", "conf", "inputs", "output", "tenant", "priority",
             "mode", "state_dir", "nonce", "req_id"}
    extra = set(obj) - known
    if extra:
        raise ValueError(f"unknown request field(s): {sorted(extra)}")
    kwargs = dict(obj)
    kwargs.setdefault("conf", {})
    kwargs.setdefault("output", "")
    nonce = kwargs.get("nonce")
    if nonce is not None and not _NONCE_RE.match(str(nonce)):
        raise ValueError(
            f"invalid nonce {nonce!r}: expected a filename-safe token "
            f"([A-Za-z0-9][A-Za-z0-9._-]*, at most 64 chars)")
    return JobRequest(**kwargs)


def result_to_json(ticket: Ticket) -> Dict:
    """The served (or failed) ticket as one result JSON object."""
    out = {"req_id": ticket.request.req_id,
           "tenant": ticket.request.tenant,
           "job": ticket.request.job}
    if ticket.request.nonce:
        out["nonce"] = ticket.request.nonce
    try:
        res = ticket.result(timeout=0)
        out.update({"ok": True, "name": res.name,
                    "counters": res.counters, "outputs": res.outputs})
    except BaseException as exc:  # noqa: BLE001 — the result IS the report
        out.update({"ok": False, "error": f"{type(exc).__name__}: {exc}"})
    return out


def serve_stream(server: JobServer, in_stream, out_stream,
                 drain_timeout: float = 86_400.0) -> int:
    """JSON-lines transport: submit every request line, drain, emit one
    result line per request in submission order. Returns the count of
    failed requests (the CLI exit code). The drain bound defaults to a
    day, not the server's 5-minute test-scale default — a session over
    a real corpus legitimately runs for many minutes, and a timeout
    here cancels every in-flight request."""
    tickets: List[Ticket] = []
    for line in in_stream:
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        try:
            tickets.append(server.submit(request_from_json(
                json.loads(line))))
        except Exception as exc:  # noqa: BLE001 — reported in-band
            failed = Ticket(JobRequest(job="<unparsed>", conf={},
                                       inputs=[], output=""))
            failed._complete(error=exc)
            tickets.append(failed)
    server.drain(timeout=drain_timeout)
    failures = 0
    for ticket in tickets:
        row = result_to_json(ticket)
        failures += 0 if row["ok"] else 1
        out_stream.write(json.dumps(row) + "\n")
    out_stream.flush()
    return failures


def spool_dirs(spool: str) -> Tuple[str, str, str]:
    """(in, work, out) subdirectories of a spool root, created."""
    paths = tuple(os.path.join(spool, d) for d in ("in", "work", "out"))
    for p in paths:
        os.makedirs(p, exist_ok=True)
    return paths


def _claim(in_dir: str, work_dir: str) -> List[Tuple[str, str]]:
    """Atomically claim every spooled request file: (name, work path)
    pairs. The work path carries a per-claim unique suffix, so a
    re-submitted filename stem can never overwrite a same-named claim
    still being served. A rename that loses a race (another claimer, a
    writer still renaming in) is skipped, never an error."""
    claimed = []
    try:
        names = sorted(os.listdir(in_dir))
    except OSError:
        return []
    for name in names:
        if not name.endswith(".json"):
            continue
        src = os.path.join(in_dir, name)
        dst = os.path.join(work_dir, f"{name}.{uuid.uuid4().hex[:8]}")
        sched_point("spool.claim")
        try:
            os.replace(src, dst)
        except OSError:
            continue
        claimed.append((name, dst))
    return claimed


def dead_letter(spool: str, name: str, work_path: str,
                reason: str) -> str:
    """Move a torn/unparseable claimed request to ``<spool>/dead/``
    with a ``.reason`` file beside it, and return the dead path. A
    request whose BYTES cannot even parse must leave the claim loop
    for good — requeueing it (a restarted host re-adopting its work
    dir, a fleet front retrying a lease) would fail identically
    forever, a crash loop with no exit. The payload is preserved for
    the operator (the reason file says why it landed there); the
    in-band failure row still goes out so a polling client sees the
    failure."""
    dead_dir = os.path.join(spool, "dead")
    os.makedirs(dead_dir, exist_ok=True)
    dead_path = os.path.join(dead_dir, os.path.basename(work_path))
    try:
        os.replace(work_path, dead_path)
    except OSError:
        dead_path = work_path          # already gone: report in place
    reason_path = os.path.join(dead_dir, f"{name}.reason")
    try:
        publish_bytes((reason + "\n").encode("utf-8"), reason_path,
                      site="spool.dead_letter")
    except OSError:
        pass
    return dead_path


def load_claimed(spool: str, name: str, work_path: str) -> Dict:
    """Parse one claimed request file — THE torn-request policy, shared
    by ``serve_spool`` and the fleet front's claim loop: bytes that
    cannot parse are dead-lettered (moved out of the claim loop for
    good) and the error re-raised for the caller's in-band failure
    row."""
    try:
        with open(work_path) as fh:
            return json.load(fh)
    except (OSError, ValueError, UnicodeDecodeError) as exc:
        dead_letter(spool, name, work_path,
                    f"{type(exc).__name__}: {exc}")
        raise


def nonce_result_name(name: str, nonce: Optional[str]) -> str:
    """THE (client nonce, id) result-file recipe — the one place the
    ``<nonce>.<name>`` join lives, shared by the host-side spool
    writer, the fleet front's expected-path computation and its
    failure rows (three sites that must agree byte-for-byte or the
    front polls a path the host never writes)."""
    return f"{nonce}.{name}" if nonce else name


def result_name(name: str, ticket: Ticket) -> str:
    """The out/ filename of one served request: the submitted filename,
    prefixed by the request's client nonce when it carried one — the
    namespacing that stops two clients reusing one filename stem from
    overwriting each other's results."""
    return nonce_result_name(name, getattr(ticket.request, "nonce",
                                           None))


def publish_result(out_dir: str, out_name: str, row: Dict) -> str:
    """Atomically publish one result row at ``<out>/<out_name>`` — THE
    spool result commit (a polling client sees no file or a complete
    one, never a torn row). A registered commit site: graftlint
    --proto kill-injects both sides of the rename."""
    return publish_json(row, os.path.join(out_dir, out_name),
                        site="spool.result", indent=1)


def write_port_file(port_file: str, port: int) -> str:
    """Atomically publish the bound port for scripts that asked for
    port 0 — a reader either sees no port file or a complete one."""
    return publish_bytes(str(port).encode("utf-8"), port_file,
                         site="spool.port")


def serve_spool(server: JobServer, spool: str, once: bool = False,
                should_stop=None) -> int:
    """Filesystem-spool transport (module docstring). Runs in the
    CALLER's thread — the server owns all worker threads — polling the
    in/ directory, submitting claims, and writing each completed
    ticket's result file as it finishes. Returns the failed-request
    count accumulated over the session.

    ``should_stop`` turning true is the graceful-drain signal: the loop
    stops claiming NEW spool files, finishes every claimed request, and
    returns — what SIGTERM/SIGINT mean for a ``serve --spool``
    session."""
    in_dir, work_dir, out_dir = spool_dirs(spool)
    # startup GC: tmp files a hard-killed session left behind (the age
    # gate keeps a concurrent writer's live tmp safe)
    for d in (in_dir, work_dir, out_dir):
        sweep_stale_tmps(d)
    pending: List[Tuple[str, str, Ticket]] = []
    failures = 0
    while True:
        stopping = should_stop is not None and should_stop()
        if not stopping:
            for name, work_path in _claim(in_dir, work_dir):
                obj = None
                try:
                    obj = load_claimed(spool, name, work_path)
                    req = request_from_json(obj)
                    pending.append((name, work_path, server.submit(req)))
                except Exception as exc:  # noqa: BLE001 — reported in-band
                    # the failure row must honor the nonce namespace
                    # too — a nonce-polling client has to SEE its
                    # failure, and an un-namespaced row could clobber
                    # another client's same-stem result
                    nonce = obj.get("nonce") \
                        if isinstance(obj, dict) else None
                    if not (isinstance(nonce, str)
                            and _NONCE_RE.match(nonce)):
                        nonce = None
                    failed = Ticket(JobRequest(job="<unparsed>", conf={},
                                               inputs=[], output="",
                                               nonce=nonce))
                    failed._complete(error=exc)
                    pending.append((name, work_path, failed))
        still = []
        for name, work_path, ticket in pending:
            if not ticket.done:
                still.append((name, work_path, ticket))
                continue
            row = result_to_json(ticket)
            failures += 0 if row["ok"] else 1
            out_name = result_name(name, ticket)
            publish_result(out_dir, out_name, row)
            try:
                os.remove(work_path)
            except OSError:
                pass
        pending = still
        if stopping and not pending:
            # drained what was claimed; unclaimed spool files stay for
            # the next session — the graceful half of a SIGTERM exit
            return failures
        # only *.json files count as spooled work: a stray temp or dotfile
        # in in/ must not keep --once alive forever
        try:
            spooled = any(n.endswith(".json") for n in os.listdir(in_dir))
        except OSError:
            spooled = False
        if once and not pending and not spooled:
            return failures
        time.sleep(_SPOOL_POLL_SECS)


def install_drain_handlers(stop: threading.Event) -> Callable[[], bool]:
    """SIGTERM/SIGINT set `stop` (graceful drain) instead of killing
    the process mid-serve; a SECOND signal restores the default
    disposition and re-raises, so an operator whose drain is wedged on
    a hung job can still escalate (signal once = drain, twice = die)
    without resorting to SIGKILL's no-teardown exit. Returns
    ``stop.is_set`` as the loop predicate. No-op outside the main
    thread (in-process test harnesses), where the caller drives `stop`
    directly."""
    import os
    import signal

    def _graceful(signum, frame):      # noqa: ARG001 — signal signature
        if stop.is_set():               # second signal: stop draining
            signal.signal(signum, signal.SIG_DFL)
            os.kill(os.getpid(), signum)
            return
        stop.set()

    try:
        signal.signal(signal.SIGTERM, _graceful)
        signal.signal(signal.SIGINT, _graceful)
    except ValueError:                  # not the main thread
        pass
    return stop.is_set


def serve_listen(server: JobServer, listen: str, stop: threading.Event,
                 policy=None, port_file: Optional[str] = None) -> int:
    """One ``serve --listen`` session: start the HTTP edge, run until
    `stop` (the signal handlers' event), then drain gracefully — edge
    refuses new work (healthz flips to draining), in-flight requests
    finish, the final metrics snapshot is the caller's shutdown().
    Returns the failed-request count served over the session."""
    from avenir_tpu.net.listener import NetListener

    host, _, port = listen.rpartition(":")
    listener = NetListener(server, host=host or "127.0.0.1",
                           port=int(port or 0), policy=policy)
    listener.start()
    try:
        print(json.dumps({"server": "listening",
                          "address": listener.address}),
              file=sys.stderr, flush=True)
        if port_file:
            write_port_file(port_file, listener.port)
        while not stop.is_set():
            stop.wait(_SPOOL_POLL_SECS)
        listener.begin_drain()
        server.drain(timeout=86_400.0)
    finally:
        listener.stop()
    return int(server.stats()["failed"])


def serve_main(argv) -> int:
    """`python -m avenir_tpu serve ...` — build the server from flags,
    run one transport session, shut down cleanly. Spool and listen
    sessions drain gracefully on SIGTERM/SIGINT: stop accepting,
    finish in-flight, write the final metrics.json, exit 0."""
    import argparse

    ap = argparse.ArgumentParser(prog="avenir_tpu serve")
    group = ap.add_mutually_exclusive_group(required=True)
    group.add_argument("--stdin", action="store_true",
                       help="JSON-lines requests on stdin, results on "
                            "stdout; EOF drains and exits")
    group.add_argument("--spool", default=None,
                       help="spool directory: requests in <dir>/in, "
                            "results in <dir>/out")
    group.add_argument("--listen", default=None,
                       help="HOST:PORT for the JSON-over-HTTP edge "
                            "(port 0 binds an ephemeral port, printed "
                            "as a JSON line on stderr)")
    ap.add_argument("--once", action="store_true",
                    help="spool mode: serve what is spooled, drain, exit")
    ap.add_argument("--budget-mb", type=float,
                    default=DEFAULT_BUDGET_BYTES / (1 << 20),
                    help="admission RSS ceiling (default 3072)")
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--warm-budget-mb", type=float,
                    default=DEFAULT_WARM_BUDGET_BYTES / (1 << 20),
                    help="pinned encoded-block cache budget (default 256)")
    ap.add_argument("--state-root", default=None,
                    help="managed incremental-checkpoint root (default: "
                         "a per-session temp dir)")
    ap.add_argument("--autotune-dir", default=None,
                    help="autotune profile store (tuned pricer + "
                         "fold-cost-balanced batches; the fleet shares "
                         "one across hosts)")
    ap.add_argument("--metrics", default=None,
                    help="metrics.json snapshot path (default: "
                         "<spool>/metrics.json in spool mode; off for "
                         "--stdin/--listen unless given)")
    ap.add_argument("--metrics-interval", type=float, default=2.0,
                    help="seconds between metrics.json refreshes "
                         "(default 2)")
    ap.add_argument("--shed-mode", choices=("reject", "hold"),
                    default="reject",
                    help="listen mode: edge behavior past the priced "
                         "budget or tenant depth bound — 429 with "
                         "Retry-After, or hold the accept (default "
                         "reject)")
    ap.add_argument("--max-tenant-depth", type=int, default=64,
                    help="listen mode: per-tenant queued-request bound "
                         "before the edge sheds (default 64)")
    ap.add_argument("--port-file", default=None,
                    help="listen mode: write the bound port here "
                         "(atomic), for scripts that asked for port 0")
    args = ap.parse_args(argv)
    metrics_path = args.metrics
    if metrics_path is None and args.spool:
        os.makedirs(args.spool, exist_ok=True)
        metrics_path = os.path.join(args.spool, "metrics.json")
    server = JobServer(budget_bytes=int(args.budget_mb * (1 << 20)),
                       workers=args.workers,
                       warm_budget_bytes=int(
                           args.warm_budget_mb * (1 << 20)),
                       state_root=args.state_root,
                       autotune_dir=args.autotune_dir,
                       metrics_path=metrics_path,
                       metrics_interval_s=args.metrics_interval)
    stop = threading.Event()
    # stdin sessions keep the default signal behavior (Ctrl+C/SIGTERM
    # end them; EOF is their graceful drain) — a drain handler there
    # would absorb the signals while serve_stream blocks on a read it
    # cannot be woken from, leaving the session killable only by EOF
    # or SIGKILL
    should_stop = stop.is_set if args.stdin \
        else install_drain_handlers(stop)
    server.start()
    try:
        if args.stdin:
            failures = serve_stream(server, sys.stdin, sys.stdout)
        elif args.listen is not None:
            from avenir_tpu.net.listener import EdgePolicy

            failures = serve_listen(
                server, args.listen, stop,
                policy=EdgePolicy(shed_mode=args.shed_mode,
                                  max_tenant_depth=args.max_tenant_depth),
                port_file=args.port_file)
        else:
            failures = serve_spool(server, args.spool, once=args.once,
                                   should_stop=should_stop)
    finally:
        server.shutdown()
    print(json.dumps({"server": "done", "failed": failures,
                      "drained": stop.is_set(),
                      "stats": server.stats()}), file=sys.stderr)
    return 1 if failures else 0
