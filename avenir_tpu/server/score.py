"""avenir-score: micro-batched online scoring beside the batch scheduler.

Everything else in the server is job-shaped — a request names a corpus
and buys a scan. The traffic real deployments serve is query-shaped: one
row, one trained artifact, an answer in milliseconds (the reference's
Storm+Redis real-time RL layer). The perf thesis is the repo's usual
one: share the expensive thing. Here the expensive things are the
*loaded model* (parse + device upload per request would dwarf sub-ms
math) and the *dispatch* (one jitted call has a fixed host cost that
dominates single-row predicts), so the plane keeps both warm:

- **ModelCache** — a budget-bounded warm cache of loaded scorers with
  EXCLUSIVE CHECKOUT (WarmStore's pop-on-lookup discipline,
  server/jobserver.py): a checked-out entry is *out of the cache*, so
  the budget sweep can never unload a model a dispatch is using —
  delete-while-checked-out safety by construction, not by flag. Cache
  identity is :func:`avenir_tpu.core.keys.model_tuple` (artifact
  content digest, schema digest, stamped format version, kind dims):
  a retrained artifact, an edited schema or a foreign restamp can only
  MISS — stale fits are unreachable, never invalidated in place.
- **micro-batch coalescer** — arriving scores for one (model, conf)
  group are held at most ``score.batch.window.ms`` (default 2ms) or
  until ``score.batch.max`` rows, then ONE vectorized predict serves
  the whole window and results demultiplex per request. Every family's
  predict is invariant to batch composition (models/ entry points), so
  the demuxed row is bit-identical to a solo predict — the window
  trades a bounded latency add for an amortized dispatch, which is
  what pins the p99: under load the window fills instantly and the
  per-row cost is predict/N.

Model loads are digest-verified (models/artifact.py): a stamped
artifact whose stamp names a foreign ``format_version`` REFUSES to
load (:class:`ModelFormatSkew`) and the plane goes cold for that model
— the PR 19 manifest contract extended to served models.

Bandit scoring folds a **reward journal** — a streaming append journal
beside the artifact (``<artifact>.rewards.json``) holding post-serve
reward observations. Appends commit atomically under the registered
``score.reward`` crash site and carry a nonce so a retried append is
exactly-once. single-writer: one ScorePlane owns the journals beside
the artifacts it serves; appends are serialized under the plane's
journal lock, and a second process appending to the same journal is
out of contract (the lost-update window between its read and publish
is the documented cost of the whole-file atomic commit).
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import OrderedDict, deque
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional, Sequence, Tuple

import numpy as np

from avenir_tpu.core.atomic import publish_json
from avenir_tpu.core import keys as _keys
from avenir_tpu.models.artifact import (ModelFormatSkew, file_digest,
                                        stamp_version, verify_stamp)
from avenir_tpu.obs.histogram import LatencyHistogram

#: coalescing window: how long a dispatch waits for co-travellers
DEFAULT_WINDOW_MS = 2.0
#: rows per dispatch ceiling — a full window never waits out the clock
DEFAULT_BATCH_MAX = 64
#: warm model cache budget
DEFAULT_CACHE_BUDGET = 256 << 20

#: the scoreable families (each maps 1:1 to a batch predictor's row math)
SCORE_KINDS = ("bayes", "discriminant", "markov", "bandit")

REWARD_JOURNAL_VERSION = 1

_JOIN_SECS = 10.0


class ScoreError(RuntimeError):
    """A score request that cannot be served (bad kind/conf/row)."""


class ScoreTimeout(ScoreError):
    """The caller's wait deadline passed before the window dispatched."""


# ======================================================================
# request / result
# ======================================================================

_KNOWN_FIELDS = {"kind", "model", "row", "conf", "action", "req_id"}
_ACTIONS = ("score", "reward")


@dataclass
class ScoreRequest:
    """One query: a row against a trained artifact. ``conf`` carries
    the family's loader/classifier knobs (the same key names the batch
    jobs read, minus their job prefix); ``action="reward"`` is the
    bandit feedback path (row = ``group,item,reward[,count]``)."""

    kind: str
    model: str
    row: str
    conf: Dict[str, str] = field(default_factory=dict)
    action: str = "score"
    req_id: str = ""


@dataclass
class ScoreResult:
    """The demuxed answer plus the stage timings the histograms see."""

    row: str
    req_id: str = ""
    kind: str = ""
    model: str = ""
    window_rows: int = 1
    queue_ms: float = 0.0
    batch_ms: float = 0.0
    predict_ms: float = 0.0
    total_ms: float = 0.0

    def to_json(self) -> Dict:
        return {"row": self.row, "req_id": self.req_id,
                "kind": self.kind, "model": self.model,
                "window_rows": self.window_rows,
                "timings_ms": {"queue": round(self.queue_ms, 3),
                               "batch": round(self.batch_ms, 3),
                               "predict": round(self.predict_ms, 3),
                               "total": round(self.total_ms, 3)}}


def score_request_from_json(obj: Dict) -> ScoreRequest:
    """Strict parse of one ``POST /score`` body — unknown fields are
    rejected (the spool request contract), so a client typo can never
    silently no-op a knob."""
    if not isinstance(obj, dict):
        raise ValueError("score request must be a JSON object")
    unknown = set(obj) - _KNOWN_FIELDS
    if unknown:
        raise ValueError(f"unknown score request fields: {sorted(unknown)}")
    kind = obj.get("kind", "")
    if kind not in SCORE_KINDS:
        raise ValueError(f"unknown score kind {kind!r} "
                         f"(want one of {list(SCORE_KINDS)})")
    action = obj.get("action", "score")
    if action not in _ACTIONS:
        raise ValueError(f"unknown score action {action!r}")
    model = obj.get("model", "")
    if not model:
        raise ValueError("score request needs a model artifact path")
    row = obj.get("row", "")
    if not isinstance(row, str) or not row.strip():
        raise ValueError("score request needs a non-empty row string")
    if "\n" in row or "\r" in row:
        # one request, one row: an embedded newline would parse into
        # extra dataset rows and shift every later slot's positional
        # demux — cross-request leakage, so it is rejected at the edge
        raise ValueError("score row must be a single line "
                         "(embedded newlines break window framing)")
    conf = obj.get("conf", {}) or {}
    if not isinstance(conf, dict):
        raise ValueError("conf must be an object of string knobs")
    conf = {str(k): str(v) for k, v in conf.items()}
    return ScoreRequest(kind=kind, model=model, row=row, conf=conf,
                        action=action, req_id=str(obj.get("req_id", "")))


# ======================================================================
# reward journal (streaming append beside the artifact)
# ======================================================================

def reward_journal_path(artifact: str) -> str:
    return artifact + ".rewards.json"


def load_reward_journal(artifact: str, strict: bool = False) -> List[Dict]:
    """The journal's entries in append order ([] when absent). A
    journal stamped with a foreign format refuses like a model does.

    ``strict`` is the WRITER's mode (read_stamp's skew-not-absence
    rule): a present-but-unparseable journal raises instead of reading
    as [], because the append path republishes whatever this returns —
    shrugging there would overwrite all prior reward history with a
    journal containing only the new entry."""
    path = reward_journal_path(artifact)
    try:
        with open(path) as fh:
            obj = json.load(fh)
    except FileNotFoundError:
        return []
    except (OSError, ValueError) as exc:
        if strict:
            raise ModelFormatSkew(
                f"unreadable reward journal {path}: {exc} — refusing "
                "to publish over history that cannot be read") from exc
        # torn by a racing delete/external truncation, which every
        # protocol READER treats as absent, never a crash
        return []
    if obj.get("format_version") != REWARD_JOURNAL_VERSION:
        raise ModelFormatSkew(
            f"reward journal beside {artifact}: format_version="
            f"{obj.get('format_version')!r}, this build speaks "
            f"{REWARD_JOURNAL_VERSION}")
    return list(obj.get("entries", []))


def append_reward(artifact: str, group: str, item: str, reward: float,
                  count: int = 1, nonce: Optional[str] = None) -> Dict:
    """Append one reward observation to the artifact's journal.

    Read-extend-publish under the ``score.reward`` crash site: the
    rename either lands the new entry or leaves the old journal — a
    crash can never tear it. ``nonce`` makes the append exactly-once
    (a retry after an ambiguous crash re-sends the same nonce and
    dedupes), which is also what makes the crash auditor's recovery —
    just re-run the append — idempotent. single-writer: callers
    serialize through the owning plane's journal lock.
    """
    entries = load_reward_journal(artifact, strict=True)
    if nonce is not None:
        for e in entries:
            if e.get("nonce") == nonce:
                return {"applied": False, "entries": len(entries)}
    entries.append({"group": str(group), "item": str(item),
                    "reward": float(reward), "count": int(count),
                    "nonce": nonce})
    publish_json({"format_version": REWARD_JOURNAL_VERSION,
                  "entries": entries},
                 reward_journal_path(artifact), site="score.reward")
    return {"applied": True, "entries": len(entries)}


def fold_rewards(data, entries: Sequence[Dict]) -> None:
    """Fold journal entries into a loaded GroupBanditData in append
    order: trial counts add, the per-item average reward re-weights by
    the incoming observation count — the same running-average algebra
    the reference's aggregate loop applies between rounds, so a folded
    journal equals a re-aggregated stats file up to float32 rounding."""
    index = {(g, it): (gi, ai)
             for gi, g in enumerate(data.group_ids)
             for ai, it in enumerate(data.item_ids[gi])}
    for e in entries:
        pos = index.get((e["group"], e["item"]))
        if pos is None:
            raise ScoreError(
                f"reward journal names unknown arm "
                f"({e['group']!r}, {e['item']!r})")
        gi, ai = pos
        c0 = int(data.counts[gi, ai])
        n = int(e.get("count", 1))
        total = np.float64(data.rewards[gi, ai]) * c0 + e["reward"]
        data.counts[gi, ai] = c0 + n
        data.rewards[gi, ai] = np.float32(total / max(c0 + n, 1))


def reward_journal_digest(artifact: str) -> str:
    """Content digest of the journal ('' when absent) — a model-cache
    key dim for bandits, so a fresh reward observation makes the warm
    folded stats unreachable instead of stale."""
    try:
        return file_digest(reward_journal_path(artifact))
    except FileNotFoundError:
        return ""


# ======================================================================
# family scorers — thin wrappers over the models/ vectorized entry
# points, each returning the BATCH JOB's exact per-row output string
# ======================================================================

def _conf_list(conf: Dict[str, str], key: str, delim: str) -> List[str]:
    raw = conf.get(key, "")
    return [t.strip() for t in raw.split(delim)] if raw else []


class _BayesScorer:
    """NB class posterior — bayesianPredictor's row math (runner.py)."""

    def __init__(self, model_path: str, conf: Dict[str, str]):
        from avenir_tpu.core.schema import FeatureSchema
        from avenir_tpu.models.naive_bayes import (NaiveBayesModel,
                                                   NaiveBayesPredictor)
        from avenir_tpu.utils.metrics import CostBasedArbitrator

        self.delim = conf.get("field.delim", ",")
        schema_path = conf.get("schema.path", "")
        if not schema_path:
            raise ScoreError("bayes scoring needs conf['schema.path']")
        self.schema = FeatureSchema.from_file(schema_path)
        model = NaiveBayesModel.load(model_path, self.schema,
                                     delim=self.delim)
        arbitrator = None
        costs = _conf_list(conf, "predict.class.cost", self.delim)
        if costs:
            classes = _conf_list(conf, "predict.class", self.delim) \
                or self.schema.class_values()
            arbitrator = CostBasedArbitrator(classes[0], classes[1],
                                             int(costs[0]), int(costs[1]))
        self.pred = NaiveBayesPredictor(model, arbitrator=arbitrator)
        self.cls_vals = self.schema.class_values()
        tables = model.finish()
        self.nbytes = sum(int(np.asarray(t).nbytes)
                          for t in tables.values())

    def predict_rows(self, rows: Sequence[str]) -> List[str]:
        from avenir_tpu.core.dataset import Dataset
        ds = Dataset.from_csv("\n".join(rows) + "\n", self.schema,
                              delim=self.delim, keep_raw=True)
        if len(ds.raw_rows) != len(rows):
            # a blank row vanishes (Dataset skips it) and an embedded
            # newline splits in two — either way positional demux
            # would hand later slots the wrong answers, so refuse
            raise ScoreError(
                f"bayes window framing: {len(rows)} request rows "
                f"parsed into {len(ds.raw_rows)} dataset rows "
                "(blank or multi-line row in the batch)")
        codes, post = self.pred.predict(ds)
        out = []
        for raw, c, row_post in zip(ds.raw_rows, codes, post):
            tot = float(np.sum(row_post)) or 1.0
            prob = int(np.rint(100.0 * row_post[int(c)] / tot))
            out.append(self.delim.join(
                raw + [self.cls_vals[int(c)], str(prob)]))
        return out


class _DiscriminantScorer:
    """Fisher boundary side — FisherDiscriminant.predict's math."""

    def __init__(self, model_path: str, conf: Dict[str, str]):
        from avenir_tpu.models.discriminant import FisherDiscriminant
        self.delim = conf.get("field.delim", ",")
        self.fd = FisherDiscriminant.load(model_path, delim=self.delim)
        self.nbytes = 64 * max(len(self.fd.boundaries), 1)

    def predict_rows(self, rows: Sequence[str], conf: Dict[str, str]
                     ) -> List[str]:
        ordinal = int(conf.get("ordinal", "-1"))
        if ordinal < 0:
            raise ScoreError("discriminant scoring needs conf['ordinal']")
        toks = [[t.strip() for t in r.split(self.delim)] for r in rows]
        x = np.asarray([float(t[ordinal]) for t in toks], np.float64)
        side = self.fd.predict_values(ordinal, x)
        return [self.delim.join(t + [str(int(s))])
                for t, s in zip(toks, side)]


class _MarkovScorer:
    """Sequence log-odds class — markovModelClassifier's row math."""

    def __init__(self, model_path: str, conf: Dict[str, str]):
        from avenir_tpu.models.markov import (MarkovModelClassifier,
                                              MarkovStateTransitionModel)
        self.delim = conf.get("field.delim", ",")
        model = MarkovStateTransitionModel.load(model_path,
                                                delim=self.delim)
        labels = _conf_list(conf, "class.labels", self.delim)
        if len(labels) != 2:
            raise ScoreError("markov scoring needs conf['class.labels'] "
                             "= 'pos,neg'")
        self.clf = MarkovModelClassifier(
            model, labels[0], labels[1],
            threshold=float(conf.get("log.odds.threshold", "0")))
        self.skip = int(conf.get("skip.field.count", "1"))
        self.nbytes = int(np.asarray(self.clf.log_odds).nbytes) \
            + int(model.counts.nbytes)

    def predict_rows(self, rows: Sequence[str]) -> List[str]:
        # token trim matches runner._parse_sequences exactly
        ids, seqs = [], []
        for ln in rows:
            toks = [t.strip(" \t\r") for t in ln.split(self.delim)]
            ids.append(toks[0] if self.skip > 0 else "")
            seqs.append(toks[self.skip:])
        cls, scores = self.clf.predict(seqs)
        return [f"{rid}{self.delim}{c}{self.delim}{s:.6f}"
                for rid, c, s in zip(ids, cls, scores)]


class _BanditScorer:
    """Arm pull — bandit_job's per-group selection rows, with the
    reward journal folded into the loaded stats. Every select runs
    over the FULL group set with the round's seeded key (exactly the
    batch job's execution), then demuxes the requested groups — which
    is what makes a coalesced pull bit-identical to a solo one."""

    def __init__(self, model_path: str, conf: Dict[str, str]):
        from avenir_tpu.models.bandits import GroupBanditData
        verify_stamp(model_path)
        self.delim = conf.get("field.delim", ",")
        with open(model_path) as fh:
            rows = [[t.strip() for t in ln.split(self.delim)]
                    for ln in fh if ln.strip()]
        self.data = GroupBanditData.from_rows(
            rows,
            count_ord=int(conf.get("count.ordinal", "2")),
            reward_ord=int(conf.get("reward.ordinal", "3")))
        fold_rewards(self.data, load_reward_journal(model_path))
        self.nbytes = int(self.data.counts.nbytes
                          + self.data.rewards.nbytes
                          + self.data.mask.nbytes) + 1024

    def predict_rows(self, rows: Sequence[str], conf: Dict[str, str]
                     ) -> List[str]:
        from avenir_tpu.models.bandits import make_bandit_job
        name = conf.get("algorithm", "greedyRandomBandit")
        batch = int(conf.get("batch.size", "1"))
        kw = {}
        if name == "greedyRandomBandit":
            kw = {
                "random_selection_prob":
                    float(conf.get("random.selection.prob", "0.1")),
                "prob_reduction_algorithm":
                    conf.get("prob.reduction.algorithm", "linear"),
                "prob_reduction_constant":
                    float(conf.get("prob.reduction.constant", "1.0")),
                "auer_greedy_constant":
                    float(conf.get("auer.greedy.constant", "1.0")),
                "selection_unique":
                    conf.get("selection.unique", "false").lower()
                    == "true",
            }
        elif name == "softMaxBandit":
            kw = {"temp_constant": float(conf.get("temp.constant", "1.0"))}
        bj = make_bandit_job(name, batch, **kw)
        sel = np.asarray(bj.select(self.data,
                                   int(conf.get("round", "1"))))
        lines: Dict[str, List[str]] = {}
        for parts in self.data.selections_to_rows(
                sel, conf.get("output.decision.count", "false").lower()
                == "true"):
            lines.setdefault(parts[0], []).append(self.delim.join(parts))
        out = []
        for g in rows:
            g = g.strip()
            if g not in lines:
                raise ScoreError(f"unknown bandit group {g!r}")
            out.append("\n".join(lines[g]))
        return out


_SCORERS = {"bayes": _BayesScorer, "discriminant": _DiscriminantScorer,
            "markov": _MarkovScorer, "bandit": _BanditScorer}

#: scorers whose predict needs the window's conf at call time
_CONF_AT_PREDICT = ("discriminant", "bandit")


def model_cache_key(kind: str, model: str, conf: Dict[str, str]) -> tuple:
    """The warm-cache identity of one served model — the
    :func:`avenir_tpu.core.keys.model_tuple` recipe applied to this
    request's view of the artifact. Recomputed per dispatch: the
    digest probe is what turns every retrain/restamp/reward into a
    MISS instead of a stale hit."""
    delim = conf.get("field.delim", ",")
    schema_digest = ""
    if kind == "bayes":
        schema_path = conf.get("schema.path", "")
        if schema_path:
            schema_digest = file_digest(schema_path)
    dims: Tuple = (delim,)
    if kind == "bayes":
        dims = (delim, conf.get("predict.class", ""),
                conf.get("predict.class.cost", ""))
    elif kind == "markov":
        dims = (delim, conf.get("class.labels", ""),
                conf.get("log.odds.threshold", "0"),
                conf.get("skip.field.count", "1"))
    elif kind == "bandit":
        dims = (delim, conf.get("count.ordinal", "2"),
                conf.get("reward.ordinal", "3"),
                reward_journal_digest(model))
    return _keys.model_tuple(kind, model, file_digest(model),
                             schema_digest, stamp_version(model), dims)


def load_scorer(kind: str, model: str, conf: Dict[str, str]):
    """Digest-verified cold load of one family scorer (raises
    :class:`ModelFormatSkew` on foreign/torn stamps)."""
    try:
        cls = _SCORERS[kind]
    except KeyError:
        raise ScoreError(f"unknown score kind {kind!r}")
    return cls(model, conf)


def score_once(kind: str, model: str, row: str,
               conf: Dict[str, str]) -> str:
    """Cold solo score — load, predict one row, drop the model. The
    reference implementation the plane's coalesced path must match
    bit-for-bit; also the audit drivers' serve."""
    scorer = load_scorer(kind, model, conf)
    if kind in _CONF_AT_PREDICT:
        return scorer.predict_rows([row], conf)[0]
    return scorer.predict_rows([row])[0]


# ======================================================================
# warm model cache — exclusive checkout
# ======================================================================

@dataclass
class _ModelEntry:
    key: tuple
    scorer: object
    nbytes: int


class ModelCache:
    """Budget-bounded warm cache of loaded scorers with exclusive
    checkout: ``checkout`` POPS the entry, ``checkin`` re-inserts it
    and runs the LRU budget sweep. An entry a dispatch holds is not in
    the cache at all, so eviction can never unload a model mid-use —
    the WarmStore discipline, which is what keeps the plane safe under
    the race auditor's delete-while-checked-out contract."""

    def __init__(self, budget_bytes: int = DEFAULT_CACHE_BUDGET):
        self.budget_bytes = int(budget_bytes)
        self._entries: "OrderedDict[tuple, _ModelEntry]" = OrderedDict()
        self._lock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def checkout(self, key: tuple) -> Optional[_ModelEntry]:
        with self._lock:
            entry = self._entries.pop(key, None)
            if entry is None:
                self.misses += 1
            else:
                self.hits += 1
            return entry

    def checkin(self, entry: _ModelEntry) -> None:
        with self._lock:
            self._entries[entry.key] = entry
            self._entries.move_to_end(entry.key)
            # LRU sweep; may drop the just-returned entry itself when a
            # single model is over budget — served this window, cold next
            total = sum(e.nbytes for e in self._entries.values())
            while total > self.budget_bytes and self._entries:
                _, victim = self._entries.popitem(last=False)
                total -= victim.nbytes
                self.evictions += 1

    def snapshot(self) -> Dict:
        with self._lock:
            return {"entries": len(self._entries),
                    "nbytes": sum(e.nbytes
                                  for e in self._entries.values()),
                    "budget_bytes": self.budget_bytes,
                    "hits": self.hits, "misses": self.misses,
                    "evictions": self.evictions}


# ======================================================================
# the plane
# ======================================================================

@dataclass
class _Slot:
    request: ScoreRequest
    t0: float
    done: threading.Event = field(default_factory=threading.Event)
    result: Optional[ScoreResult] = None
    error: Optional[BaseException] = None


@dataclass
class _Window:
    gkey: tuple
    opened: float
    slots: List[_Slot] = field(default_factory=list)


class ScorePlane:
    """The online scoring half of the server: a coalescing dispatcher
    in front of the warm model cache (module docstring has the
    design). One non-daemon dispatcher thread owns all predict calls;
    ``close()`` drains and joins it (the joinable-worker contract —
    a wedged dispatcher raises, never leaks)."""

    def __init__(self, budget_bytes: int = DEFAULT_CACHE_BUDGET,
                 window_ms: float = DEFAULT_WINDOW_MS,
                 batch_max: int = DEFAULT_BATCH_MAX):
        self.window_s = max(float(window_ms), 0.0) / 1000.0
        self.batch_max = max(int(batch_max), 1)
        self.cache = ModelCache(budget_bytes)
        self._cv = threading.Condition()
        self._pending: Dict[tuple, _Window] = {}
        self._ready: Deque[_Window] = deque()
        self._closed = False
        self._journal_lock = threading.Lock()
        self._hists: Dict[str, LatencyHistogram] = {}
        self._predicts: Dict[str, int] = {}
        self.stats = {"scores": 0, "rewards": 0, "predict_calls": 0,
                      "window_rows": 0, "model_loads": 0, "errors": 0}
        self._thread = threading.Thread(target=self._run,
                                        name="score-dispatch")
        self._thread.start()

    # ------------------------------------------------------------ public
    def score(self, request: ScoreRequest,
              timeout: float = 30.0) -> ScoreResult:
        """Block until this request's window dispatches; returns the
        demuxed row (bit-identical to a solo predict)."""
        if request.action == "reward":
            raise ScoreError("reward updates go through reward()")
        slot = _Slot(request, time.monotonic())
        gkey = (request.kind, os.path.abspath(request.model),
                tuple(sorted(request.conf.items())))
        with self._cv:
            if self._closed:
                raise ScoreError("score plane is closed")
            w = self._pending.get(gkey)
            if w is None:
                w = _Window(gkey, slot.t0)
                self._pending[gkey] = w
            w.slots.append(slot)
            if len(w.slots) >= self.batch_max:
                del self._pending[gkey]
                self._ready.append(w)
            self._cv.notify_all()
        if not slot.done.wait(timeout):
            slot.error = ScoreTimeout(
                f"score wait exceeded {timeout}s "
                f"(model {request.model})")
        if slot.error is not None:
            raise slot.error
        return slot.result

    def reward(self, request: ScoreRequest) -> Dict:
        """Bandit feedback: append one observation to the artifact's
        journal (row = ``group,item,reward[,count]``). The journal
        digest is a cache-key dim, so the NEXT pull misses the warm
        stats and folds this entry — no in-place invalidation."""
        if request.kind != "bandit":
            raise ScoreError("reward updates are a bandit action")
        delim = request.conf.get("field.delim", ",")
        parts = [t.strip() for t in request.row.split(delim)]
        if len(parts) < 3:
            raise ScoreError("reward row wants group,item,reward[,count]")
        count = int(parts[3]) if len(parts) > 3 else 1
        with self._journal_lock:
            ack = append_reward(request.model, parts[0], parts[1],
                                float(parts[2]), count=count,
                                nonce=request.req_id or None)
        with self._cv:
            self.stats["rewards"] += 1
        return ack

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        self._thread.join(_JOIN_SECS)
        if self._thread.is_alive():
            raise RuntimeError(
                "score dispatcher failed to drain within "
                f"{_JOIN_SECS}s — a predict is wedged")

    # ----------------------------------------------------------- metrics
    def hist_summaries(self) -> Dict[str, Dict]:
        with self._cv:
            return {name: h.summary()
                    for name, h in self._hists.items()}

    def hists_raw(self) -> Dict[str, Dict]:
        with self._cv:
            return {name: h.to_dict()
                    for name, h in self._hists.items()}

    def predict_calls(self, model: str) -> int:
        """Vectorized dispatches for one artifact (coalescing proof)."""
        with self._cv:
            return self._predicts.get(self._model_name(model), 0)

    def snapshot(self) -> Dict:
        with self._cv:
            stats = dict(self.stats)
            predicts = dict(self._predicts)
        return {"stats": stats, "per_model_predicts": predicts,
                "cache": self.cache.snapshot()}

    # ---------------------------------------------------------- internals
    @staticmethod
    def _model_name(model: str) -> str:
        base = os.path.basename(model)
        return os.path.splitext(base)[0].replace(".", "_") or "model"

    def _feed(self, name: str, ms: float) -> None:
        # caller holds self._cv
        h = self._hists.get(name)
        if h is None:
            h = self._hists[name] = LatencyHistogram()
        h.add(ms)

    def _run(self) -> None:
        while True:
            window: Optional[_Window] = None
            with self._cv:
                while window is None:
                    now = time.monotonic()
                    if self._ready:
                        window = self._ready.popleft()
                        break
                    if self._closed and self._pending:
                        # drain: a closing plane dispatches every held
                        # window immediately, no window wait
                        window = self._pending.pop(
                            next(iter(self._pending)))
                        break
                    due = [k for k, w in self._pending.items()
                           if now - w.opened >= self.window_s]
                    if due:
                        window = self._pending.pop(due[0])
                        break
                    if self._closed:
                        return
                    if self._pending:
                        nearest = min(w.opened + self.window_s
                                      for w in self._pending.values())
                        self._cv.wait(max(nearest - now, 0.0002))
                    else:
                        self._cv.wait(0.05)
            if window is not None:
                self._dispatch(window)

    def _dispatch(self, window: _Window) -> None:
        """Serve one window, demuxing ANY failure to its waiters. The
        wrapper is the dispatcher thread's survival guarantee: a bug
        anywhere in the dispatch path must become a per-slot error —
        an escaped exception would kill the sole ``score-dispatch``
        thread, leaving these waiters hung and every later score on
        the plane timing out."""
        try:
            self._dispatch_window(window)
        except BaseException as exc:
            undone = [s for s in window.slots if not s.done.is_set()]
            try:
                with self._cv:
                    self.stats["errors"] += len(undone)
            finally:
                for slot in undone:
                    slot.error = exc
                    slot.done.set()

    def _dispatch_window(self, window: _Window) -> None:
        kind, model, _ = window.gkey
        conf = window.slots[0].request.conf
        rows = [s.request.row for s in window.slots]
        t_start = time.monotonic()
        entry: Optional[_ModelEntry] = None
        results: List[str] = []
        error: Optional[BaseException] = None
        predict_ms = 0.0
        loaded = False
        try:
            key = model_cache_key(kind, model, conf)
            entry = self.cache.checkout(key)
            if entry is None:
                entry = _ModelEntry(key, load_scorer(kind, model, conf),
                                    0)
                entry.nbytes = int(entry.scorer.nbytes)
                loaded = True
            t_pred = time.monotonic()
            if kind in _CONF_AT_PREDICT:
                results = entry.scorer.predict_rows(rows, conf)
            else:
                results = entry.scorer.predict_rows(rows)
            if len(results) != len(window.slots):
                raise ScoreError(
                    f"{kind} predict returned {len(results)} rows for "
                    f"a window of {len(window.slots)} — refusing the "
                    "positional demux (misaligned answers)")
            predict_ms = (time.monotonic() - t_pred) * 1000.0
        except BaseException as exc:   # demuxed to every waiter
            error = exc
            # a scorer that failed to load or predict does not go back
            # warm: the next window re-probes the artifact cold
            entry = None
        finally:
            if entry is not None:
                self.cache.checkin(entry)
        t_done = time.monotonic()
        batch_ms = (t_start - window.opened) * 1000.0
        name = self._model_name(model)
        with self._cv:
            if loaded:
                self.stats["model_loads"] += 1
            if error is None:
                self.stats["predict_calls"] += 1
                self.stats["scores"] += len(window.slots)
                self.stats["window_rows"] += len(window.slots)
                self._predicts[name] = self._predicts.get(name, 0) + 1
                self._feed(f"score_{name}_batch_ms", batch_ms)
                self._feed(f"score_{name}_predict_ms", predict_ms)
            else:
                self.stats["errors"] += len(window.slots)
            for slot in window.slots:
                self._feed(f"score_{name}_queue_ms",
                           (t_start - slot.t0) * 1000.0)
                if error is None:
                    self._feed(f"score_{name}_total_ms",
                               (t_done - slot.t0) * 1000.0)
        for i, slot in enumerate(window.slots):
            if error is not None:
                slot.error = error
            else:
                slot.result = ScoreResult(
                    row=results[i], req_id=slot.request.req_id,
                    kind=kind, model=model,
                    window_rows=len(window.slots),
                    queue_ms=(t_start - slot.t0) * 1000.0,
                    batch_ms=batch_ms, predict_ms=predict_ms,
                    total_ms=(t_done - slot.t0) * 1000.0)
            slot.done.set()
