"""Sharded-scan worker process: claim blocks, fold, commit states.

``python -m avenir_tpu.dist.worker <shard-root> <worker-id>`` — spawned
by :func:`avenir_tpu.dist.driver.run_sharded`, one process per worker.
The loop:

1. **Boot barrier** — write ``ready/w<i>`` once imports and the plan
   load are done, then wait for the coordinator's ``go`` file. The
   measured sharded wall starts at ``go``, so interpreter/jax boot
   (paid once per worker, concurrently) never skews the scan A/B — the
   same protocol the fleet tripwire uses with its warmup requests.
2. **Home blocks** — claim and fold this worker's contiguous home run
   first (disk-sequential reads).
3. **Steal the tail** — when the home run is done, claim from the
   global unclaimed tail: a fast worker absorbs a slow one's
   never-started blocks with zero redundancy.
4. **Mirror stragglers** — when nothing is unclaimed but blocks remain
   uncommitted, consult the straggler detector: this worker's own
   per-block telemetry (``stream.read/parse/fold`` spans →
   :func:`avenir_tpu.tune.signals.extract_signals`) prices a block, and
   a peer's claim older than the policy multiple is folded REDUNDANTLY.
   The block ledger's first-commit-wins keeps the fold-exactly-once
   invariant; the rejected duplicate lands in ``Shard:DedupBlocks``.
5. **Per-k rounds** (miner plans, ``plan.per_k``) — the worker stays
   resident after pass 1, keeps its folded per-block sources (and
   their committed encoded-block caches) alive, and re-enters the SAME
   claim/steal/mirror loop once per candidate length against the
   level-namespaced ledger (``k<k>/b<id>``): the coordinator publishes
   an atomic token-space candidate manifest under
   ``<root>/candidates/``, the worker counts each claimed block's
   candidate supports by REPLAYING its own committed cache segments
   (zero CSV re-parses on the happy path; a stolen block re-folds its
   byte range once, then replays), and commits the per-block count
   vector first-commit-wins — so a block's counts fold into a level's
   merged support exactly once. ``final.json`` releases the worker.

Every block folds through the REAL streamed machinery: the registered
``StreamFoldOps`` factory builds the sink, ``SharedScan`` drives it (one
instrumentation point with the solo/fused/incremental paths), and the
carry crosses processes via the registered ``serialize_state`` — the
same ops the graftlint --merge auditor proves byte-exact every round.
"""

from __future__ import annotations

import io
import json
import os
import sys
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from avenir_tpu import obs as _obs
from avenir_tpu.core.atomic import (publish_bytes, publish_json,
                                    sched_point)
from avenir_tpu.dist.detect import (StragglerPolicy, mirror_after_s,
                                    mirror_after_wall_s)
from avenir_tpu.dist.ledger import BlockLedger
from avenir_tpu.dist.plan import ShardBlock, ShardPlan, load_plan

#: test-only chaos hook (cross-process, so an env var):
#: "worker:block:secs" makes that worker sleep that long after CLAIMING
#: the pass-1 block and before folding it; "worker:level:block:secs"
#: (level = "k2", "tids", ...) holds a per-k count block the same way —
#: deterministic stragglers for the dedup tests; the SIGSTOP chaos leg
#: in bench_scaling.shard_tripwire stays signal-driven
_HOLD_ENV = "AVENIR_SHARD_TEST_HOLD"

#: the fold families whose finish() re-scans their inputs (the miners'
#: per-k passes): run_sharded distributes those passes as per-k count
#: rounds through the level-namespaced ledger (plan.per_k); the merge
#: auditor's in-process path instead restores their per-block states
#: against per-block SLICES of the corpus — see driver._restore_inputs
RESCAN_AT_FINISH = ("frequentItemsApriori", "candidateGenerationWithSelfJoin")


def _sidecar_range_feed(canonical: str, cfg, ops, schema, path: str,
                        start: int, end: int, block_bytes: int):
    """A write=False sidecar feed over one claimed byte range, or None.
    The ranged contract replays ALL of [start, end) from verified
    sidecar blocks or nothing — a worker never writes the shared
    sidecar (N processes racing an append would tear it) and never
    splices replay with cold parse mid-range; when the plan boundaries
    were snapped to sidecar block starts the whole range replays."""
    try:
        from avenir_tpu.native import sidecar as sc

        opts = sc.opts_from_cfg(cfg)
        if ops.kind == "dataset":
            return sc.dataset_blocks(opts, path, schema,
                                     cfg.field_delim_regex, block_bytes,
                                     byte_range=(start, end), write=False)
        return sc.byte_blocks(opts, path, cfg.field_delim_regex,
                              cfg.get_int("skip.field.count", 1),
                              block_bytes, byte_range=(start, end),
                              write=False)
    except Exception:
        return None


def fold_block(canonical: str, cfg, ops, schema, inputs: List[str],
               path: str, start: int, end: int,
               fps_out: Optional[list] = None):
    """Fold ONE plan block — the byte range ``[start, end)`` of
    ``path`` — through the registered fold sink, and return the fed
    fold. Newline-aligned plan blocks make the range self-contained:
    the LineRecordReader contract in the readers degrades to a plain
    slice read. When the whole range re-proves against the columnar
    sidecar, the fold streams replayed payloads instead of parsing the
    CSV (the fold sinks dispatch on payload type). Shared by the worker
    loop and the graftlint --merge sharded-steal leg, so the audited
    fold path IS the production one.

    ``fps_out`` (refresh plans) collects the content fingerprints of
    the EXACT chunks the fold consumed — the sidecar feed's verified
    hashes, or a hash of each raw block as it is read — tiling
    [start, end) gap-free. The coordinator extends the incremental
    checkpoint from these instead of re-reading the file, so a source
    appended to between this fold and the merge can never stamp
    never-folded bytes into the checkpoint."""
    from avenir_tpu.core import incremental as incr
    from avenir_tpu.core.stream import (CsvBlockReader, iter_byte_blocks,
                                        prefetched)
    from avenir_tpu.runner import _drive_fold

    fold = ops.factory(cfg, list(inputs), schema)
    block_bytes = int(cfg.get_float("stream.block.size.mb", 64.0)
                      * (1 << 20))
    feed = None
    if start < end:
        feed = _sidecar_range_feed(canonical, cfg, ops, schema, path,
                                   start, end, block_bytes)
    if feed is not None:
        def _sidecar_chunks():
            for off, length, hsh, payload in feed:
                if fps_out is not None:
                    fps_out.append({"offset": int(off),
                                    "length": int(length), "hash": hsh})
                if payload is not None:
                    yield payload
        chunks = _sidecar_chunks()
    elif fps_out is not None:
        reader = CsvBlockReader(path, schema, cfg.field_delim_regex,
                                block_bytes, byte_range=(start, end)) \
            if ops.kind == "dataset" else None

        def _fingerprinted_chunks():
            for off, data in prefetched(
                    iter_byte_blocks(path, block_bytes,
                                     byte_range=(start, end),
                                     with_offsets=True), depth=1):
                fps_out.append(incr.block_fingerprint(off, data))
                yield reader._parse(data) if reader is not None else data
        chunks = _fingerprinted_chunks()
    elif ops.kind == "dataset":
        chunks = iter(CsvBlockReader(path, schema, cfg.field_delim_regex,
                                     block_bytes, byte_range=(start, end)))
    else:
        chunks = iter_byte_blocks(path, block_bytes,
                                  byte_range=(start, end))
    _drive_fold(fold, chunks, canonical)
    return fold


def _hold(worker: int, block_id: int, level: Optional[str] = None) -> None:
    spec = os.environ.get(_HOLD_ENV, "")
    parts = spec.split(":")
    try:
        if len(parts) == 4:
            w, lvl, b, secs = parts
            if lvl != (level or ""):
                return
        else:
            w, b, secs = parts
            if level is not None:
                return
        if int(w) == worker and int(b) == block_id:
            time.sleep(float(secs))
    except ValueError:
        pass


class _Worker:
    def __init__(self, root: str, worker: int):
        self.root = root
        self.worker = worker
        self.plan: ShardPlan = load_plan(os.path.join(root, "plan.json"))
        self.policy = StragglerPolicy.from_dict(self.plan.policy)
        self.ledger = BlockLedger(root)
        self.per_k = bool(self.plan.per_k)
        self.stats = {"worker": worker, "claimed": 0, "stolen": 0,
                      "mirrored": 0, "dedup_rejected": 0, "folded": 0,
                      "perk_claimed": 0, "perk_stolen": 0,
                      "perk_mirrored": 0, "perk_dedup": 0,
                      "perk_folded": 0, "perk_levels": 0,
                      "scan_s": 0.0, "perk_s": 0.0}
        from avenir_tpu.runner import _job_cfg, stream_fold_ops

        self.canonical, self.prefix, cfg = _job_cfg(self.plan.job,
                                                    dict(self.plan.props))
        self.ops = stream_fold_ops(self.canonical)
        if self.canonical in RESCAN_AT_FINISH and not self.per_k:
            # legacy (non-per-k) sharded miner plans never run per-k
            # passes in the worker — spilling an encoded-block cache
            # per block would be pure waste. Per-k plans NEED the
            # cache: it is what the per-k count rounds replay.
            cfg.props[f"{self.prefix}.stream.encoded.cache"] = "false"
        self.cfg = cfg
        self.schema = None
        if self.ops.kind == "dataset":
            from avenir_tpu.runner import _schema

            self.schema = _schema(cfg)
        self.inputs = self.plan.input_paths()
        # ---- per-k state (miner plans only) ----
        self._folds: Dict[int, object] = {}    # block id -> kept fold
        self._miner = None
        if self.per_k:
            from avenir_tpu.runner import _build_miner

            self._miner = _build_miner(self.canonical, cfg)
        self._perk_wall = 0.0       # measured seconds over per-k blocks
        self._perk_done = 0         # ...the straggler detector's input
        #: the coordinator's pid at boot: per-k workers can only exit
        #: when the coordinator publishes the next manifest, so a
        #: coordinator that dies hard (SIGKILL/OOM — its finally never
        #: runs) must not leave workers polling forever; reparenting
        #: (getppid() change) is the death signal
        self._coord_pid = os.getppid()

    # ------------------------------------------------------- lifecycle
    def barrier(self, timeout_s: float = 300.0) -> None:
        ready = os.path.join(self.root, "ready")
        os.makedirs(ready, exist_ok=True)
        marker = os.path.join(ready, f"w{self.worker}")
        publish_bytes(str(os.getpid()).encode("utf-8"), marker)
        deadline = time.perf_counter() + timeout_s
        go = os.path.join(self.root, "go")
        while not os.path.exists(go):
            if time.perf_counter() > deadline:
                raise RuntimeError(
                    f"worker {self.worker}: no go signal in {timeout_s}s")
            time.sleep(0.01)

    def write_stats(self, signals) -> None:
        self.stats["signals"] = signals.to_json()
        if self.per_k:
            # per-k replay folds only (keys >= 0): the tids slice folds
            # (negative keys) cover the same byte ranges again — summing
            # them would double-count the spill on emit.trans.id runs
            replay = [f for bid, f in self._folds.items() if bid >= 0]
            self.stats["cache_bytes"] = float(sum(
                f.src.cache_nbytes for f in replay))
            self.stats["cache_evicted"] = float(sum(
                f.src.cache_evicted_bytes for f in replay))
        path = os.path.join(self.root, "stats", f"w{self.worker}.json")
        os.makedirs(os.path.dirname(path), exist_ok=True)
        publish_json(self.stats, path)

    # ------------------------------------------------------- fold path
    def _fold_and_commit(self, blk: ShardBlock) -> None:
        src = self.plan.inputs[blk.input]["path"]
        # refresh plans: fingerprint the exact chunks this fold reads so
        # the coordinator extends the checkpoint from folded bytes, not
        # from a post-hoc re-read a concurrent writer may have changed
        fps = [] if self.plan.record_fps else None
        fold = fold_block(self.canonical, self.cfg, self.ops, self.schema,
                          self.inputs, src, blk.start, blk.end,
                          fps_out=fps)
        if self.per_k:
            # seal NOW: commits this block's encoded spill cache, so the
            # per-k rounds replay it instead of re-parsing the CSV. The
            # serialized meta records sealed=True; the coordinator's
            # per-k merge reads only vocab/counts/n from it.
            fold._seal()
        blob = self.ops.serialize_state(fold)
        if self.ledger.commit(blk.id, self.worker, blob, fps=fps):
            self.stats["folded"] += 1
        else:
            self.stats["dedup_rejected"] += 1
        if self.per_k:
            # keep the fold (and its committed cache) for the per-k
            # rounds — even a dedup-rejected redundant fold is a usable
            # per-k replay source for this worker
            self._folds[blk.id] = fold
        else:
            close = getattr(getattr(fold, "src", None), "close", None)
            if close is not None:
                close()

    def _next_unclaimed(self, ledger: BlockLedger
                        ) -> Optional[Tuple[ShardBlock, bool]]:
        """Home blocks first, then the global unclaimed tail (a steal);
        returns (block, stolen) or None. One loop serves pass 1 and
        every per-k level — only the ledger namespace changes."""
        by_id = {b.id: b for b in self.plan.blocks}
        done = set(ledger.committed())
        claims = ledger.claims()
        home = [b.id for b in self.plan.blocks if b.home == self.worker]
        tail = [b.id for b in self.plan.blocks if b.home != self.worker]
        for bid in home + tail:
            if bid in done or bid in claims:
                continue
            if ledger.claim(bid, self.worker):
                blk = by_id[bid]
                return blk, blk.home != self.worker
        return None

    def _stale_peer_block(self, ledger: BlockLedger,
                          threshold: float) -> Optional[int]:
        """Oldest claimed-but-uncommitted peer block past the mirror
        threshold (never this worker's own claim), or None."""
        n_blocks = len(self.plan.blocks)
        stale = ledger.stale_claims(n_blocks, threshold)
        claims = ledger.claims()   # ONE snapshot
        stale = [b for b in stale
                 if (claims.get(b) or {}).get("worker") != self.worker]
        return stale[0] if stale else None

    def run(self) -> None:
        self.barrier()
        by_id = {b.id: b for b in self.plan.blocks}
        t_run = time.perf_counter()
        sc0 = None
        try:
            from avenir_tpu.native import sidecar as _sc

            sc0 = _sc.counters_snapshot()
        except Exception:
            pass
        try:
            with _obs.capture() as rec:
                from avenir_tpu.tune.signals import extract_signals

                while True:
                    nxt = self._next_unclaimed(self.ledger)
                    if nxt is not None:
                        blk, stolen = nxt
                        self.stats["claimed"] += 1
                        if stolen:
                            self.stats["stolen"] += 1
                        _hold(self.worker, blk.id)
                        self._fold_and_commit(blk)
                        continue
                    pending = self.ledger.pending(len(self.plan.blocks))
                    if not pending:
                        break
                    # nothing unclaimed, blocks outstanding: the
                    # straggler detector prices a block from THIS
                    # worker's telemetry and mirrors any claim older
                    # than the policy multiple
                    if self.policy.mirror:
                        signals = extract_signals(rec.spans())
                        threshold = mirror_after_s(self.policy, signals,
                                                   self.stats["folded"])
                        bid = self._stale_peer_block(self.ledger,
                                                     threshold)
                        if bid is not None:
                            self.stats["mirrored"] += 1
                            self._fold_and_commit(by_id[bid])
                            continue
                    time.sleep(self.policy.poll_s)
                self.stats["scan_s"] = round(
                    time.perf_counter() - t_run, 4)
                if self.per_k:
                    self._run_per_k(by_id)
                    self.stats["perk_s"] = round(self._perk_wall, 4)
                # the parse-free-replay proof the coordinator surfaces:
                # this worker's own span record (how many blocks hit the
                # CSV parser vs the sidecar) plus the sidecar counter
                # delta — cross-process, so it rides the stats file
                spans = rec.spans()
                self.stats["parse_spans"] = sum(
                    1 for sp in spans if sp.name == "stream.parse")
                self.stats["replay_spans"] = sum(
                    1 for sp in spans
                    if sp.name == "stream.sidecar.replay")
                if sc0 is not None:
                    try:
                        now = _sc.counters_snapshot()
                        self.stats["sidecar_hit_blocks"] = \
                            now["hit_blocks"] - sc0["hit_blocks"]
                        self.stats["sidecar_delta_blocks"] = \
                            now["delta_blocks"] - sc0["delta_blocks"]
                    except Exception:
                        pass
                self.write_stats(extract_signals(spans))
        finally:
            for fold in self._folds.values():
                fold.src.close()

    # ------------------------------------------------------ per-k path
    def _load_manifest(self, path: str) -> Optional[Dict]:
        sched_point("cand.poll")
        try:
            with open(path) as fh:
                return json.load(fh)
        except (OSError, ValueError):
            return None          # not published yet (writes are atomic)

    def _coordinator_gone(self) -> bool:
        """True when this worker was reparented — the coordinator died
        hard and no further manifest (or final.json) is ever coming."""
        return os.getppid() != self._coord_pid

    def _run_per_k(self, by_id: Dict[int, ShardBlock]) -> None:
        """The per-k rounds: follow the coordinator's candidate
        manifests in publish order (k2, k3, ..., optionally tids),
        claim/steal/mirror count blocks per level through the
        level-namespaced ledger, exit at final.json — or when the
        coordinator itself died (a hard-killed coordinator must not
        orphan workers polling for a manifest nobody will publish)."""
        cand_dir = os.path.join(self.root, "candidates")
        next_k = 2
        tids_done = False
        while True:
            man = self._load_manifest(
                os.path.join(cand_dir, f"k{next_k}.json"))
            if man is not None:
                self._count_level(f"k{next_k}", man, by_id)
                next_k += 1
                continue
            if not tids_done:
                man = self._load_manifest(
                    os.path.join(cand_dir, "tids.json"))
                if man is not None:
                    self._count_level("tids", man, by_id)
                    tids_done = True
                    continue
            if os.path.exists(os.path.join(cand_dir, "final.json")):
                return
            if self._coordinator_gone():
                raise RuntimeError(
                    f"worker {self.worker}: coordinator died mid per-k "
                    f"rounds (no final.json will come)")
            time.sleep(self.policy.poll_s)

    def _count_level(self, tag: str, man: Dict,
                     by_id: Dict[int, ShardBlock]) -> None:
        """One level's claim/steal/mirror loop — the pass-1 discipline
        against the ``ledger/<tag>/`` namespace, with the count fold
        (cache replay) in place of the pass-1 parse fold."""
        cands = [tuple(cd) for cd in man["cands"]]
        c_pad = int(man["c_pad"])
        mask = [str(t) for t in man.get("mask", [])]
        ledger = self.ledger.level(tag)
        n_blocks = len(self.plan.blocks)
        self.stats["perk_levels"] += 1
        while True:
            nxt = self._next_unclaimed(ledger)
            if nxt is not None:
                blk, stolen = nxt
                self.stats["perk_claimed"] += 1
                if stolen:
                    self.stats["perk_stolen"] += 1
                _hold(self.worker, blk.id, tag)
                self._count_and_commit(ledger, tag, blk, cands, c_pad,
                                       mask)
                continue
            if not ledger.pending(n_blocks):
                return
            if self.policy.mirror:
                threshold = mirror_after_wall_s(
                    self.policy, self._perk_wall, self._perk_done)
                bid = self._stale_peer_block(ledger, threshold)
                if bid is not None:
                    self.stats["perk_mirrored"] += 1
                    self._count_and_commit(ledger, tag, by_id[bid],
                                           cands, c_pad, mask)
                    continue
            if self._coordinator_gone():
                raise RuntimeError(
                    f"worker {self.worker}: coordinator died waiting "
                    f"on level {tag} commits")
            time.sleep(self.policy.poll_s)

    def _count_and_commit(self, ledger: BlockLedger, tag: str,
                          blk: ShardBlock, cands, c_pad: int,
                          mask: List[str]) -> None:
        t0 = time.perf_counter()
        if tag == "tids":
            from avenir_tpu.models.association import \
                collect_token_trans_ids

            # the id pass needs per-row ids (not in the cache): a
            # slice-backed source whose python feed sees exactly this
            # block's lines
            src = self._slice_source(blk, mask)
            tids = collect_token_trans_ids(src, cands, c_pad,
                                           self._miner.block)
            blob = json.dumps({"tids": tids}).encode()
        else:
            src = self._block_source(blk, mask)
            counts = self._count_supports(src, cands, c_pad)
            buf = io.BytesIO()
            np.savez(buf, counts=np.asarray(counts, np.int64))
            blob = buf.getvalue()
        self._perk_wall += time.perf_counter() - t0
        self._perk_done += 1
        if ledger.commit(blk.id, self.worker, blob):
            self.stats["perk_folded"] += 1
        else:
            self.stats["perk_dedup"] += 1

    def _count_supports(self, src, cands, c_pad: int) -> np.ndarray:
        if self.canonical == "frequentItemsApriori":
            from avenir_tpu.models.association import count_token_supports
        else:
            from avenir_tpu.models.sequence import count_token_supports
        return count_token_supports(src, cands, c_pad, self._miner.block)

    def _install_mask(self, src, mask: List[str]) -> None:
        """Install the global frequent-token mask once per source (the
        remap is the installed-flag: every level publishes the same
        mask, so re-installation is never needed)."""
        if src._remap is not None:
            return
        keep = [src.index[t] for t in mask if t in src.index]
        if self.canonical == "frequentItemsApriori":
            src.mask_items(keep)
        else:
            src.mask_tokens(keep)

    def _replayable(self, fold) -> bool:
        """True when per-k counts over this fold's source are correct:
        its committed cache can replay this block's rows, or the
        source is slice-backed (its re-parse paths see exactly the
        block's lines — the cache-off / budget-evicted fallback)."""
        if getattr(fold, "_perk_slice", False):
            return True
        cache = fold.src._cache
        return cache is not None and cache.valid

    def _block_source(self, blk: ShardBlock, mask: List[str]):
        """The per-block streaming source a per-k count folds over —
        this worker's kept pass-1 fold when its committed cache can
        replay (the zero-re-parse happy path), else a rebuilt fold
        (a stolen block: one pass-1 re-fold of the byte range, then
        cache replay for every later level)."""
        fold = self._folds.get(blk.id)
        if fold is None or not self._replayable(fold):
            if fold is not None:
                fold.src.close()
            fold = self._rebuild_fold(blk)
            self._folds[blk.id] = fold
        self._install_mask(fold.src, mask)
        return fold.src

    def _rebuild_fold(self, blk: ShardBlock):
        """Pass-1 re-fold of a block this worker never folded (stolen
        per-k work) or whose cache can no longer replay (budget
        eviction). When even the fresh cache cannot serve — the block
        alone exceeds the cache budget — fall back to a slice-file
        source whose re-parse paths see exactly the block's lines:
        correctness over throughput."""
        src_path = self.plan.inputs[blk.input]["path"]
        fold = fold_block(self.canonical, self.cfg, self.ops,
                          self.schema, self.inputs, src_path,
                          blk.start, blk.end)
        fold._seal()
        if self._replayable(fold):
            return fold
        fold.src.close()
        slice_path = self._slice_path(blk)
        fold = fold_block(self.canonical, self.cfg, self.ops,
                          self.schema, [slice_path], slice_path, 0,
                          os.path.getsize(slice_path))
        fold._seal()
        fold._perk_slice = True
        return fold

    def _slice_path(self, blk: ShardBlock) -> str:
        """Materialize (once) this block's bytes as a standalone file —
        legal because plan blocks are newline-aligned."""
        path = os.path.join(self.root, "slices",
                            f"w{self.worker}_b{blk.id}.bin")
        if os.path.exists(path):
            return path
        os.makedirs(os.path.dirname(path), exist_ok=True)
        src = self.plan.inputs[blk.input]["path"]
        with open(src, "rb") as fh:
            fh.seek(blk.start)
            data = fh.read(blk.end - blk.start)
        publish_bytes(data, path)
        return path

    def _slice_source(self, blk: ShardBlock, mask: List[str]):
        """A slice-backed source for the row-bearing passes (the tids
        level): its python feed parses exactly this block's lines, its
        vocabulary comes from a pass-1 fold of the same bytes (so
        token_code agrees with the count folds)."""
        slice_path = self._slice_path(blk)
        fold = fold_block(self.canonical, self.cfg, self.ops,
                          self.schema, [slice_path], slice_path, 0,
                          os.path.getsize(slice_path))
        fold._seal()
        key = -(blk.id + 1)     # kept for closing; never collides with
        old = self._folds.get(key)  # the per-k replay folds keyed >= 0
        if old is not None:
            old.src.close()
        self._folds[key] = fold
        self._install_mask(fold.src, mask)
        return fold.src


def worker_main(argv) -> int:
    root, worker = argv[0], int(argv[1])
    _Worker(root, worker).run()
    return 0


if __name__ == "__main__":
    sys.exit(worker_main(sys.argv[1:]))
