"""Sharded-scan worker process: claim blocks, fold, commit states.

``python -m avenir_tpu.dist.worker <shard-root> <worker-id>`` — spawned
by :func:`avenir_tpu.dist.driver.run_sharded`, one process per worker.
The loop:

1. **Boot barrier** — write ``ready/w<i>`` once imports and the plan
   load are done, then wait for the coordinator's ``go`` file. The
   measured sharded wall starts at ``go``, so interpreter/jax boot
   (paid once per worker, concurrently) never skews the scan A/B — the
   same protocol the fleet tripwire uses with its warmup requests.
2. **Home blocks** — claim and fold this worker's contiguous home run
   first (disk-sequential reads).
3. **Steal the tail** — when the home run is done, claim from the
   global unclaimed tail: a fast worker absorbs a slow one's
   never-started blocks with zero redundancy.
4. **Mirror stragglers** — when nothing is unclaimed but blocks remain
   uncommitted, consult the straggler detector: this worker's own
   per-block telemetry (``stream.read/parse/fold`` spans →
   :func:`avenir_tpu.tune.signals.extract_signals`) prices a block, and
   a peer's claim older than the policy multiple is folded REDUNDANTLY.
   The block ledger's first-commit-wins keeps the fold-exactly-once
   invariant; the rejected duplicate lands in ``Shard:DedupBlocks``.

Every block folds through the REAL streamed machinery: the registered
``StreamFoldOps`` factory builds the sink, ``SharedScan`` drives it (one
instrumentation point with the solo/fused/incremental paths), and the
carry crosses processes via the registered ``serialize_state`` — the
same ops the graftlint --merge auditor proves byte-exact every round.
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import List, Optional

from avenir_tpu import obs as _obs
from avenir_tpu.dist.detect import StragglerPolicy, mirror_after_s
from avenir_tpu.dist.ledger import BlockLedger
from avenir_tpu.dist.plan import ShardBlock, ShardPlan, load_plan

#: test-only chaos hook (cross-process, so an env var): "worker:block:secs"
#: makes that worker sleep that long after CLAIMING the block and before
#: folding it — a deterministic straggler for the dedup tests; the
#: SIGSTOP chaos leg in bench_scaling.shard_tripwire stays signal-driven
_HOLD_ENV = "AVENIR_SHARD_TEST_HOLD"

#: the fold families whose finish() re-scans their inputs (the miners'
#: per-k passes): their per-block states must be restored against a
#: per-block SLICE of the corpus, not the whole file — see
#: driver._restore_inputs
RESCAN_AT_FINISH = ("frequentItemsApriori", "candidateGenerationWithSelfJoin")


def fold_block(canonical: str, cfg, ops, schema, inputs: List[str],
               path: str, start: int, end: int):
    """Fold ONE plan block — the byte range ``[start, end)`` of
    ``path`` — through the registered fold sink, and return the fed
    fold. Newline-aligned plan blocks make the range self-contained:
    the LineRecordReader contract in the readers degrades to a plain
    slice read. Shared by the worker loop and the graftlint --merge
    sharded-steal leg, so the audited fold path IS the production
    one."""
    from avenir_tpu.core.stream import CsvBlockReader, iter_byte_blocks
    from avenir_tpu.runner import _drive_fold

    fold = ops.factory(cfg, list(inputs), schema)
    block_bytes = int(cfg.get_float("stream.block.size.mb", 64.0)
                      * (1 << 20))
    if ops.kind == "dataset":
        chunks = iter(CsvBlockReader(path, schema, cfg.field_delim_regex,
                                     block_bytes, byte_range=(start, end)))
    else:
        chunks = iter_byte_blocks(path, block_bytes,
                                  byte_range=(start, end))
    _drive_fold(fold, chunks, canonical)
    return fold


def _hold(worker: int, block_id: int) -> None:
    spec = os.environ.get(_HOLD_ENV, "")
    try:
        w, b, secs = spec.split(":")
        if int(w) == worker and int(b) == block_id:
            time.sleep(float(secs))
    except ValueError:
        pass


class _Worker:
    def __init__(self, root: str, worker: int):
        self.root = root
        self.worker = worker
        self.plan: ShardPlan = load_plan(os.path.join(root, "plan.json"))
        self.policy = StragglerPolicy.from_dict(self.plan.policy)
        self.ledger = BlockLedger(root)
        self.stats = {"worker": worker, "claimed": 0, "stolen": 0,
                      "mirrored": 0, "dedup_rejected": 0, "folded": 0,
                      "scan_s": 0.0}
        from avenir_tpu.runner import _job_cfg, stream_fold_ops

        self.canonical, self.prefix, cfg = _job_cfg(self.plan.job,
                                                    dict(self.plan.props))
        self.ops = stream_fold_ops(self.canonical)
        if self.canonical in RESCAN_AT_FINISH:
            # per-block folds never run per-k passes here (the
            # coordinator does, over restored states) — spilling an
            # encoded-block cache per block would be pure waste
            cfg.props[f"{self.prefix}.stream.encoded.cache"] = "false"
        self.cfg = cfg
        self.schema = None
        if self.ops.kind == "dataset":
            from avenir_tpu.runner import _schema

            self.schema = _schema(cfg)
        self.inputs = self.plan.input_paths()

    # ------------------------------------------------------- lifecycle
    def barrier(self, timeout_s: float = 300.0) -> None:
        ready = os.path.join(self.root, "ready")
        os.makedirs(ready, exist_ok=True)
        marker = os.path.join(ready, f"w{self.worker}")
        with open(marker + ".tmp", "w") as fh:
            fh.write(str(os.getpid()))
        os.replace(marker + ".tmp", marker)
        deadline = time.perf_counter() + timeout_s
        go = os.path.join(self.root, "go")
        while not os.path.exists(go):
            if time.perf_counter() > deadline:
                raise RuntimeError(
                    f"worker {self.worker}: no go signal in {timeout_s}s")
            time.sleep(0.01)

    def write_stats(self, signals) -> None:
        self.stats["signals"] = signals.to_json()
        path = os.path.join(self.root, "stats", f"w{self.worker}.json")
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = f"{path}.tmp"
        with open(tmp, "w") as fh:
            json.dump(self.stats, fh)
        os.replace(tmp, path)

    # ------------------------------------------------------- fold path
    def _fold_and_commit(self, blk: ShardBlock) -> None:
        src = self.plan.inputs[blk.input]["path"]
        fold = fold_block(self.canonical, self.cfg, self.ops, self.schema,
                          self.inputs, src, blk.start, blk.end)
        blob = self.ops.serialize_state(fold)
        if self.ledger.commit(blk.id, self.worker, blob):
            self.stats["folded"] += 1
        else:
            self.stats["dedup_rejected"] += 1

    def _next_unclaimed(self) -> Optional[ShardBlock]:
        """Home blocks first, then the global unclaimed tail (a
        steal)."""
        by_id = {b.id: b for b in self.plan.blocks}
        done = set(self.ledger.committed())
        claims = self.ledger.claims()
        home = [b.id for b in self.plan.blocks if b.home == self.worker]
        tail = [b.id for b in self.plan.blocks if b.home != self.worker]
        for bid in home + tail:
            if bid in done or bid in claims:
                continue
            if self.ledger.claim(bid, self.worker):
                blk = by_id[bid]
                self.stats["claimed"] += 1
                if blk.home != self.worker:
                    self.stats["stolen"] += 1
                return blk
        return None

    def run(self) -> None:
        self.barrier()
        n_blocks = len(self.plan.blocks)
        by_id = {b.id: b for b in self.plan.blocks}
        t_run = time.perf_counter()
        with _obs.capture() as rec:
            from avenir_tpu.tune.signals import extract_signals

            while True:
                blk = self._next_unclaimed()
                if blk is not None:
                    _hold(self.worker, blk.id)
                    self._fold_and_commit(blk)
                    continue
                pending = self.ledger.pending(n_blocks)
                if not pending:
                    break
                # nothing unclaimed, blocks outstanding: the straggler
                # detector prices a block from THIS worker's telemetry
                # and mirrors any claim older than the policy multiple
                signals = extract_signals(rec.spans())
                if self.policy.mirror:
                    threshold = mirror_after_s(self.policy, signals,
                                               self.stats["folded"])
                    stale = self.ledger.stale_claims(n_blocks, threshold)
                    claims = self.ledger.claims()   # ONE snapshot
                    stale = [b for b in stale
                             if (claims.get(b) or {})
                             .get("worker") != self.worker]
                    if stale:
                        self.stats["mirrored"] += 1
                        self._fold_and_commit(by_id[stale[0]])
                        continue
                time.sleep(self.policy.poll_s)
            self.stats["scan_s"] = round(time.perf_counter() - t_run, 4)
            self.write_stats(extract_signals(rec.spans()))


def worker_main(argv) -> int:
    root, worker = argv[0], int(argv[1])
    _Worker(root, worker).run()
    return 0


if __name__ == "__main__":
    sys.exit(worker_main(sys.argv[1:]))
