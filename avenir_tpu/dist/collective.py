"""Cross-process collective merge — the TPU/GPU path, behind a gate.

The sharded driver's CPU merge path restores every block's serialized
carry in the coordinator and chains the registered ``merge_states`` —
correct everywhere, O(states) host work. On a real multi-process
accelerator mesh the same sum is one collective: each process assembles
its LOCAL merged carry as a flat vector, ``jax.make_array_from_process_
local_data`` builds the globally process-sharded array without any host
materializing the whole thing, and a ``psum`` over the data axis hands
every process the fleet-wide sufficient statistics (the SNIPPETS.md
partitioner template; the per-family payload sizes are the validated
``collective_payload_model`` entries).

The gate exists because jaxlib's CPU backend REFUSES compiled
multiprocess computation ("Multiprocess computations aren't implemented
on the CPU backend" — pinned by tests/test_multihost.py since PR 4), so
this module is built and unit-gated on CPU rounds but EXERCISED only on
TPU/GPU rounds: callers ask :func:`collective_ready` first and fall
back to the serialized-state merge, which produces byte-identical
artifacts by the proven merge algebra.
"""

from __future__ import annotations

from typing import Dict

import numpy as np


class CollectiveUnavailable(RuntimeError):
    """The cross-process collective merge cannot run on this backend
    (CPU multiprocess, or a single-process run with nothing to merge
    across)."""


def collective_ready() -> bool:
    """True only where the psum merge can actually compile: a non-CPU
    backend inside an initialized multi-process ``jax.distributed``
    run. CPU multiprocess is the documented jaxlib refusal; CPU
    single-process has nothing to merge across (the in-process
    ``merge_states`` chain is strictly cheaper than a device
    round-trip)."""
    import jax

    return jax.default_backend() != "cpu" and jax.process_count() > 1


def allsum_carry(arrays: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    """Sum each carry array across every process of the distributed
    run: flatten this process's arrays into ONE local row, assemble the
    (procs, L) process-sharded global array, psum over the data axis,
    and unflatten. Additive carries only (counts/moments — exactly what
    every registered ``state_dict`` stores besides ``meta``); the
    caller merges ``meta`` by its own rules.

    Raises :class:`CollectiveUnavailable` off-gate — callers fall back
    to the serialized-state merge path, never silently compute a
    different answer."""
    if not collective_ready():
        raise CollectiveUnavailable(
            "collective merge needs a multi-process TPU/GPU backend; "
            "CPU rounds merge via StreamFoldOps.merge_states "
            "(jaxlib: multiprocess computations aren't implemented on "
            "the CPU backend)")
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from avenir_tpu.parallel.mesh import DATA_AXIS
    from avenir_tpu.parallel.multihost import global_mesh

    keys = sorted(arrays)
    shapes = {k: np.shape(arrays[k]) for k in keys}
    # one widening AFTER the concat (not per-array in the loop): the
    # carries are exact additive counts/moments, summed in float64 by
    # the same contract every state_dict stores them under
    flat = (np.concatenate([np.ravel(arrays[k]) for k in keys])
            .astype(np.float64) if keys else np.zeros(0, np.float64))
    mesh = global_mesh()
    sharding = NamedSharding(mesh, P(DATA_AXIS))
    world = jax.make_array_from_process_local_data(
        sharding, flat[None, :])

    @jax.jit
    def _sum(x):
        return jnp.sum(x, axis=0)

    total = np.asarray(_sum(world))
    out: Dict[str, np.ndarray] = {}
    off = 0
    for k in keys:
        n = int(np.prod(shapes[k])) if shapes[k] else 1
        out[k] = total[off:off + n].reshape(shapes[k])
        off += n
    return out
