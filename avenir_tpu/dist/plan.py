"""Shard planner: over-partition inputs into newline-aligned blocks.

The multi-process streaming driver (:mod:`avenir_tpu.dist.driver`) does
not hand each worker one fixed split — that is exactly the layout a
single slow worker turns into a tail. Instead every input is cut into
``factor`` × ``procs`` blocks (the over-partitioning "Leveraging Coding
Techniques for Speeding up Distributed Computing", arXiv:1802.03049,
grounds: a finer work unit is what makes redundant tail execution cheap)
and workers CLAIM blocks through the block ledger — home blocks first,
then the unclaimed tail of slower workers.

Blocks are **newline-aligned**: each boundary is advanced to just past
the next ``\\n`` at or after its nominal ceil-division position
(``core.stream.split_byte_ranges``), so a block's byte range contains
exactly whole lines, the ranges tile ``[0, size)`` gap-free, and a
block's bytes can be sliced verbatim out of the input (the driver
materializes such slices for the miners' per-k re-parse). A corpus
whose last line has no trailing newline, a corpus smaller than the
block count (trailing empty blocks), and a single-line corpus are all
legal plans — the same edge set the split arithmetic is
regression-tested on.

The plan is written as ONE atomic JSON manifest (tmp+rename, the spool
discipline) that workers — separate processes with no other channel —
load to learn the job, its config, the block table and the straggler
policy. The manifest is the unit of auditability: ``plan.json`` under
the shard root says exactly which byte range every block id means, and
the ledger next to it says who folded it.
"""

from __future__ import annotations

import bisect
import json
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from avenir_tpu.core.atomic import publish_json
from avenir_tpu.core.stream import split_byte_ranges

#: default over-partitioning: blocks per worker. 4x keeps the steal/
#: mirror unit at ~25% of a worker's share — fine enough that a dead
#: worker strands little, coarse enough that per-block fold + serialize
#: overhead stays amortized.
DEFAULT_FACTOR = 4

#: how far past a nominal boundary the aligner will scan for a newline
#: before giving up and taking EOF — a single line longer than this is
#: pathological for a line-oriented corpus (64MB, one default block)
_ALIGN_SCAN_BYTES = 64 << 20


class PlanError(ValueError):
    """A shard plan that cannot be built or loaded."""


@dataclass(frozen=True)
class ShardBlock:
    """One claimable unit of work: a newline-aligned byte range of one
    input file, with a deterministic ``home`` worker (the worker that
    folds it when nobody is slow; any worker may steal it from the
    unclaimed tail)."""

    id: int
    input: int          # index into ShardPlan.inputs
    start: int
    end: int
    home: int

    def to_dict(self) -> Dict:
        return {"id": self.id, "input": self.input, "start": self.start,
                "end": self.end, "home": self.home}

    @classmethod
    def from_dict(cls, obj: Dict) -> "ShardBlock":
        return cls(id=int(obj["id"]), input=int(obj["input"]),
                   start=int(obj["start"]), end=int(obj["end"]),
                   home=int(obj["home"]))


@dataclass
class ShardPlan:
    """The atomic plan manifest: inputs (path + size, so a worker can
    detect a corpus that changed under the plan), the job and its
    prefixed properties, the block table, and the straggler policy
    knobs. ``blocks`` is in PLAN ORDER — the order the coordinator
    merges committed block states in, which is what makes the sharded
    artifact byte-identical to the solo scan under the proven merge
    algebra."""

    procs: int
    factor: int
    job: str = ""
    prefix: str = ""
    props: Dict[str, str] = field(default_factory=dict)
    inputs: List[Dict] = field(default_factory=list)
    blocks: List[ShardBlock] = field(default_factory=list)
    policy: Dict[str, float] = field(default_factory=dict)
    #: miner jobs: workers stay resident after pass 1 and re-enter the
    #: per-k candidate-counting loop against the level-namespaced
    #: ledger (driver publishes candidate manifests, workers count)
    per_k: bool = False
    #: refresh plans: workers fingerprint the exact chunks each block
    #: fold consumes and commit them with the block state, so the
    #: coordinator extends the incremental checkpoint from folded
    #: bytes instead of re-reading files a concurrent writer may have
    #: changed since the fold
    record_fps: bool = False

    def input_paths(self) -> List[str]:
        return [str(i["path"]) for i in self.inputs]

    def blocks_for(self, worker: int) -> List[ShardBlock]:
        return [b for b in self.blocks if b.home == worker]

    def to_dict(self) -> Dict:
        return {"procs": self.procs, "factor": self.factor,
                "job": self.job, "prefix": self.prefix,
                "props": dict(self.props),
                "inputs": [dict(i) for i in self.inputs],
                "blocks": [b.to_dict() for b in self.blocks],
                "policy": dict(self.policy),
                "per_k": bool(self.per_k),
                "record_fps": bool(self.record_fps)}

    @classmethod
    def from_dict(cls, obj: Dict) -> "ShardPlan":
        return cls(procs=int(obj["procs"]), factor=int(obj["factor"]),
                   job=str(obj.get("job", "")),
                   prefix=str(obj.get("prefix", "")),
                   props=dict(obj.get("props", {})),
                   inputs=[dict(i) for i in obj.get("inputs", [])],
                   blocks=[ShardBlock.from_dict(b)
                           for b in obj.get("blocks", [])],
                   policy=dict(obj.get("policy", {})),
                   per_k=bool(obj.get("per_k", False)),
                   record_fps=bool(obj.get("record_fps", False)))


def _snap_cut(b: int, lo: int, size: int,
              snap: Sequence[int]) -> Optional[int]:
    """The snap offset nearest a nominal boundary ``b`` that still cuts
    strictly inside ``(lo, size)``, or None when the sorted snap list
    has none. Snap offsets are sidecar block starts — themselves
    newline-aligned — so a snapped cut needs no newline scan, and a
    fully-snapped plan's block ranges tile the sidecar's own block
    layout exactly (what lets a worker replay its claimed range)."""
    i = bisect.bisect_left(snap, b)
    best = None
    for j in (i - 1, i):
        if 0 <= j < len(snap) and lo < snap[j] < size:
            if best is None or abs(snap[j] - b) < abs(best - b):
                best = snap[j]
    return best


def _align_boundaries(path: str, size: int, n: int, start: int = 0,
                      snap: Optional[Sequence[int]] = None
                      ) -> List[Tuple[int, int]]:
    """Newline-aligned [lo, hi) ranges tiling ``[start, size)``: nominal
    ceil-division bounds, each interior boundary advanced to one past
    the next ``\\n`` at or after it — or, when a sorted ``snap`` offset
    list is given (verified sidecar block starts), moved to the nearest
    snap offset instead. Boundaries that run out of newlines collapse
    onto ``size`` — trailing empty ranges tile gap-free, exactly like
    ``split_byte_ranges`` on a corpus smaller than the split count."""
    nominal = split_byte_ranges(size - start, n)
    cuts = [start]
    with open(path, "rb") as fh:
        for _lo, hi in nominal[:-1]:
            b = max(start + hi, cuts[-1])
            if b >= size:
                cuts.append(size)
                continue
            if snap:
                snapped = _snap_cut(b, cuts[-1], size, snap)
                if snapped is not None:
                    cuts.append(snapped)
                    continue
            fh.seek(b)
            scanned = 0
            nl = -1
            while scanned < _ALIGN_SCAN_BYTES:
                buf = fh.read(min(1 << 16, _ALIGN_SCAN_BYTES - scanned))
                if not buf:
                    break
                nl = buf.find(b"\n")
                if nl >= 0:
                    nl = b + scanned + nl
                    break
                scanned += len(buf)
                nl = -1
            cuts.append(size if nl < 0 else min(nl + 1, size))
    cuts.append(size)
    return list(zip(cuts[:-1], cuts[1:]))


def plan_shards(inputs: Sequence[str], procs: int,
                factor: int = DEFAULT_FACTOR,
                policy: Optional[Dict[str, float]] = None,
                starts: Optional[Sequence[int]] = None,
                snap: Optional[Sequence[Optional[Sequence[int]]]] = None
                ) -> ShardPlan:
    """Build the over-partitioned plan: every input cut into
    ``procs * factor`` newline-aligned blocks, block ids global in
    (input, offset) order, homes assigned as CONTIGUOUS runs per input
    (worker w's home blocks are one disk-sequential stretch; the steal
    path is what breaks contiguity, and only when someone is slow).

    ``starts[i]`` plans input ``i`` from that byte offset instead of 0
    (the sharded-refresh delta tail; must sit on a line boundary — the
    incremental verified-prefix contract already guarantees it).
    ``snap[i]`` is a sorted list of preferred cut offsets for input
    ``i`` (verified sidecar block starts) — boundaries move to the
    nearest snap offset so every plan block is a whole run of sidecar
    blocks and a worker's claimed range replays parse-free."""
    if procs < 1:
        raise PlanError(f"procs must be positive, got {procs}")
    if factor < 1:
        raise PlanError(f"factor must be positive, got {factor}")
    if not inputs:
        raise PlanError("shard plan needs at least one input")
    if starts is not None and len(starts) != len(inputs):
        raise PlanError("starts must align with inputs")
    if snap is not None and len(snap) != len(inputs):
        raise PlanError("snap must align with inputs")
    plan = ShardPlan(procs=procs, factor=factor,
                     policy=dict(policy or {}))
    bid = 0
    for ii, path in enumerate(inputs):
        if not os.path.exists(path):
            raise PlanError(f"no such input file: {path!r}")
        size = os.path.getsize(path)
        start = int(starts[ii]) if starts is not None else 0
        if not 0 <= start <= size:
            raise PlanError(
                f"start {start} outside [0, {size}] for {path!r}")
        plan.inputs.append({"path": os.path.abspath(path), "size": size})
        n = procs * factor
        ranges = _align_boundaries(
            path, size, n, start=start,
            snap=sorted(snap[ii]) if snap is not None and snap[ii]
            else None)
        for j, (lo, hi) in enumerate(ranges):
            # contiguous home runs: blocks [w*factor, (w+1)*factor) of
            # this input belong to worker w
            plan.blocks.append(ShardBlock(
                id=bid, input=ii, start=lo, end=hi, home=j // factor))
            bid += 1
    return plan


def write_json_atomic(obj: Dict, path: str) -> str:
    """Atomically publish one JSON manifest (unique sibling tmp +
    rename, the core.atomic discipline): a reader either sees no
    manifest or a complete one, never a torn table. Shared by the plan
    manifest and the per-k candidate manifests the sharded mining
    driver publishes under ``<root>/candidates/``. A registered commit
    site — graftlint --proto kill-injects both sides of the rename."""
    return publish_json(obj, path, site="plan.manifest", indent=1)


def write_plan(plan: ShardPlan, path: str) -> str:
    """Atomically publish the plan manifest — see write_json_atomic."""
    return write_json_atomic(plan.to_dict(), path)


def load_plan(path: str) -> ShardPlan:
    try:
        with open(path) as fh:
            return ShardPlan.from_dict(json.load(fh))
    except (OSError, ValueError, KeyError) as e:
        raise PlanError(f"cannot load shard plan {path!r}: {e}") from e
