"""Sharded streaming driver: N worker processes, one merged artifact.

:func:`run_sharded` is the multi-process sibling of ``run_job`` /
``run_shared`` / ``run_incremental``: same registered jobs, same conf
surface, same artifact contract — byte-identical output to the solo
runner — but the STREAMING pass runs across ``procs`` worker processes
on this host. The machinery:

1. The shard planner over-partitions every input into newline-aligned
   byte-range blocks (``factor`` × ``procs``) and publishes the atomic
   plan manifest.
2. Workers (:mod:`avenir_tpu.dist.worker`) claim blocks through the
   block ledger — home run first, then stealing the unclaimed tail —
   fold each block through the registered ``StreamFoldOps`` sink, and
   commit the serialized carry first-commit-wins. Stragglers' in-flight
   blocks are redundantly re-dispatched past the telemetry-derived
   threshold; the ledger dedups, because every fold family is
   NON-idempotent (the merge auditor's overlap probe) and a block must
   fold into the final state exactly once.
3. The coordinator restores every committed block state with the
   registered ``restore_state``, merges them IN PLAN ORDER with the
   registered ``merge_states`` (the algebra graftlint --merge proves
   byte-exact for merge chains every round), and finishes the fold once
   — CPU path. The cross-process collective merge
   (``jax.make_array_from_process_local_data`` + psum) lives behind the
   backend gate in :mod:`avenir_tpu.dist.collective` and is exercised
   on TPU/GPU rounds only: jaxlib's CPU backend refuses compiled
   multiprocess computation (tests/test_multihost.py pins the
   limitation).

**Miner jobs run their per-k candidate rounds distributed too**
(``plan.per_k``): after the pass-1 merge the coordinator does ZERO
candidate counting itself. It thresholds the merged k=1 supports,
publishes each level's candidates as an atomic token-space manifest
(``<root>/candidates/k<k>.json`` — candidates translate per block via
``token_code``), and the resident workers re-enter the claim/steal/
mirror loop against the level-namespaced ledger (``k<k>/b<id>``),
counting each claimed block's candidate supports by replaying their
own committed encoded-block cache segments (no CSV re-parse on the
happy path). The coordinator merges each level's per-block count
vectors through ``merge_support_counts`` — the same reducer algebra
``mine_stream_merged`` uses, driven through the miner's OWN
``_merged_rounds`` control loop, so the kept sets and counts are
identical to the in-process sharded miner by construction — prunes,
publishes k+1, and releases the workers with ``final.json`` when the
frontier empties.

Every sharded JobResult carries the shard counters next to the standard
streamed set: ``Shard:Blocks`` (plan blocks), ``Shard:StolenBlocks``
(claims outside the claimant's home run, across every ledger
namespace), ``Shard:DedupBlocks`` (rejected duplicate commits across
every namespace — redundancy that actually fired), ``Shard:MergeMs``
(restore+merge wall), and — miner jobs — ``Shard:PerKRounds`` (the
distributed candidate-counting levels) and ``Shard:PerKBlocks`` (the
per-level block commits merged).
"""

from __future__ import annotations

import io
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from avenir_tpu import obs as _obs
from avenir_tpu.core.atomic import publish_bytes, sched_point
from avenir_tpu.dist.detect import StragglerPolicy
from avenir_tpu.dist.ledger import BlockLedger
from avenir_tpu.dist.plan import (DEFAULT_FACTOR, ShardPlan, plan_shards,
                                  write_json_atomic, write_plan)
from avenir_tpu.dist.worker import RESCAN_AT_FINISH


class ShardError(RuntimeError):
    """A sharded run that lost workers or blocks."""


def _pkg_parent() -> str:
    import avenir_tpu

    return os.path.dirname(os.path.dirname(os.path.abspath(
        avenir_tpu.__file__)))


def _worker_env() -> Dict[str, str]:
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (_pkg_parent(), env.get("PYTHONPATH")) if p)
    return env


def _sidecar_snap(canonical: str, cfg, ops,
                  inputs: Sequence[str], procs: int, factor: int,
                  schema=None) -> Optional[List[Optional[List[int]]]]:
    """Per-input sidecar block-start offsets for the shard planner to
    snap its cuts to — only for inputs whose VERIFIED sidecar coverage
    is at least as fine as the plan (>= procs*factor blocks; a coarser
    sidecar would collapse plan blocks together and starve workers).
    None when no input qualifies: the planner keeps its newline scan
    and the workers fold cold, exactly the pre-sidecar behavior."""
    try:
        from avenir_tpu.native import sidecar as sc

        opts = sc.opts_from_cfg(cfg)
        if opts is None:
            return None
        block_bytes = int(cfg.get_float("stream.block.size.mb", 64.0)
                          * (1 << 20))
        delim = cfg.field_delim_regex
        if ops.kind == "dataset" and schema is None:
            from avenir_tpu.runner import _schema

            schema = _schema(cfg)
        snap: List[Optional[List[int]]] = []
        for path in inputs:
            if ops.kind == "dataset":
                dirpath = sc.dataset_dir(opts, path, schema, delim,
                                         block_bytes)
            else:
                dirpath = sc.bytes_dir(
                    opts, path, delim,
                    cfg.get_int("skip.field.count", 1), block_bytes)
            offs = sc.verified_offsets(dirpath, path, block_bytes)
            snap.append(offs if len(offs) >= procs * factor else None)
        return snap if any(s is not None for s in snap) else None
    except Exception:
        return None


def _restore_inputs(canonical: str, plan: ShardPlan, block,
                    inputs: Sequence[str], workdir: str) -> List[str]:
    """The input list a restored block state folds/finishes against.
    The miners' ``finish()`` re-scans its inputs per itemset length, so
    each of their block states must see exactly ITS block's lines — a
    byte slice of the input, legal because plan blocks are
    newline-aligned. Every other family's finish never re-reads inputs,
    so the real input list (better error messages, zero extra disk)
    is kept. (run_sharded's own miner path distributes the per-k
    rounds instead and never takes this slice; the graftlint --merge
    sharded-steal leg's in-process merge still does.)"""
    if canonical not in RESCAN_AT_FINISH:
        return list(inputs)
    src = plan.inputs[block.input]["path"]
    slice_path = os.path.join(workdir, f"slice_b{block.id}.bin")
    if not os.path.exists(slice_path):
        with open(src, "rb") as fh:
            fh.seek(block.start)
            data = fh.read(block.end - block.start)
        publish_bytes(data, slice_path)
    return [slice_path]


def merge_block_states(canonical: str, cfg, ops, plan: ShardPlan,
                       states: Dict[int, bytes], inputs: Sequence[str],
                       workdir: str, schema=None):
    """Restore every committed block state and merge IN PLAN ORDER —
    the coordinator's half of the dedup contract (exactly one state per
    block id ever reaches this table) and the merge-algebra chain the
    auditor proves byte-exact. Returns the merged fold, ready for
    ``finish()``. Shared with the graftlint --merge sharded-steal leg."""
    merged = None
    for blk in plan.blocks:
        if blk.id not in states:
            raise ShardError(f"block {blk.id} has no committed state")
        rins = _restore_inputs(canonical, plan, blk, inputs, workdir)
        fold = ops.restore_state(cfg, rins, states[blk.id], schema=schema)
        merged = fold if merged is None else ops.merge_states(merged, fold)
    if merged is None:
        raise ShardError("shard plan has no blocks")
    return merged


# ----------------------------------------------------------- per-k rounds
def _miner_scan_state(blob: bytes):
    """(vocab, k=1 counts, row count) out of one committed pass-1 miner
    block state — the npz ``serialize_state`` wrote; the per-k merge
    needs only the discovery triple, never a rebuilt fold."""
    with np.load(io.BytesIO(blob), allow_pickle=False) as z:
        meta = json.loads(str(z["meta"]))
        counts = np.asarray(z["counts"], np.int64)
    return list(meta["vocab"]), counts, int(meta["n"])


def _level_counts(blob: bytes) -> np.ndarray:
    with np.load(io.BytesIO(blob), allow_pickle=False) as z:
        return np.asarray(z["counts"], np.int64)


def _level_tids(blob: bytes) -> List[List[str]]:
    return json.loads(blob.decode("utf-8"))["tids"]


def publish_candidates(cand_dir: str, name: str, man: dict) -> str:
    """Publish one per-k candidates manifest (``k<k>.json`` / ``tids
    .json`` / ``final.json``) into `cand_dir` — the coordinator's side
    of the manifest-vs-worker-poll seam the race auditor steps."""
    path = os.path.join(cand_dir, f"{name}.json")
    sched_point("cand.publish")
    write_json_atomic(man, path)
    return path


def _wait_commits(ledger: BlockLedger, n_blocks: int, workers, logs: str,
                  deadline: float, poll_s: float) -> None:
    """Wait until every block id is committed in ``ledger``'s
    namespace; raise when every worker died or the deadline passed."""
    while True:
        done = len(ledger.committed())
        if done >= n_blocks:
            return
        if not any(p.poll() is None for _log, p in workers):
            _raise_workers_dead(workers, logs, done, n_blocks)
        if time.perf_counter() > deadline:
            raise ShardError(
                f"sharded scan incomplete at run deadline "
                f"({done}/{n_blocks} blocks committed in namespace "
                f"{ledger.ns or 'pass-1'})")
        time.sleep(poll_s)


def _coordinate_per_k(canonical: str, cfg, plan: ShardPlan,
                      ledger: BlockLedger, root: str, workers,
                      logs: str, deadline: float,
                      policy: StragglerPolicy) -> Dict:
    """The miners' distributed per-k rounds, coordinator half: merge
    the committed pass-1 block states into the global k=1 supports,
    then drive the miner's OWN ``_merged_rounds`` control loop with a
    count function that publishes each level's candidate manifest,
    waits for every block's first-committed count vector in the
    level-namespaced ledger, and merges them via
    ``merge_support_counts``. Zero coordinator-side candidate
    counting; the counts — and therefore the kept sets — are the
    in-process ``mine_stream_merged``'s by construction."""
    from avenir_tpu.models.association import (frequent_tokens,
                                               merge_support_counts)
    from avenir_tpu.runner import _build_miner

    t_perk = t0 = time.perf_counter()
    blocks_meta = []
    committed = set(ledger.committed())
    for blk in plan.blocks:
        if blk.id not in committed:
            raise ShardError(
                f"block {blk.id} has no committed pass-1 state")
        blocks_meta.append(_miner_scan_state(ledger.load_state(blk.id)))
    n = sum(nb for _v, _c, nb in blocks_meta)
    support1 = merge_support_counts(
        *[{vocab[i]: int(counts[i]) for i in range(len(vocab))}
          for vocab, counts, _nb in blocks_meta])
    miner = _build_miner(canonical, cfg)
    # the mask every per-block source installs before counting — the
    # global frequent-token frontier, same rule mine_stream_merged
    # masks its shard sources with
    mask = frequent_tokens(support1, miner.support_threshold * n)
    stats = {"rounds": 0, "blocks": 0, "tags": [],
             "merge_s": time.perf_counter() - t0}

    cand_dir = os.path.join(root, "candidates")
    os.makedirs(cand_dir, exist_ok=True)
    n_blocks = len(plan.blocks)

    def run_level(tag: str, cands, c_pad: int, parse_state):
        lk = ledger.level(tag)
        publish_candidates(
            cand_dir, tag,
            {"tag": tag, "job": canonical, "mask": mask,
             "cands": [list(cd) for cd in cands], "c_pad": int(c_pad)})
        _wait_commits(lk, n_blocks, workers, logs, deadline,
                      policy.poll_s)
        t1 = time.perf_counter()
        payloads = [parse_state(lk.load_state(bid))
                    for bid in range(n_blocks)]
        stats["merge_s"] += time.perf_counter() - t1
        stats["blocks"] += n_blocks
        stats["tags"].append(tag)
        return payloads

    def count_level(k: int, cands, c_pad: int) -> np.ndarray:
        payloads = run_level(f"k{k}", cands, c_pad, _level_counts)
        t1 = time.perf_counter()
        merged = merge_support_counts(
            *[dict(zip(cands, p)) for p in payloads])
        out = np.array([int(merged.get(cd, 0)) for cd in cands],
                       np.int64)
        stats["merge_s"] += time.perf_counter() - t1
        stats["rounds"] += 1
        return out

    if canonical == "frequentItemsApriori":
        rounds = miner._merged_rounds(support1, n, count_level)
        tids = None
        if miner.emit_trans_id:
            all_sets = [cd for _k, sets_k, _c in rounds
                        for cd in sets_k]
            tids = [[] for _ in all_sets]
            if all_sets:
                c_pad = max(64, 1 << (len(all_sets) - 1).bit_length())
                payloads = run_level("tids", all_sets, c_pad,
                                     _level_tids)
                for p in payloads:    # plan order == corpus order
                    for ci in range(len(all_sets)):
                        tids[ci].extend(p[ci])
        levels = miner._pack_merged_rounds(rounds, n, tids)
    else:
        levels = miner._merged_rounds(support1, n, count_level)
    # release the workers: no further manifests are coming
    publish_candidates(cand_dir, "final",
                       {"done": True, "rounds": stats["rounds"]})
    return {"levels": levels, "n": n, "rounds": stats["rounds"],
            "blocks": stats["blocks"], "tags": stats["tags"],
            "merge_s": stats["merge_s"],
            "perk_s": time.perf_counter() - t_perk}


def run_sharded(name: str, conf, inputs: Sequence[str], output: str,
                procs: int = 2, factor: int = DEFAULT_FACTOR,
                shard_root: Optional[str] = None,
                policy: Optional[StragglerPolicy] = None,
                pin_cores: Optional[Sequence[int]] = None,
                worker_hook: Optional[Callable] = None,
                timeout_s: float = 7200.0) -> "JobResult":
    """Run one registered streamed job across ``procs`` worker
    processes — byte-identical artifact to ``run_job``, wall clock
    scaled by the host's process parallelism (miner jobs: BOTH the
    pass-1 scan and every per-k candidate round run distributed).

    ``worker_hook(pids, root)`` is the chaos/test tap, called once the
    workers are spawned (before the go barrier releases them) — the
    SIGSTOP chaos leg arms its watcher here. ``pin_cores`` pins worker
    i to core ``pin_cores[i % len]`` (the fleet convention: one core
    per worker makes a same-box N-vs-1 comparison measure scale-out,
    not XLA's intra-op oversubscription)."""
    from avenir_tpu.runner import (JobResult, _finish_fold, _job_cfg,
                                   finish_miner_levels, stream_fold_ops)

    canonical, prefix, cfg = _job_cfg(name, conf)
    ops = stream_fold_ops(canonical)
    policy = policy or StragglerPolicy()
    root = shard_root or tempfile.mkdtemp(prefix="avenir_shard_")
    own_root = shard_root is None
    procs = max(int(procs), 1)
    per_k = canonical in RESCAN_AT_FINISH
    try:
        plan = plan_shards(list(inputs), procs, factor,
                           policy=policy.to_dict(),
                           snap=_sidecar_snap(canonical, cfg, ops,
                                              list(inputs), procs,
                                              factor))
        plan.job = canonical
        plan.prefix = prefix
        plan.props = {k: str(v) for k, v in cfg.props.items()
                      if k != "__job_name__"}
        plan.per_k = per_k
        write_plan(plan, os.path.join(root, "plan.json"))
        ledger = BlockLedger(root)
        logs = os.path.join(root, "logs")
        os.makedirs(logs, exist_ok=True)

        workers = []
        for w in range(procs):
            preexec = None
            if pin_cores and hasattr(os, "sched_setaffinity"):
                core = pin_cores[w % len(pin_cores)]
                preexec = (lambda c=core: os.sched_setaffinity(0, {c}))
            log = open(os.path.join(logs, f"w{w}.log"), "ab")
            workers.append((log, subprocess.Popen(
                [sys.executable, "-m", "avenir_tpu.dist.worker",
                 root, str(w)],
                stdout=log, stderr=log, env=_worker_env(),
                cwd=_pkg_parent(), preexec_fn=preexec)))
        mined = None
        try:
            if worker_hook is not None:
                worker_hook([p.pid for _log, p in workers], root)
            # boot barrier: the measured scan starts when every worker
            # has finished its (concurrent) interpreter+jax boot — the
            # solo arm's convention too (its child times run_job, not
            # imports), so the A/B compares scans, not boots
            deadline = time.perf_counter() + timeout_s
            ready = os.path.join(root, "ready")
            while True:
                try:
                    n_ready = len(os.listdir(ready))
                except OSError:
                    n_ready = 0
                if n_ready >= procs:
                    break
                _reap_check(workers, ledger, plan, logs)
                if time.perf_counter() > deadline:
                    raise ShardError(
                        f"{n_ready}/{procs} workers ready within "
                        f"{timeout_s}s")
                time.sleep(0.01)
            t_scan = time.perf_counter()
            publish_bytes(b"go", os.path.join(root, "go"))

            n_blocks = len(plan.blocks)
            if per_k:
                # pass 1: wait for every block's committed state — the
                # workers stay resident for the per-k rounds
                _wait_commits(ledger, n_blocks, workers, logs,
                              deadline, policy.poll_s)
                mined = _coordinate_per_k(canonical, cfg, plan, ledger,
                                          root, workers, logs, deadline,
                                          policy)
            # once the scan is complete (pass 1 for single-pass
            # families; final.json published for miners), straggling
            # workers get a BOUNDED grace to exit on their own — long
            # enough for a woken straggler to finish its in-flight fold
            # and record the rejected duplicate in the dedup counters,
            # short enough that a permanently wedged worker (the
            # mirroring exists to survive) cannot hold a finished scan
            # hostage for the run timeout; past it the finally kills
            # the stragglers and the merge proceeds
            grace_until = None
            while True:
                alive = [p for _log, p in workers if p.poll() is None]
                done = len(ledger.committed())
                if per_k or done >= n_blocks:
                    if not alive:
                        break
                    if grace_until is None:
                        grace_until = time.perf_counter() \
                            + policy.exit_grace_s
                    elif time.perf_counter() > grace_until:
                        break
                elif not alive:
                    _raise_workers_dead(workers, logs, done, n_blocks)
                if time.perf_counter() > deadline:
                    raise ShardError(
                        f"sharded scan incomplete after {timeout_s}s "
                        f"({done}/{n_blocks} blocks committed)")
                time.sleep(0.02)
        finally:
            for log, proc in workers:
                if proc.poll() is None:
                    proc.kill()
                    proc.wait()
                log.close()

        # ------------------------------------------------------- merge
        stats = _worker_stats(root, procs)
        if per_k:
            # the levels are already merged (per-k rounds); only the
            # artifact write remains — zero coordinator-side counting
            merge_ms = mined["merge_s"] * 1e3
            t0 = _obs.now()
            res = finish_miner_levels(
                canonical, cfg, mined["levels"], mined["n"],
                time.perf_counter() - t_scan, output,
                extra_counters={
                    "Cache:SpillBytes": float(sum(
                        s.get("cache_bytes", 0) for s in stats)),
                    "Cache:EvictedBytes": float(sum(
                        s.get("cache_evicted", 0) for s in stats))})
            _obs.record("job.dispatch", t0, mode="sharded",
                        procs=procs, blocks=n_blocks,
                        perk_rounds=mined["rounds"], jobs=canonical)
        else:
            t_merge = time.perf_counter()
            states = {bid: ledger.load_state(bid)
                      for bid in ledger.committed()}
            schema = None
            if ops.kind == "dataset":
                from avenir_tpu.runner import _schema

                schema = _schema(cfg)
            merged = merge_block_states(canonical, cfg, ops, plan,
                                        states, list(inputs), root,
                                        schema=schema)
            merge_ms = (time.perf_counter() - t_merge) * 1e3
            if output:
                parent = os.path.dirname(os.path.abspath(output))
                os.makedirs(parent, exist_ok=True)
            t0 = _obs.now()
            res = _finish_fold(merged, output, canonical)
            _obs.record("job.dispatch", t0, mode="sharded", procs=procs,
                        blocks=n_blocks, jobs=canonical)

        by_id = {b.id: b for b in plan.blocks}
        ledgers = [ledger] + [ledger.level(tag)
                              for tag in (mined["tags"] if mined else ())]
        stolen = dups = 0
        for led in ledgers:
            dups += led.dup_count()
            stolen += sum(1 for bid, info in led.claims().items()
                          if bid in by_id
                          and by_id[bid].home != info["worker"])
        res.counters["Shard:Blocks"] = float(n_blocks)
        res.counters["Shard:StolenBlocks"] = float(stolen)
        res.counters["Shard:DedupBlocks"] = float(dups)
        res.counters["Shard:MergeMs"] = round(merge_ms, 3)
        res.counters["Shard:ScanSeconds"] = round(
            time.perf_counter() - t_scan, 4)
        res.counters["Shard:Workers"] = float(procs)
        if stats:
            res.counters["Shard:MirroredBlocks"] = float(
                sum(s.get("mirrored", 0) + s.get("perk_mirrored", 0)
                    for s in stats))
            _add_worker_sidecar_counters(res, stats)
        if per_k:
            res.counters["Shard:PerKRounds"] = float(mined["rounds"])
            res.counters["Shard:PerKBlocks"] = float(mined["blocks"])
            # the distributed per-k phase's wall (pass-1 merge through
            # final.json) — the denominator of the per-k speedup the
            # shard_tripwire miner leg and stream_scale_check record
            res.counters["Shard:PerKSeconds"] = round(
                mined["perk_s"], 4)
        return res
    finally:
        if own_root:
            shutil.rmtree(root, ignore_errors=True)


def run_sharded_refresh(name: str, conf, inputs: Sequence[str],
                        output: str, procs: int = 2,
                        factor: int = DEFAULT_FACTOR,
                        shard_root: Optional[str] = None,
                        policy: Optional[StragglerPolicy] = None,
                        pin_cores: Optional[Sequence[int]] = None,
                        worker_hook: Optional[Callable] = None,
                        timeout_s: float = 7200.0,
                        state_dir: Optional[str] = None) -> "JobResult":
    """``--shard`` and ``--incremental`` composed: restore the last
    fold-carry checkpoint exactly like :func:`runner.run_incremental`
    (same store, same content-fingerprint gate, cold fallback on any
    doubt), then fold ONLY the verified prefix's delta tail — sharded
    across ``procs`` worker processes when there is one. The committed
    per-block delta states merge IN PLAN ORDER into the restored carry
    through the registered merge algebra, the delta blocks' content
    fingerprints extend the checkpoint, and the artifact is
    byte-identical to a solo incremental refresh (and therefore to a
    cold full scan).

    The miners stay a loud error: their per-k candidate rounds re-scan
    the whole corpus per level, so a 'delta refresh' of one is not an
    O(delta) operation and pretending otherwise would silently hide a
    full re-mine behind an incremental flag."""
    from avenir_tpu.runner import (_job_cfg, _note_sidecar_counters,
                                   _plan_finish, _prepare_incremental,
                                   _sidecar_counters, stream_fold_ops)

    canonical, prefix, cfg = _job_cfg(name, conf)
    if canonical in RESCAN_AT_FINISH:
        raise ShardError(
            f"{canonical} cannot refresh incrementally under --shard: "
            f"the miners' per-k rounds re-scan the whole corpus per "
            f"candidate length; run --shard (full re-mine) or "
            f"--incremental alone")
    ops = stream_fold_ops(canonical)
    policy = policy or StragglerPolicy()
    inputs = [str(p) for p in inputs]
    iplan = _prepare_incremental(canonical, cfg, inputs, output,
                                 state_dir)
    sc0 = _sidecar_counters()
    sizes = [os.path.getsize(p) for p in inputs]
    if all(w >= s for w, s in zip(iplan.watermarks, sizes)):
        # nothing appended anywhere: re-emit from the carry alone —
        # zero worker processes, zero bytes read
        res = _plan_finish(iplan)
        _note_sidecar_counters(canonical, res, sc0)
        res.counters["Shard:Blocks"] = 0.0
        res.counters["Shard:Workers"] = 0.0
        return res

    root = shard_root or tempfile.mkdtemp(prefix="avenir_refresh_")
    own_root = shard_root is None
    procs = max(int(procs), 1)
    try:
        plan = plan_shards(inputs, procs, factor,
                           policy=policy.to_dict(),
                           starts=list(iplan.watermarks),
                           snap=_sidecar_snap(canonical, cfg, ops,
                                              inputs, procs, factor,
                                              schema=iplan.schema))
        plan.job = canonical
        plan.prefix = prefix
        plan.props = {k: str(v) for k, v in cfg.props.items()
                      if k != "__job_name__"}
        plan.record_fps = True
        write_plan(plan, os.path.join(root, "plan.json"))
        ledger = BlockLedger(root)
        logs = os.path.join(root, "logs")
        os.makedirs(logs, exist_ok=True)
        workers = []
        for w in range(procs):
            preexec = None
            if pin_cores and hasattr(os, "sched_setaffinity"):
                core = pin_cores[w % len(pin_cores)]
                preexec = (lambda c=core: os.sched_setaffinity(0, {c}))
            log = open(os.path.join(logs, f"w{w}.log"), "ab")
            workers.append((log, subprocess.Popen(
                [sys.executable, "-m", "avenir_tpu.dist.worker",
                 root, str(w)],
                stdout=log, stderr=log, env=_worker_env(),
                cwd=_pkg_parent(), preexec_fn=preexec)))
        try:
            if worker_hook is not None:
                worker_hook([p.pid for _log, p in workers], root)
            deadline = time.perf_counter() + timeout_s
            ready = os.path.join(root, "ready")
            while True:
                try:
                    n_ready = len(os.listdir(ready))
                except OSError:
                    n_ready = 0
                if n_ready >= procs:
                    break
                _reap_check(workers, ledger, plan, logs)
                if time.perf_counter() > deadline:
                    raise ShardError(
                        f"{n_ready}/{procs} workers ready within "
                        f"{timeout_s}s")
                time.sleep(0.01)
            t_scan = time.perf_counter()
            publish_bytes(b"go", os.path.join(root, "go"))
            n_blocks = len(plan.blocks)
            _wait_commits(ledger, n_blocks, workers, logs, deadline,
                          policy.poll_s)
            grace_until = time.perf_counter() + policy.exit_grace_s
            while any(p.poll() is None for _log, p in workers) \
                    and time.perf_counter() < grace_until:
                time.sleep(0.02)
        finally:
            for log, proc in workers:
                if proc.poll() is None:
                    proc.kill()
                    proc.wait()
                log.close()

        # ---- merge the delta INTO the restored carry, in plan order
        t_merge = time.perf_counter()
        states = {bid: ledger.load_state(bid)
                  for bid in ledger.committed()}
        delta = merge_block_states(canonical, cfg, ops, plan, states,
                                   inputs, root, schema=iplan.schema)
        iplan.fold = (ops.merge_states(iplan.fold, delta)
                      if iplan.hit_blocks > 0 else delta)
        # the delta blocks' fingerprints extend the checkpoint — the
        # WORKER-recorded fingerprints of the exact chunks each fold
        # consumed (ledger.load_fps), never a coordinator re-read: a
        # source appended to between a worker's fold and this merge
        # must not stamp never-folded bytes into the checkpoint. A
        # block whose fingerprints are missing or do not tile its
        # range (commit-crash window) poisons the whole extension: the
        # merged carry already contains that block, so a checkpoint
        # stamped without its fingerprints would double-fold it on the
        # next refresh — keep the PREVIOUS checkpoint instead (the next
        # refresh re-parses the delta: a cold fallback, never a wrong
        # one).
        gap = False
        for blk in plan.blocks:
            if blk.start >= blk.end:
                continue
            iplan.delta_blocks += 1
            if gap:
                continue
            fps = ledger.load_fps(blk.id)
            ok = bool(fps)
            if ok:
                expect = blk.start
                try:
                    for fp in fps:
                        if int(fp["offset"]) != expect:
                            ok = False
                            break
                        expect += int(fp["length"])
                except (KeyError, TypeError, ValueError):
                    ok = False
                ok = ok and expect == blk.end
            if not ok:
                gap = True
                continue
            iplan.fps[blk.input].extend(fps)
            iplan.watermarks[blk.input] = blk.end
        merge_ms = (time.perf_counter() - t_merge) * 1e3
        t0 = _obs.now()
        res = _plan_finish(iplan, checkpoint=not gap)
        _obs.record("job.dispatch", t0, mode="sharded-refresh",
                    procs=procs, blocks=n_blocks, jobs=canonical)
        _note_sidecar_counters(canonical, res, sc0)
        stats = _worker_stats(root, procs)
        by_id = {b.id: b for b in plan.blocks}
        res.counters["Shard:Blocks"] = float(n_blocks)
        res.counters["Shard:StolenBlocks"] = float(
            sum(1 for bid, info in ledger.claims().items()
                if bid in by_id and by_id[bid].home != info["worker"]))
        res.counters["Shard:DedupBlocks"] = float(ledger.dup_count())
        res.counters["Shard:MergeMs"] = round(merge_ms, 3)
        res.counters["Shard:ScanSeconds"] = round(
            time.perf_counter() - t_scan, 4)
        res.counters["Shard:Workers"] = float(procs)
        if stats:
            res.counters["Shard:MirroredBlocks"] = float(
                sum(s.get("mirrored", 0) for s in stats))
            _add_worker_sidecar_counters(res, stats)
        return res
    finally:
        if own_root:
            shutil.rmtree(root, ignore_errors=True)


def _add_worker_sidecar_counters(res, stats: List[Dict]) -> None:
    """Sum the workers' own sidecar/parse accounting into the result —
    the cross-process half of the parse-free-replay proof: a sharded
    run whose plan snapped to a warm sidecar reports Shard:ParseSpans
    == 0 and Sidecar:HitBlocks == the plan's block tally."""
    res.counters["Sidecar:HitBlocks"] = float(
        sum(s.get("sidecar_hit_blocks", 0) for s in stats))
    res.counters["Sidecar:DeltaBlocks"] = float(
        sum(s.get("sidecar_delta_blocks", 0) for s in stats))
    res.counters["Shard:ParseSpans"] = float(
        sum(s.get("parse_spans", 0) for s in stats))
    res.counters["Shard:ReplaySpans"] = float(
        sum(s.get("replay_spans", 0) for s in stats))


def _worker_stats(root: str, procs: int) -> List[Dict]:
    out = []
    for w in range(procs):
        try:
            with open(os.path.join(root, "stats", f"w{w}.json")) as fh:
                out.append(json.load(fh))
        except (OSError, ValueError):
            pass                  # a killed worker writes no stats
    return out


def _reap_check(workers, ledger, plan, logs: str) -> None:
    """Boot-phase liveness: a worker dead before the barrier is a
    config error the caller must see immediately."""
    if all(p.poll() is None for _log, p in workers):
        return
    _raise_workers_dead(workers, logs, len(ledger.committed()),
                        len(plan.blocks))


def _raise_workers_dead(workers, logs: str, done: int,
                        n_blocks: int) -> None:
    dead = [(i, p.returncode) for i, (_log, p) in enumerate(workers)
            if p.poll() is not None and p.returncode != 0]
    tails = []
    for i, rc in dead[:2]:
        try:
            with open(os.path.join(logs, f"w{i}.log"), "rb") as fh:
                tails.append(f"w{i} rc={rc}: "
                             + fh.read()[-800:].decode("utf-8", "replace"))
        except OSError:
            tails.append(f"w{i} rc={rc}: <no log>")
    raise ShardError(
        f"sharded scan lost its workers with {done}/{n_blocks} blocks "
        f"committed; dead={[(i, rc) for i, rc in dead]}\n"
        + "\n".join(tails))
