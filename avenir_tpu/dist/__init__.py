"""avenir-shard: multi-process sharded streaming with coded straggler
tolerance.

The streaming path, finally across processes: a shard planner
over-partitions inputs into newline-aligned byte-range blocks
(:mod:`avenir_tpu.dist.plan`), workers claim them through a
first-commit-wins block ledger (:mod:`avenir_tpu.dist.ledger`) — fast
workers steal the unclaimed tail, stragglers' in-flight blocks are
redundantly re-dispatched past a telemetry-derived threshold
(:mod:`avenir_tpu.dist.detect`) — and the coordinator merges committed
block states in plan order through the registered fold-state algebra
(:mod:`avenir_tpu.dist.driver`), byte-identical to the solo runner.
Miner jobs distribute END TO END: their per-k candidate rounds re-enter
the same claim/steal/mirror loop against level-namespaced ledgers
(``k<k>/b<id>``), workers counting by replaying their own committed
encoded-block caches while the coordinator only publishes candidate
manifests and merges supports. The TPU/GPU psum merge lives behind the
backend gate in :mod:`avenir_tpu.dist.collective`.

Gated by ``bench_scaling.shard_tripwire``: 2-process byte-identity +
capacity-scaled speedup floor (single-pass families AND the miner
per-k leg), plus a SIGSTOP chaos leg asserting the tail completes
redundantly with ``Shard:DedupBlocks >= 1`` and zero lost blocks.
"""

from avenir_tpu.dist.detect import (StragglerPolicy, mirror_after_s,
                                    mirror_after_wall_s)
from avenir_tpu.dist.driver import (ShardError, merge_block_states,
                                    run_sharded)
from avenir_tpu.dist.ledger import BlockLedger
from avenir_tpu.dist.plan import (DEFAULT_FACTOR, PlanError, ShardBlock,
                                  ShardPlan, load_plan, plan_shards,
                                  write_json_atomic, write_plan)

__all__ = [
    "BlockLedger",
    "DEFAULT_FACTOR",
    "PlanError",
    "ShardBlock",
    "ShardError",
    "ShardPlan",
    "StragglerPolicy",
    "load_plan",
    "merge_block_states",
    "mirror_after_s",
    "mirror_after_wall_s",
    "plan_shards",
    "run_sharded",
    "write_json_atomic",
    "write_plan",
]
