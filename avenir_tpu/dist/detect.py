"""Straggler detection: from per-block span telemetry to steal/mirror.

The decision inputs already exist: every block a worker folds emits the
PR-10 ``stream.read`` / ``stream.parse`` / ``stream.fold`` spans, and
PR-11's :func:`avenir_tpu.tune.signals.extract_signals` rolls a captured
window of them into totals. A worker therefore KNOWS, from its own
telemetry, how long one block's read+parse+fold takes on this host — and
that number, not a hardcoded timeout, is what decides when a peer's
claim has gone stale:

- **Steal** is the cheap, always-on move: a worker with no home blocks
  left claims from the global unclaimed tail. No detector needed — an
  unclaimed block is free work by construction.
- **Mirror** is the expensive move (redundant compute, a guaranteed
  rejected duplicate commit when the original eventually finishes), so
  it is gated: only a claim older than ``mirror_multiple`` × the
  observed per-block wall (floored at ``mirror_floor_s`` so microscopic
  corpora don't mirror every scheduling wobble) is re-dispatched. The
  first-commit-wins ledger makes the duplicate harmless; this policy
  makes it RARE.

Pure functions over :class:`RunSignals` + plain numbers, so tests and
the chaos harness drive them with synthetic telemetry.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict

from avenir_tpu.tune.signals import RunSignals


@dataclass
class StragglerPolicy:
    """The sharded run's straggler knobs — plan-manifest-serializable
    (plain floats) so the coordinator chooses them once and every
    worker applies the same thresholds."""

    #: worker poll granularity while waiting on peers' commits
    poll_s: float = 0.05
    #: mirror a claim older than this multiple of the observed
    #: per-block wall
    mirror_multiple: float = 4.0
    #: ...but never sooner than this (an idle-ish host's scheduling
    #: jitter — or a peer's one-time jit warmup on its first block —
    #: must not trigger redundant work; chaos tests dial it down)
    mirror_floor_s: float = 5.0
    #: hard ceiling on how long an uncommitted claim can gate the run
    #: even when the local per-block estimate is huge
    mirror_cap_s: float = 120.0
    #: once EVERY block is committed, how long the coordinator waits
    #: for straggling workers to exit on their own (recording their
    #: late rejected commits in the dedup counters) before killing
    #: them — a permanently wedged worker must not hold a finished
    #: scan hostage for the run timeout
    exit_grace_s: float = 60.0
    #: False turns redundant re-dispatch off entirely (steal-only)
    mirror: bool = True

    def to_dict(self) -> Dict[str, float]:
        return {"poll_s": self.poll_s,
                "mirror_multiple": self.mirror_multiple,
                "mirror_floor_s": self.mirror_floor_s,
                "mirror_cap_s": self.mirror_cap_s,
                "exit_grace_s": self.exit_grace_s,
                "mirror": float(self.mirror)}

    @classmethod
    def from_dict(cls, obj: Dict) -> "StragglerPolicy":
        base = cls()
        return cls(
            poll_s=float(obj.get("poll_s", base.poll_s)),
            mirror_multiple=float(obj.get("mirror_multiple",
                                          base.mirror_multiple)),
            mirror_floor_s=float(obj.get("mirror_floor_s",
                                         base.mirror_floor_s)),
            mirror_cap_s=float(obj.get("mirror_cap_s", base.mirror_cap_s)),
            exit_grace_s=float(obj.get("exit_grace_s",
                                       base.exit_grace_s)),
            mirror=bool(obj.get("mirror", True)))


def per_block_seconds(sig: RunSignals, blocks_done: int) -> float:
    """Observed wall per folded block from one worker's extracted
    signals: total read+parse+fold seconds over the blocks it has
    finished. 0.0 until the first block lands (no evidence yet)."""
    if blocks_done < 1:
        return 0.0
    return (sig.read_s + sig.parse_s + sig.fold_s) / blocks_done


def mirror_after_s(policy: StragglerPolicy, sig: RunSignals,
                   blocks_done: int) -> float:
    """Claim age past which a peer's uncommitted block is redundantly
    re-dispatched: ``mirror_multiple`` × the telemetry-observed
    per-block wall, clamped to [floor, cap]. With no local evidence yet
    the floor applies — a worker that has folded nothing has no basis
    to call anyone else slow."""
    est = policy.mirror_multiple * per_block_seconds(sig, blocks_done)
    return min(max(est, policy.mirror_floor_s), policy.mirror_cap_s)


def mirror_after_wall_s(policy: StragglerPolicy, wall_s: float,
                        blocks_done: int) -> float:
    """The per-k variant of :func:`mirror_after_s`: the miners' per-k
    count folds replay the encoded-block cache (no ``stream.read`` /
    ``stream.parse`` spans fire), so the worker prices a per-k block
    from its DIRECTLY measured count wall — total seconds over per-k
    blocks it has finished — instead of the span extractor. Same
    multiple, same floor/cap clamp, same no-evidence rule."""
    est = (policy.mirror_multiple * wall_s / blocks_done
           if blocks_done > 0 else 0.0)
    return min(max(est, policy.mirror_floor_s), policy.mirror_cap_s)
