"""Block ledger: filesystem claims and first-commit-wins block states.

The sharded driver's one hard invariant comes from the merge auditor's
overlap probe: EVERY registered fold family is NON-idempotent (re-folding
a block changes the output), so redundant execution — the whole point of
over-partitioning and straggler mirroring — must dedup at block
granularity BEFORE the fold. The ledger is where that happens, with the
same single-writer filesystem discipline as ``net/fault.py`` leases:

- **Claims** (``claims/b<id>.json``): a worker claims a block by writing
  the claim JSON to a tmp file and hard-LINKING it into place —
  ``os.link`` fails with EEXIST when a claim already exists, so exactly
  one of N racing workers wins, and because the tmp file is complete
  before the link, a reader can never see a torn claim from this path.
  A claim that IS torn (external truncation, a crashed hand-rolled
  writer) is treated as unclaimed: the first worker to notice renames
  it aside (atomic — exactly one renamer succeeds) and re-claims.
- **Commits** (``states/b<id>.npz``): the serialized fold state itself
  is the commit record, published the same tmp+link way. The FIRST
  commit wins; a duplicate commit of the same block id — a mirrored
  straggler block finishing twice, a SIGCONT'd worker completing work
  someone already re-did — is REJECTED (EEXIST), counted, and recorded
  as a ``dups/`` marker. The coordinator merges exactly one state per
  block id, in plan order: a block folds into the final state exactly
  once, never twice.

Everything is observable from ``ls``: claims say who owes which block
(and since when — the straggler detector's input), states say what is
done, dups say the dedup fired. No daemon, no lock server; rename and
link on one filesystem are the whole coordination substrate, exactly
like the fleet's spool and lease files.

**Namespaces** (:meth:`BlockLedger.level`): the miners' distributed
per-k rounds reuse the same claim/commit discipline once per candidate
length — level ``k`` counts block ``b`` under ``ledger/k<k>/b<b>``, so
one block id claims, commits and dedups independently PER LEVEL and a
block's candidate counts fold into a level's merged support exactly
once. The default (pass-1) namespace is the bare ``ledger/`` root, so
every pre-existing caller is the empty-namespace case.
"""

from __future__ import annotations

import json
import os
import time
import uuid
from typing import Dict, List, Optional

from avenir_tpu.core.atomic import (AFTER_RENAME, BEFORE_RENAME,
                                    crash_point, publish_json,
                                    sched_point, sweep_stale_tmps)
from avenir_tpu.core.keys import key_site

#: ledger record/state layout version. Stamped into every claim and dup
#: record and into a per-states-dir ``states/FORMAT.json`` marker; a
#: marker stamped with a DIFFERENT version makes :meth:`BlockLedger.
#: load_state` / :meth:`BlockLedger.committed` refuse (go cold) — old
#: readers must never silently merge a newer state layout. A MISSING
#: marker is a pre-versioning ledger and still serves.
FORMAT_VERSION = 1

#: per-states-dir layout-version marker file name
STATES_FORMAT = "FORMAT.json"


class BlockLedger:
    """Claim/commit ledger for one sharded run, rooted at
    ``<root>/ledger``. Safe for concurrent use by any number of worker
    processes on one filesystem."""

    def __init__(self, root: str, ns: str = ""):
        self._base = root
        self.ns = ns
        self.root = (os.path.join(root, "ledger", ns) if ns
                     else os.path.join(root, "ledger"))
        self.claims_dir = os.path.join(self.root, "claims")
        self.states_dir = os.path.join(self.root, "states")
        self.dups_dir = os.path.join(self.root, "dups")
        for d in (self.claims_dir, self.states_dir, self.dups_dir):
            os.makedirs(d, exist_ok=True)
        # stamp the states-dir layout version once, first writer wins
        # (deterministic bytes, so racing stampers publish identical
        # content; an existing marker — any version — is left alone)
        marker = os.path.join(self.states_dir, STATES_FORMAT)
        if not os.path.exists(marker):
            publish_json({"format_version": FORMAT_VERSION}, marker,
                         site="ledger.format")
        # startup GC: tmp files a hard-killed worker left behind (the
        # age gate keeps a concurrent writer's live tmp safe)
        sweep_stale_tmps(self.root)

    def level(self, ns: str) -> "BlockLedger":
        """A NAMESPACED sub-ledger under ``ledger/<ns>/`` — the per-k
        rounds' handle (``level("k2")`` scopes block ``b`` at
        ``k2/b<b>``): same first-commit-wins discipline, independent
        claim/commit/dup state per level."""
        if not ns or os.sep in ns or ns != os.path.basename(ns):
            raise ValueError(f"bad ledger namespace {ns!r}")
        return BlockLedger(self._base, ns=ns)

    # ---------------------------------------------------------- claims
    def claim_path(self, block_id: int) -> str:
        return os.path.join(self.claims_dir, f"b{block_id}.json")

    def claim(self, block_id: int, worker: int,
              mirror: bool = False) -> bool:
        """Atomically claim a block; True when THIS call won. ``mirror``
        marks a redundant re-dispatch claim record (informational — a
        mirror does not take the claim, it races the commit; the flag
        only lands in the claim file when the mirrorer claims an
        abandoned, never-claimed block)."""
        path = self.claim_path(block_id)
        tmp = os.path.join(self.claims_dir,
                           f".tmp.b{block_id}.{uuid.uuid4().hex}")
        with open(tmp, "w") as fh:
            json.dump({"format_version": FORMAT_VERSION,
                       "block": block_id, "worker": worker,
                       "claimed_at": time.time(), "mirror": mirror}, fh)
        crash_point("ledger.claim", BEFORE_RENAME)
        try:
            for _ in range(8):
                sched_point("ledger.claim")
                try:
                    os.link(tmp, path)
                    crash_point("ledger.claim", AFTER_RENAME)
                    sched_point("ledger.claim")
                    return True
                except FileExistsError:
                    if self.claim_info(block_id) is not None:
                        return False          # a well-formed claim holds
                    # torn claim: treated as unclaimed. Exactly one
                    # worker wins the rename-aside; the loser re-loads
                    # and either sees the winner's fresh claim or races
                    # the next link round.
                    torn = f"{path}.torn.{uuid.uuid4().hex}"
                    try:
                        os.rename(path, torn)
                    except OSError:
                        pass
            return False
        finally:
            try:
                os.remove(tmp)
            except OSError:
                pass

    def claim_info(self, block_id: int) -> Optional[Dict]:
        """The claim record, or None when unclaimed OR torn (an
        unparseable claim is by contract not a claim)."""
        try:
            with open(self.claim_path(block_id)) as fh:
                obj = json.load(fh)
            return {"block": int(obj["block"]),
                    "worker": int(obj["worker"]),
                    "claimed_at": float(obj["claimed_at"]),
                    "mirror": bool(obj.get("mirror", False))}
        except (OSError, ValueError, KeyError, TypeError):
            return None

    def claims(self) -> Dict[int, Dict]:
        out: Dict[int, Dict] = {}
        try:
            names = os.listdir(self.claims_dir)
        except OSError:
            return out
        for n in names:
            if not n.startswith("b") or not n.endswith(".json"):
                continue
            try:
                bid = int(n[1:-5])
            except ValueError:
                continue
            info = self.claim_info(bid)
            if info is not None:
                out[bid] = info
        return out

    # --------------------------------------------------------- commits
    def state_path(self, block_id: int) -> str:
        return os.path.join(self.states_dir, f"b{block_id}.npz")

    def fps_path(self, block_id: int) -> str:
        return os.path.join(self.states_dir, f"b{block_id}.fps.json")

    def commit(self, block_id: int, worker: int, blob: bytes,
               fps: Optional[List[Dict]] = None) -> bool:
        """Publish a block's serialized fold state, FIRST COMMIT WINS.
        Returns True when this state is the one the coordinator will
        merge; False when the block was already committed — the
        duplicate is rejected (never merged: the fold families are
        non-idempotent) and recorded under ``dups/``.

        ``fps`` (refresh plans) are the content fingerprints of the
        chunks THIS fold consumed; only the winning commit publishes
        them (a losing mirror may have re-read different bytes), so
        the coordinator's checkpoint extension always describes the
        state it merges. Published after the state link — a crash in
        between leaves a committed block with no fingerprints, which
        the coordinator treats as end-of-extension (cold next refresh
        from there), never as a wrong checkpoint."""
        path = self.state_path(block_id)
        tmp = os.path.join(self.states_dir,
                           f".tmp.b{block_id}.{uuid.uuid4().hex}")
        with open(tmp, "wb") as fh:
            fh.write(blob)
        crash_point("ledger.commit", BEFORE_RENAME)
        try:
            sched_point("ledger.commit")
            os.link(tmp, path)
            crash_point("ledger.commit", AFTER_RENAME)
            sched_point("ledger.commit")
            if fps is not None:
                fptmp = f"{tmp}.fps"
                with open(fptmp, "w") as fh:
                    json.dump(fps, fh)
                os.replace(fptmp, self.fps_path(block_id))
            return True
        except FileExistsError:
            self._mark_dup(block_id, worker)
            return False
        finally:
            try:
                os.remove(tmp)
            except OSError:
                pass

    def load_fps(self, block_id: int) -> Optional[List[Dict]]:
        """The winning commit's folded-chunk fingerprints, or None when
        the block committed without them (non-refresh plan, or a crash
        between the state link and the fingerprint publish)."""
        try:
            with open(self.fps_path(block_id)) as fh:
                fps = json.load(fh)
            return list(fps) if isinstance(fps, list) else None
        except (OSError, ValueError):
            return None

    def _mark_dup(self, block_id: int, worker: int) -> None:
        """Record one rejected duplicate commit — worker-namespaced so
        concurrent losers never race one file, atomic so the
        coordinator's count never reads a torn marker."""
        path = os.path.join(self.dups_dir, f"b{block_id}.w{worker}.json")
        publish_json({"format_version": FORMAT_VERSION,
                      "block": block_id, "worker": worker,
                      "rejected_at": time.time()}, path,
                     site="ledger.dup")

    def _states_format_ok(self) -> bool:
        """Whether the states dir's layout-version marker matches this
        reader. A missing or torn marker is a pre-versioning ledger and
        still serves; a PRESENT marker with a different version makes
        every state read refuse — merging a newer layout as if it were
        this one is the silent-wrong-answer case the stamp exists for."""
        try:
            with open(os.path.join(self.states_dir, STATES_FORMAT)) as fh:
                marker = json.load(fh)
        except (OSError, ValueError):
            return True
        if not isinstance(marker, dict):
            return True
        return marker.get("format_version",
                          FORMAT_VERSION) == FORMAT_VERSION

    def load_state(self, block_id: int) -> bytes:
        """The winning commit's serialized fold state. The committed
        identity is first-commit-wins per (namespace, block id):
        whichever worker linked ``states/b<id>.npz`` first is the state
        every reader serves — content validity is the link's atomicity
        plus the version marker, never mtime.

        key-covered: all — the path IS the key (ns + block id).
        """
        key_site("ledger.committed")
        if not self._states_format_ok():
            raise ValueError(
                f"ledger states dir {self.states_dir!r}: layout version "
                f"mismatch (reader expects {FORMAT_VERSION}) — refusing "
                f"to serve; start a fresh ledger root")
        with open(self.state_path(block_id), "rb") as fh:
            return fh.read()

    def committed(self) -> List[int]:
        if not self._states_format_ok():
            return []      # version skew: nothing servable, go cold
        try:
            names = os.listdir(self.states_dir)
        except OSError:
            return []
        out = []
        for n in names:
            if n.startswith("b") and n.endswith(".npz"):
                try:
                    out.append(int(n[1:-4]))
                except ValueError:
                    pass
        return sorted(out)

    def dup_count(self) -> int:
        try:
            return sum(1 for n in os.listdir(self.dups_dir)
                       if n.endswith(".json"))
        except OSError:
            return 0

    # ------------------------------------------------------- summaries
    def pending(self, n_blocks: int) -> List[int]:
        """Block ids not yet committed."""
        done = set(self.committed())
        return [b for b in range(n_blocks) if b not in done]

    def unclaimed(self, n_blocks: int) -> List[int]:
        """Block ids with neither a (well-formed) claim nor a commit."""
        done = set(self.committed())
        claimed = set(self.claims())
        return [b for b in range(n_blocks)
                if b not in done and b not in claimed]

    def stale_claims(self, n_blocks: int, older_than_s: float,
                     now: Optional[float] = None) -> List[int]:
        """Claimed-but-uncommitted block ids whose claim is older than
        ``older_than_s`` — the straggler detector's candidates for
        redundant re-dispatch, oldest first."""
        now = time.time() if now is None else now
        done = set(self.committed())
        rows = [(info["claimed_at"], bid)
                for bid, info in self.claims().items()
                if bid not in done
                and now - info["claimed_at"] > older_than_s]
        return [bid for _t, bid in sorted(rows)]
