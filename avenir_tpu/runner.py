"""Properties-driven job runner: the L6/L5 surface of the reference.

The reference is driven as `hadoop jar avenir.jar <ToolClass>
-Dconf.path=<props> IN OUT` from bash case-statement scripts
(resource/detr.sh:52, resource/knn.sh:76); every job reads namespaced keys
from one flat properties file (SURVEY §2.11, §5 config). This module keeps
that surface: a registry of jobs addressed by the reference's job names /
Tool class names, each reading the *same* config keys (`bad.*`, `nen.*`,
`dtb.*`, `fia.*`, `mst.*`, ...) from the same properties files, plus a
`Pipeline` that replaces the shell case statements.

What changes is the execution: a "job" here is an in-process call into the
jitted TPU kernels — no JVM spawn, no HDFS round trip between stages. Jobs
that the reference chains through intermediate HDFS files (e.g. the 5-stage
KNN pipeline, SURVEY §3.3) collapse into fused single jobs, but each stage
name is still addressable for drop-in pipeline parity.

Model/state files between iterative rounds stay plain files (SURVEY §5
checkpoint/resume): DecisionPathList JSON, itemset CSVs per Apriori k,
Markov matrix files, LR coefficient history.
"""

from __future__ import annotations

import io
import json
import math
import os
import sys
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from avenir_tpu import obs as _obs
from avenir_tpu.core.config import (JobConfig, MissingConfigError,
                                    load_properties)
from avenir_tpu.core.dataset import Dataset
from avenir_tpu.core.schema import FeatureSchema
from avenir_tpu.utils.metrics import ConfusionMatrix, throughput_counters


@dataclass
class JobResult:
    """What a job hands back to the driver: Hadoop-counter-style counters
    (the reference's "Validation:*" groups, BayesianPredictor.java:170-180)
    plus produced file paths and an optional in-memory payload."""

    name: str
    counters: Dict[str, float] = field(default_factory=dict)
    outputs: List[str] = field(default_factory=list)
    payload: object = None

    def __repr__(self) -> str:
        return f"JobResult({self.name}, counters={self.counters}, outputs={self.outputs})"


JobFn = Callable[[JobConfig, List[str], str], JobResult]

# registry key (job name or Tool class alias) -> (canonical name, prefix, fn)
_REGISTRY: Dict[str, Tuple[str, str, JobFn]] = {}


def job(name: str, prefix: str, *aliases: str):
    """Register a job under its pipeline name + reference Tool class name."""

    def deco(fn: JobFn) -> JobFn:
        for key in (name, *aliases):
            _REGISTRY[key] = (name, prefix, fn)
        return fn

    return deco


def job_names() -> List[str]:
    return sorted(_REGISTRY)


def job_prefix(name: str) -> str:
    """The reference config prefix a registered job reads (e.g.
    greedyRandomBandit -> 'grb'); accepts aliases."""
    if name not in _REGISTRY:
        raise KeyError(f"unknown job {name!r}")
    return _REGISTRY[name][1]


def _job_cfg(name: str, conf) -> Tuple[str, str, JobConfig]:
    """(canonical name, prefix, scoped JobConfig) for a registered job.
    `conf` is a properties file path, a dict, or a JobConfig."""
    if name not in _REGISTRY:
        raise KeyError(
            f"unknown job {name!r}; known: {', '.join(job_names())}"
        )
    canonical, prefix, _fn = _REGISTRY[name]
    if isinstance(conf, str):
        if conf.endswith(".conf"):
            # Spark-surface HOCON config: one block per job name
            # (resource/atmTrans.conf, chombo-spark JobConfiguration)
            cfg = JobConfig.from_hocon(conf, canonical, prefix)
        else:
            cfg = JobConfig(load_properties(conf), prefix)
    elif isinstance(conf, dict):
        cfg = JobConfig(conf, prefix)
    else:
        cfg = conf.scoped(prefix)
    cfg.props["__job_name__"] = canonical
    return canonical, prefix, cfg


def run_job(name: str, conf, inputs: Sequence[str], output: str = "") -> JobResult:
    """Run a registered job. `conf` is a properties file path, a dict, or a
    JobConfig; the job sees it scoped under its reference prefix.

    Every streamed job's result additionally carries the memory-oracle
    counter pair: `Mem:PredictedPeakBytes` (the analysis/mem analytic
    footprint model at the job's block size and corpus) next to the
    measured `Mem:PeakRSS` — so long-running anchors (the 100M-row
    stream_scale_check children run one job per process) record the
    model's error over time."""
    canonical, _prefix, cfg = _job_cfg(name, conf)
    fn = _REGISTRY[canonical][2]
    if output:
        parent = os.path.dirname(os.path.abspath(output))
        os.makedirs(parent, exist_ok=True)
    session = _autotune_begin([canonical], [cfg], inputs)
    rss0 = _rss_now()
    sc0 = _sidecar_counters()
    t0 = _obs.now()
    try:
        res = fn(cfg, list(inputs), output)
    except BaseException:
        if session is not None:
            session.close()   # a leaked session would contaminate
        raise                 # every later one in this process
    _obs.record("job.run", t0, job=canonical)
    _note_sidecar_counters(canonical, res, sc0)
    _add_mem_counters(canonical, cfg, inputs, res, rss0=rss0)
    if session is not None:
        session.finish({canonical: res})
    return res


#: highest process-lifetime peak RSS (bytes) already attributed to a
#: streamed result. ru_maxrss is a LIFETIME peak: inside a resident
#: process every later job re-reads the biggest job's number, so a
#: residual recorded from it would poison the learned admission factor
#: for every small job that follows. Only a run that RAISES the peak
#: records one — exact for the one-job-per-process scale anchors (the
#: designed signal source), silent for the jobs residency dwarfs.
#: Unlocked int: a racing double/missed record costs one advisory
#: history sample, never a wrong knob or price.
_residual_peak_seen = 0


def _rss_now() -> int:
    """Current (not peak) resident bytes via /proc/self/statm; 0 where
    unavailable. Snapshotted at job start so the residual record can
    price the job's INCREMENTAL footprint (peak minus the resident
    baseline already paid — interpreter, jax, earlier jobs' sticky
    arenas), which is what the analytic model predicts; pairing the
    absolute peak against an incremental prediction would bake the
    process baseline into the learned admission factor."""
    try:
        with open("/proc/self/statm") as fh:
            return int(fh.read().split()[1]) * (os.sysconf("SC_PAGE_SIZE")
                                                or 4096)
    except (OSError, ValueError, IndexError):
        return 0


def _add_mem_counters(canonical: str, cfg: JobConfig,
                      inputs: Sequence[str], res: JobResult,
                      rss0: Optional[int] = None) -> None:
    """Attach the memory-oracle counters to a streamed job's result.
    Advisory by contract: a failure to PREDICT must never fail a job
    that already ran, so any error here drops the counters silently.

    Every streamed result also carries the delta-scan accounting triple
    next to the Mem:*/Cache:* counters — run_incremental fills the real
    numbers before this runs; a plain (cold) run keeps the zeros, so
    every streamed JobResult speaks one counter schema."""
    if canonical not in _STREAM_FOLDS:
        return
    res.counters.setdefault("Cache:HitBlocks", 0.0)
    res.counters.setdefault("Cache:DeltaBlocks", 0.0)
    res.counters.setdefault("Resume:SkippedBytes", 0.0)
    res.counters.setdefault("Sidecar:HitBlocks", 0.0)
    res.counters.setdefault("Sidecar:DeltaBlocks", 0.0)
    try:
        import resource

        from avenir_tpu.analysis.mem import corpus_stats, footprint_model

        paths = [p for p in inputs if os.path.exists(p)]
        if not paths:
            return
        # linux ru_maxrss is KB; this is the process peak at job end —
        # exact for the one-job-per-process scale anchors, an upper
        # bound inside long-lived processes
        rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024
        if "Mem:PredictedPeakBytes" not in res.counters:
            # run_incremental already priced the scan (its checkpoint
            # advisory) and pre-set the counter — don't re-sample the
            # corpus for the same number
            from avenir_tpu.core.stream import prefetch_depth

            block = int(cfg.get_float("stream.block.size.mb", 64.0)
                        * (1 << 20))
            stats = corpus_stats(paths, delim=cfg.field_delim_regex)
            schema = None
            schema_path = cfg.get("feature.schema.file.path")
            if schema_path:
                schema = FeatureSchema.from_file(schema_path)
            est = footprint_model(canonical, block, schema, stats,
                                  prefetch_depth=prefetch_depth(cfg))
            res.counters["Mem:PredictedPeakBytes"] = float(est.total_bytes)
        res.counters["Mem:PeakRSS"] = float(rss)
        # the tuner's model-refinement history: a streamed result whose
        # run RAISED the process peak (see _residual_peak_seen) lands
        # its predicted-vs-measured pair in the per-(job, corpus)
        # profile store — from day one, not only when autotune is on.
        # measured is the INCREMENTAL growth over the run's starting
        # RSS (rss0, captured by the caller), matching what the model
        # predicts; callers without a start snapshot (the warm-miner
        # fast path) record nothing.
        global _residual_peak_seen
        if rss > _residual_peak_seen:
            _residual_peak_seen = rss
            if rss0 is not None and rss - rss0 > 0:
                from avenir_tpu import tune

                tune.record_residual(
                    canonical, cfg, paths,
                    res.counters["Mem:PredictedPeakBytes"], rss - rss0)
    except Exception:
        pass


def _sidecar_counters() -> Optional[dict]:
    """Snapshot of the process-global sidecar hit/delta counters taken
    before a scan; _note_sidecar_counters pairs it with a second one to
    attribute the delta to a JobResult. None (and no attribution) when
    the sidecar layer cannot load."""
    try:
        from avenir_tpu.native import sidecar

        return sidecar.counters_snapshot()
    except Exception:
        return None


def _note_sidecar_counters(canonical: str, res: JobResult,
                           before: Optional[dict]) -> None:
    """Report the sidecar blocks this scan replayed (Sidecar:HitBlocks)
    vs parsed cold into the sidecar (Sidecar:DeltaBlocks). Counters are
    process-global, so a FUSED run attributes the shared scan's totals
    to every fold it fed — the replays genuinely served each of them.
    Advisory: any failure leaves the zeros _add_mem_counters installs."""
    if before is None or canonical not in _STREAM_FOLDS:
        return
    try:
        from avenir_tpu.native import sidecar

        after = sidecar.counters_snapshot()
        res.counters["Sidecar:HitBlocks"] = float(
            after["hit_blocks"] - before["hit_blocks"])
        res.counters["Sidecar:DeltaBlocks"] = float(
            after["delta_blocks"] - before["delta_blocks"])
    except Exception:
        pass


def _autotune_begin(canonicals: Sequence[str], cfgs: Sequence[JobConfig],
                    inputs: Sequence[str]):
    """Start an autotuned run when the (first) config opts in with the
    `stream.autotune` key and every job is streamed: overlays the
    profile store's chosen knobs onto the configs and returns the
    session whose ``finish(results)`` records this run's telemetry and
    chooses the next knobs (avenir_tpu.tune.begin_run). Returns None
    when autotune is off or inapplicable.

    Advisory EXCEPT for the knob guard: a profile naming an unknown or
    out-of-range knob key raises KnobError — loudly, so a typo'd tuned
    profile can never silently run defaults; any other storage failure
    degrades to an untuned run."""
    cfg0 = cfgs[0]
    if not cfg0.get_bool("stream.autotune", False):
        return None
    if not inputs or any(c not in _STREAM_FOLDS for c in canonicals):
        return None
    from avenir_tpu import tune

    try:
        return tune.begin_run(list(canonicals), list(cfgs), list(inputs))
    except tune.KnobError:
        raise
    except Exception:
        return None


# ---------------------------------------------------------------- helpers
def _out_file(output: str, part: str = "part-r-00000") -> str:
    """Output path contract: a directory (Hadoop-style `part-r-00000`
    inside) when the path ends with '/' or already is a directory, else a
    plain file."""
    if output.endswith(os.sep) or os.path.isdir(output):
        os.makedirs(output, exist_ok=True)
        return os.path.join(output, part)
    parent = os.path.dirname(os.path.abspath(output))
    os.makedirs(parent, exist_ok=True)
    return output


def _schema(cfg: JobConfig) -> FeatureSchema:
    return FeatureSchema.from_file(cfg.assert_get("feature.schema.file.path"))


def _dataset(path: str, cfg: JobConfig, keep_raw: bool = False) -> Dataset:
    return Dataset.from_csv(path, _schema(cfg), delim=cfg.field_delim_regex,
                            keep_raw=keep_raw)


def _read_lines(path: str) -> List[str]:
    with open(path) as fh:
        return [ln.rstrip("\n") for ln in fh if ln.strip()]


def _parse_sequences(lines: Sequence[str], delim: str, skip: int,
                     class_ord: Optional[int] = None):
    """Rows -> (ids, sequences, labels). First `skip` fields are meta
    (id/class); `class_ord` points into the full row. Token trim set is
    space/tab/CR — exactly the native seq_encode trim, so the python and
    native sequence paths tokenize identically."""
    ids, seqs, labels = [], [], []
    for ln in lines:
        toks = [t.strip(" \t\r") for t in ln.split(delim)]
        ids.append(toks[0] if skip > 0 else "")
        labels.append(toks[class_ord] if class_ord is not None else None)
        seqs.append(toks[skip:])
    return ids, seqs, labels


def _read_sequences(path: str, delim: str, skip: int,
                    class_ord: Optional[int] = None):
    return _parse_sequences(_read_lines(path), delim, skip, class_ord)


def _validate(class_values: Sequence[str], actual: np.ndarray,
              predicted: np.ndarray, pos_class: int) -> Dict[str, float]:
    """ConfusionMatrix.counters() — the reference's "Validation" Hadoop
    counter group (BayesianPredictor.java:170-180, int-percent scaled)."""
    cm = ConfusionMatrix(class_values, pos_class=pos_class)
    cm.add(actual, predicted)
    return cm.counters()


def _drive_fold(fold, chunks, job: str) -> int:
    """Drive one fold sink over a chunk iterator through ``SharedScan``
    — the single-sink special case of the scan-sharing executor, which
    is exactly what the one-job-one-scan paths always were. Routing the
    solo paths through it means per-chunk ``stream.fold`` spans and the
    ``chunk_latency_ms`` histogram come from ONE instrumentation point,
    so the solo and fused executions can never drift apart in what they
    report (or in how they close an abandoned prefetch worker)."""
    from avenir_tpu.core.stream import SharedScan

    scan = SharedScan(chunks)
    scan.add_sink(fold, label=job)
    return scan.run()


def _finish_fold(fold, output: str, job: str) -> JobResult:
    """fold.finish(output) under the ``job.finish`` span — the artifact
    write + fold seal phase of every streamed job, one call site shape
    for the solo, shared and incremental drivers."""
    t0 = _obs.now()
    res = fold.finish(output)
    _obs.record("job.finish", t0, job=job)
    return res


# ============================================================ scan sharing
# One disk read + one parse per chunk, fanned out to N registered fold
# sinks (core.stream.SharedScan). Every fold below is ALSO the body of its
# single-job streaming path, so the fused and one-job-one-scan executions
# share one implementation — which is what makes their outputs
# byte-identical (asserted by the chunk-invariance auditor's fused
# entries and tests/test_shared_scan.py).

class _NBDistrFold:
    """bayesianDistr (tabular) as a shared-scan sink: the donated-carry
    deferred NB fold (models/naive_bayes.py:_fold_batch_kernel) per
    Dataset chunk."""

    def __init__(self, cfg: JobConfig, inputs: Sequence[str], schema):
        self.cfg = cfg
        self.schema = schema
        self.model = None
        self.rows = 0

    def consume(self, ds: Dataset) -> None:
        from avenir_tpu.models.naive_bayes import NaiveBayesModel

        if self.model is None:
            # after the first parse, so data-discovered categorical
            # vocabularies are sized into the count tensors
            self.model = NaiveBayesModel.empty(self.schema)
        codes, bins = ds.feature_codes(self.model.binned_fields)
        if bins != self.model.bins:
            raise ValueError(
                "categorical vocabulary grew mid-stream (a chunk saw a "
                "value absent from the first chunk / declared "
                "cardinality); declare full cardinalities in the schema "
                "to stream")
        x_cont = ds.feature_matrix(self.model.cont_fields)
        self.model.accumulate(codes, ds.labels(), x_cont, defer=True)
        self.rows += len(ds)

    def finish(self, output: str) -> JobResult:
        from avenir_tpu.models.naive_bayes import NaiveBayesModel

        out = _out_file(output)
        model = self.model
        if model is None:
            model = NaiveBayesModel.empty(self.schema)
        model.flush()
        model.save(out, delim=self.cfg.field_delim)
        return JobResult("bayesianDistr",
                         {"Distribution Data:Records": self.rows},
                         [out], model)

    # ----------------------------------------------- merge algebra ops
    def merge(self, other: "_NBDistrFold") -> "_NBDistrFold":
        """Shard-merge: NB sufficient statistics are additive
        (NaiveBayesModel.merge — the reducer algebra), so merging shard
        folds equals folding the concatenated shards."""
        if other.model is not None:
            if self.model is None:
                self.model = other.model
            else:
                self.model.merge(other.model)
        self.rows += other.rows
        return self

    def state_dict(self) -> Dict[str, object]:
        meta = {"rows": self.rows, "cards": None}
        arrays: Dict[str, object] = {}
        if self.model is not None:
            m = self.model
            m.flush()
            # data-discovered categorical vocabularies are part of the
            # carry: codes in later chunks must keep meaning the same
            # tokens after a restore into a freshly-loaded schema
            meta["cards"] = {str(f.ordinal): list(f.cardinality)
                             for f in m.binned_fields if f.is_categorical}
            arrays = {"post": m.post_counts, "mom": m.cont_moments,
                      "cls": m.class_counts}
        return {"meta": np.array(json.dumps(meta)), **arrays}

    def load_state(self, state: Dict[str, object]) -> None:
        from avenir_tpu.models.naive_bayes import NaiveBayesModel

        meta = json.loads(str(state["meta"]))
        self.rows = int(meta["rows"])
        if meta["cards"] is None:
            return                      # checkpoint taken before any chunk
        by_ord = {f.ordinal: f for f in self.schema.fields}
        for o, card in meta["cards"].items():
            fld = by_ord[int(o)]
            if fld.is_categorical and list(fld.cardinality or []) != card:
                fld.cardinality = list(card)
                fld.discovered_cardinality = True
        self.model = NaiveBayesModel.empty(self.schema)
        for key, attr in (("post", "post_counts"), ("mom", "cont_moments"),
                          ("cls", "class_counts")):
            arr = np.asarray(state[key], np.float64)
            if arr.shape != getattr(self.model, attr).shape:
                raise ValueError(
                    f"checkpointed NB {attr} shape {arr.shape} does not "
                    f"match the schema-derived model "
                    f"{getattr(self.model, attr).shape}")
            setattr(self.model, attr, arr)


class _MutualInfoFold:
    """mutualInformation as a shared-scan sink: additive contingency
    tables folded per Dataset chunk (MutualInformationAnalyzer.add)."""

    def __init__(self, cfg: JobConfig, inputs: Sequence[str], schema):
        from avenir_tpu.models.explore import MutualInformationAnalyzer

        self.cfg = cfg
        self.inputs = list(inputs)
        self.schema = schema
        self.mi = MutualInformationAnalyzer()

    def consume(self, ds: Dataset) -> None:
        self.mi.add(ds)

    # ----------------------------------------------- merge algebra ops
    def merge(self, other: "_MutualInfoFold") -> "_MutualInfoFold":
        """Shard-merge: every MI table is an additive integer-count
        tensor (MutualInformationAnalyzer.merge)."""
        self.mi.merge(other.mi)
        return self

    def state_dict(self) -> Dict[str, object]:
        mi = self.mi
        meta = {"n": mi.n, "k": mi.k, "bins": list(mi.bins),
                "ordinals": ([f.ordinal for f in mi.fields]
                             if mi.fields is not None else None),
                "pairs": sorted(mi._pair)}
        arrays: Dict[str, object] = {}
        if mi.fields is not None:
            for i, fc in enumerate(mi._fc):
                arrays[f"fc_{i}"] = fc
            for (i, j) in mi._pair:
                arrays[f"pair_{i}_{j}"] = mi._pair[(i, j)]
                arrays[f"pairc_{i}_{j}"] = mi._pairc[(i, j)]
        return {"meta": np.array(json.dumps(meta)), **arrays}

    def load_state(self, state: Dict[str, object]) -> None:
        meta = json.loads(str(state["meta"]))
        if meta["ordinals"] is None:
            return                      # checkpoint taken before any chunk
        if self.schema is None:
            self.schema = _schema(self.cfg)
        mi = self.mi
        # the encodable field set is schema-derived, exactly what the
        # first add() would have installed (Dataset.encodable_feature_fields)
        mi.fields = [f for f in self.schema.feature_fields
                     if f.num_bins() > 0]
        if [f.ordinal for f in mi.fields] != list(meta["ordinals"]):
            raise ValueError(
                "checkpointed MI field ordinals do not match the schema")
        mi.k = int(meta["k"])
        mi.bins = [int(b) for b in meta["bins"]]
        mi.n = int(meta["n"])
        mi._fc = [np.asarray(state[f"fc_{i}"], np.float64)
                  for i in range(len(mi.fields))]
        mi._pair = {(i, j): np.asarray(state[f"pair_{i}_{j}"], np.float64)
                    for i, j in (tuple(p) for p in meta["pairs"])}
        mi._pairc = {(i, j): np.asarray(state[f"pairc_{i}_{j}"], np.float64)
                     for i, j in (tuple(p) for p in meta["pairs"])}

    def finish(self, output: str) -> JobResult:
        cfg, mi = self.cfg, self.mi
        if mi.fields is None:
            raise ValueError(f"mutualInformation: empty input "
                             f"(no records in {self.inputs})")
        mi.finalize()
        algos = cfg.get_list("mutual.info.score.algorithms", [])
        out = _out_file(output)
        delim = cfg.field_delim
        with open(out, "w") as fh:
            if cfg.get_bool("output.mutual.info", True):
                for f, fld in enumerate(mi.fields):
                    fh.write(f"featureClassMI{delim}{fld.ordinal}{delim}"
                             f"{mi.feature_class_mi[f]:.6f}\n")
            for algo in algos:
                scores = mi.score(algo,
                                  cfg.get_float("redundancy.factor", 1.0))
                for ordinal, s in scores:
                    fh.write(f"{algo}{delim}{ordinal}{delim}{s:.6f}\n")
        return JobResult("mutualInformation",
                         {"Basic:Records": mi.n}, [out], mi)


class _FisherFold:
    """fisherDiscriminant as a shared-scan sink: per-class moment fold
    per Dataset chunk (FisherDiscriminant.accumulate)."""

    def __init__(self, cfg: JobConfig, inputs: Sequence[str], schema):
        from avenir_tpu.models.discriminant import FisherDiscriminant

        self.cfg = cfg
        self.inputs = list(inputs)
        self.schema = schema
        self.fd = FisherDiscriminant()
        self.rows = 0

    def consume(self, ds: Dataset) -> None:
        self.fd.accumulate(ds)
        self.rows += len(ds)

    # ----------------------------------------------- merge algebra ops
    def merge(self, other: "_FisherFold") -> "_FisherFold":
        """Shard-merge: per-class (count, sum, sum-sq) moments are
        additive (FisherDiscriminant.merge)."""
        self.fd.merge(other.fd)
        self.rows += other.rows
        return self

    def state_dict(self) -> Dict[str, object]:
        fd = self.fd
        meta = {"rows": self.rows,
                "ordinals": ([f.ordinal for f in fd.fields]
                             if fd._cnt is not None else None)}
        arrays: Dict[str, object] = {}
        if fd._cnt is not None:
            arrays = {"cnt": fd._cnt, "s1": fd._s1, "s2": fd._s2}
        return {"meta": np.array(json.dumps(meta)), **arrays}

    def load_state(self, state: Dict[str, object]) -> None:
        meta = json.loads(str(state["meta"]))
        self.rows = int(meta["rows"])
        if meta["ordinals"] is None:
            return                      # checkpoint taken before any chunk
        if self.schema is None:
            self.schema = _schema(self.cfg)
        fd = self.fd
        fd.fields = [f for f in self.schema.feature_fields if f.is_numeric]
        if [f.ordinal for f in fd.fields] != list(meta["ordinals"]):
            raise ValueError(
                "checkpointed discriminant field ordinals do not match "
                "the schema")
        fd._cnt = np.asarray(state["cnt"], np.float64)
        fd._s1 = np.asarray(state["s1"], np.float64)
        fd._s2 = np.asarray(state["s2"], np.float64)

    def finish(self, output: str) -> JobResult:
        if self.rows == 0:
            raise ValueError(f"fisherDiscriminant: empty input "
                             f"(no records in {self.inputs})")
        self.fd.finalize()
        out = _out_file(output)
        self.fd.save(out, delim=self.cfg.field_delim)
        return JobResult("fisherDiscriminant", {}, [out], self.fd)


class _MarkovPerClassFold:
    """markovStateTransitionModel (per-class mode) as a shared-scan sink
    over RAW BYTE BLOCKS: native CSR encode + fit_csr per block when the
    C encoder is built, line decode + fit otherwise. The per-entity mode
    (mst.id.field.ordinals) keeps its own scan — its open-vocabulary key
    extraction is not a fan-out fold."""

    def __init__(self, cfg: JobConfig, inputs: Sequence[str], schema=None):
        from avenir_tpu.models.markov import MarkovStateTransitionModel
        from avenir_tpu.native.ingest import native_seq_ready

        if cfg.get_int_list("id.field.ordinals") is not None:
            raise ValueError(
                "markovStateTransitionModel per-entity mode "
                "(id.field.ordinals) is not shared-scan fusable")
        self.cfg = cfg
        self.inputs = list(inputs)
        states = cfg.get_list("model.states") or cfg.assert_list("state.list")
        scale = cfg.get_int("trans.prob.scale", 1000)
        self.class_ord = cfg.get_int("class.label.field.ord")
        self.skip = cfg.get_int("skip.field.count", 1)
        self.class_labels = cfg.get_list("class.labels")
        self.model = MarkovStateTransitionModel(
            states, scale=scale, class_labels=self.class_labels)
        self.delim = cfg.field_delim_regex
        # one shared vocabulary: states first (codes 0..S-1), then any
        # class labels that are not themselves state names
        vocab = list(states)
        for lab in self.class_labels or []:
            if lab not in vocab:
                vocab.append(lab)
        self.vocab = vocab
        self._index = {t: i for i, t in enumerate(vocab)}
        self.label_codes = np.asarray([vocab.index(lab)
                                       for lab in self.class_labels or []])
        self.native = native_seq_ready(self.delim)
        self.rows = 0

    def consume_encoded(self, blk) -> None:
        """Fold one sidecar-replayed block (native.sidecar.
        SidecarBytesBlock): rebuild the CSR code array seq_encode_native
        would have produced — meta columns re-encoded from their token
        buffers, tail codes mapped through a sidecar-vocab -> state-vocab
        LUT (unknown tokens and the empty token both land on -1, exactly
        the cold encode's sentinels) — and feed fit_csr. No tokenizer,
        no parse span: this is the parse-free repeat path."""
        from avenir_tpu.native.ingest import csr_region_mask

        lens = blk.counts + blk.skip
        offsets = np.zeros(blk.n + 1, np.int64)
        np.cumsum(lens, out=offsets[1:])
        total = int(offsets[-1])
        codes = np.empty(total, np.int32)
        idx = self._index
        starts = offsets[:-1]
        for j in range(blk.skip):
            codes[starts + j] = [idx.get(t, -1) for t in blk.meta[j]]
        lut = np.full(blk.vocab_end + 1, -1, np.int32)
        for k in range(blk.vocab_end):
            lut[k + 1] = idx.get(blk.vocab[k], -1)
        if blk.skip:
            tail = csr_region_mask(offsets, blk.skip, total)
            codes[tail] = lut[blk.codes]
        else:
            codes[:] = lut[blk.codes]
        self.model.fit_csr(
            codes, offsets, skip=self.skip,
            class_ord=self.class_ord if self.class_labels else None,
            label_codes=self.label_codes)
        self.rows += blk.n

    def consume(self, data) -> None:
        if not isinstance(data, (bytes, bytearray)):
            self.consume_encoded(data)
        elif self.native:
            from avenir_tpu.native.ingest import seq_encode_native

            # cannot be None: availability + 1-byte delim pre-checked
            t0 = _obs.now()
            enc = seq_encode_native(data, self.delim, self.vocab)
            _obs.record("stream.parse", t0, sink="markov_csr",
                        nbytes=len(data))
            self.model.fit_csr(
                *enc, skip=self.skip,
                class_ord=self.class_ord if self.class_labels else None,
                label_codes=self.label_codes)
            self.rows += enc[1].shape[0] - 1
        else:
            t0 = _obs.now()
            lines = [ln.rstrip("\r")
                     for ln in data.decode("utf-8", "replace").split("\n")
                     if ln.strip()]
            _, seqs, labels = _parse_sequences(lines, self.delim, self.skip,
                                               self.class_ord)
            _obs.record("stream.parse", t0, sink="markov_lines",
                        nbytes=len(data))
            self.model.fit(seqs, labels if self.class_labels else None)
            self.rows += len(seqs)

    def finish(self, output: str) -> JobResult:
        out = _out_file(output)
        self.model.save(out, delim=self.cfg.field_delim)
        return JobResult("markovStateTransitionModel",
                         {"Basic:Records": self.rows}, [out], self.model)

    # ----------------------------------------------- merge algebra ops
    def merge(self, other: "_MarkovPerClassFold") -> "_MarkovPerClassFold":
        """Shard-merge: per-class bigram counts are additive
        (MarkovStateTransitionModel.merge)."""
        self.model.merge(other.model)
        self.rows += other.rows
        return self

    def state_dict(self) -> Dict[str, object]:
        meta = {"rows": self.rows, "states": self.model.states,
                "class_labels": self.model.class_labels}
        return {"meta": np.array(json.dumps(meta)),
                "counts": self.model.counts}

    def load_state(self, state: Dict[str, object]) -> None:
        meta = json.loads(str(state["meta"]))
        if meta["states"] != self.model.states \
                or meta["class_labels"] != self.model.class_labels:
            raise ValueError(
                "checkpointed markov states/class labels do not match "
                "the job config")
        arr = np.asarray(state["counts"], np.float64)
        if arr.shape != self.model.counts.shape:
            raise ValueError(
                f"checkpointed markov counts shape {arr.shape} does not "
                f"match {self.model.counts.shape}")
        self.model.counts = arr
        self.rows = int(meta["rows"])


def _cache_budget(cfg: JobConfig) -> int:
    """The encoded-block spill cache's on-disk byte budget
    (`stream.encoded.cache.budget.mb`, default generous — see
    native.ingest.DEFAULT_CACHE_BUDGET_BYTES). Exceeding it evicts whole
    least-recently-replayed sources; the job re-parses those and reports
    the eviction through Cache:EvictedBytes."""
    from avenir_tpu.native.ingest import DEFAULT_CACHE_BUDGET_BYTES

    return int(cfg.get_float("stream.encoded.cache.budget.mb",
                             DEFAULT_CACHE_BUDGET_BYTES / (1 << 20))
               * (1 << 20))


def _cache_counters(src) -> Dict[str, float]:
    """Spill-cache counters for a miner JobResult: on-disk spill bytes
    and what the byte budget evicted (0 in the healthy case — a nonzero
    value is the admission layer's signal that this corpus outgrew its
    cache budget)."""
    return {"Cache:SpillBytes": float(src.cache_nbytes),
            "Cache:EvictedBytes": float(src.cache_evicted_bytes)}


def _write_apriori_outputs(cfg: JobConfig, output: str, levels) -> List[str]:
    # the miners' artifact-write phase is their "finish": spanned here so
    # every miner path (solo job, fused fold sink, warm-source serve)
    # emits job.finish from one place
    t0 = _obs.now()
    outs = []
    os.makedirs(output or ".", exist_ok=True)
    for k, isl in enumerate(levels, start=1):
        p = os.path.join(output, f"itemsets-{k}.txt")
        isl.save(p, delim=cfg.field_delim)
        outs.append(p)
    _obs.record("job.finish", t0, job="frequentItemsApriori")
    return outs


def _write_gsp_outputs(cfg: JobConfig, output: str, levels) -> List[str]:
    t0 = _obs.now()
    os.makedirs(output or ".", exist_ok=True)
    outs = []
    delim = cfg.field_delim
    for k, seqs in sorted(levels.items()):
        p = os.path.join(output, f"sequences-{k}.txt")
        with open(p, "w") as fh:
            for cand, support in sorted(seqs.items()):
                fh.write(delim.join([*cand, f"{support:.6f}"]) + "\n")
        outs.append(p)
    _obs.record("job.finish", t0, job="candidateGenerationWithSelfJoin")
    return outs


def finish_miner_levels(canonical: str, cfg: JobConfig, levels,
                        n_rows: int, wall_s: float, output: str,
                        extra_counters: Optional[Dict[str, float]] = None
                        ) -> "JobResult":
    """Artifact write + counter assembly for a miner whose per-k levels
    were computed OUTSIDE a fold sink — the sharded per-k driver's
    finish: same writers and counter names as ``_MinerScanFold.finish``
    (and the warm-serve path), so a sharded miner's artifacts and
    result row are indistinguishable from the solo runner's."""
    if canonical == "frequentItemsApriori":
        counters = {"Apriori:MaxLength": len(levels),
                    **throughput_counters(n_rows, wall_s)}
        outs = _write_apriori_outputs(cfg, output, levels)
    else:
        counters = {"GSP:MaxLength": max(levels) if levels else 0,
                    **throughput_counters(n_rows, wall_s)}
        outs = _write_gsp_outputs(cfg, output, levels)
    counters.update(extra_counters or {})
    return JobResult(canonical, counters, outs, levels)


def _build_miner(canonical: str, cfg: JobConfig):
    """The miner object one prefixed conf describes — ONE constructor
    shared by the miner fold sink, the warm-serve path and the sharded
    per-k driver/worker, so a new mining knob cannot land in one of
    them and silently miss the others."""
    if canonical == "frequentItemsApriori":
        from avenir_tpu.models.association import FrequentItemsApriori

        return FrequentItemsApriori(
            support_threshold=cfg.assert_float("support.threshold"),
            max_length=cfg.get_int("item.set.length", 3),
            emit_trans_id=cfg.get_bool("emit.trans.id", False))
    if canonical == "candidateGenerationWithSelfJoin":
        from avenir_tpu.models.sequence import GSPMiner

        return GSPMiner(
            support_threshold=cfg.assert_float("support.threshold"),
            max_length=cfg.get_int("item.set.length", 3))
    raise ValueError(f"job {canonical!r} is not a multi-pass miner")


def _build_miner_source(canonical: str, cfg: JobConfig,
                        inputs: Sequence[str], spill: bool):
    """The streaming source a miner conf describes (the companion of
    :func:`_build_miner`): the association transaction reader or the
    GSP sequence reader, with the shared block/cache knobs applied."""
    block = int(cfg.get_float("stream.block.size.mb", 64.0) * (1 << 20))
    skip = cfg.get_int("skip.field.count", 1)
    if canonical == "frequentItemsApriori":
        from avenir_tpu.models.association import StreamingTransactionSource

        src = StreamingTransactionSource(
            list(inputs), delim=cfg.field_delim_regex,
            trans_id_ord=cfg.get_int("tans.id.ord", 0),
            skip_field_count=skip, marker=cfg.get("infreq.item.marker"),
            block_bytes=block, spill_cache=spill,
            cache_budget_bytes=_cache_budget(cfg))
    else:
        from avenir_tpu.models.sequence import StreamingSequenceSource

        src = StreamingSequenceSource(
            list(inputs), delim=cfg.field_delim_regex,
            skip_field_count=skip, block_bytes=block, spill_cache=spill,
            cache_budget_bytes=_cache_budget(cfg))
    _attach_sidecar_opts(src, cfg)
    return src


def _attach_sidecar_opts(src, cfg: JobConfig) -> None:
    """Point a miner source's own-read discovery scan at the cross-run
    columnar sidecar (SpillScanMixin._scan_all); a per-job
    `stream.sidecar=false` (or a load failure) leaves the attribute
    None and the scan cold."""
    try:
        from avenir_tpu.native import sidecar

        src.sidecar_opts = sidecar.opts_from_cfg(cfg)
    except Exception:
        pass


class _MinerScanFold:
    """A multi-pass miner's DISCOVERY pass as a shared-scan sink over raw
    byte blocks: pass 1 (vocabulary + k=1 supports) folds from the shared
    read — and spills the encoded-block cache — then finish() runs the
    remaining per-k rounds, which replay the cache instead of re-reading
    the corpus. Fusing markov + a miner's k=1 scan makes the whole
    multi-job, multi-pass flow cost ONE CSV read of the corpus."""

    def __init__(self, cfg: JobConfig, inputs: Sequence[str], job: str):
        self.cfg = cfg
        self.job = job
        self.t0 = time.perf_counter()
        self.miner = _build_miner(job, cfg)
        self.src = _build_miner_source(
            job, cfg, inputs, cfg.get_bool("stream.encoded.cache", True))
        self._sink = self.src.scan_consumer()
        self._sealed = False
        self._shards: List["_MinerScanFold"] = []
        # the job server's warm-state layer sets this (via run_shared's
        # fold_hook) to ADOPT the still-open source — and its committed
        # encoded-block cache — after finish(), so a repeat mining
        # request replays encoded blocks instead of re-parsing CSV
        self.keep_sources = False

    def consume(self, data: bytes) -> None:
        self._sink.consume(data)

    def _seal(self) -> None:
        """Finish the pass-1 scan exactly once (commits the spill cache;
        idempotent so merge() and finish() compose in any order)."""
        if not self._sealed:
            self._sink.finish()
            self._sealed = True

    def _n_rows(self) -> int:
        return (self.src.n_trans if self.job == "frequentItemsApriori"
                else self.src.n_rows)

    def finish(self, output: str) -> JobResult:
        self._seal()
        srcs = [self.src] + [f.src for f in self._shards]
        levels = (self.miner.mine_stream(self.src) if len(srcs) == 1
                  else self.miner.mine_stream_merged(srcs))
        n_rows = self._n_rows() + sum(f._n_rows() for f in self._shards)
        if self.job == "frequentItemsApriori":
            counters = {"Apriori:MaxLength": len(levels),
                        **throughput_counters(
                            n_rows, time.perf_counter() - self.t0),
                        **_cache_counters(self.src)}
            outs = _write_apriori_outputs(self.cfg, output, levels)
        else:
            counters = {"GSP:MaxLength": max(levels) if levels else 0,
                        **throughput_counters(
                            n_rows, time.perf_counter() - self.t0),
                        **_cache_counters(self.src)}
            outs = _write_gsp_outputs(self.cfg, output, levels)
        if not self.keep_sources:
            for src in srcs:
                src.close()
        return JobResult(self.job, counters, outs, levels)

    # ----------------------------------------------- merge algebra ops
    def merge(self, other: "_MinerScanFold") -> "_MinerScanFold":
        """Shard-merge: seal both shards' pass-1 scans and keep the
        shard sources side by side; finish() then drives the miner's
        sharded per-k driver (mine_stream_merged), which counts every
        candidate per shard through the one _stream_support fold and
        sums supports via the registered support-merge
        (models.association.merge_support_counts)."""
        if other.job != self.job:
            raise ValueError(
                f"cannot merge {other.job!r} fold into {self.job!r}")
        self._seal()
        other._seal()
        self._shards.append(other)
        self._shards.extend(other._shards)
        other._shards = []
        return self

    def state_dict(self) -> Dict[str, object]:
        if self._shards:
            raise ValueError(
                "checkpoint a miner fold before merging shards into it")
        src = self.src
        meta = {"job": self.job, "vocab": list(src.vocab),
                "n": self._n_rows(), "sealed": self._sealed,
                "t_max": getattr(src, "t_max", None)}
        return {"meta": np.array(json.dumps(meta)),
                "counts": np.asarray(src._scan_counts, np.int64)}

    def load_state(self, state: Dict[str, object]) -> None:
        meta = json.loads(str(state["meta"]))
        if meta["job"] != self.job:
            raise ValueError(
                f"checkpointed {meta['job']!r} state for a {self.job!r} "
                f"fold")
        src = self.src
        src.restore_scan_state(meta["vocab"], state["counts"])
        if self.job == "frequentItemsApriori":
            src.n_trans = int(meta["n"])
        else:
            src.n_rows = int(meta["n"])
            src.t_max = max(int(meta["t_max"] or 1), 1)
        if meta["sealed"]:
            self._sink.finish()
            self._sealed = True


def _apriori_fold(cfg, inputs, schema=None):
    return _MinerScanFold(cfg, inputs, "frequentItemsApriori")


def _gsp_fold(cfg, inputs, schema=None):
    return _MinerScanFold(cfg, inputs, "candidateGenerationWithSelfJoin")


def _merge_folds(a, b):
    """Default merge_states op: every registered fold sink implements
    the in-place additive merge contract."""
    return a.merge(b)


@dataclass(frozen=True)
class StreamFoldOps:
    """One streamed job's fold-sink registration: the scan kind, the
    sink factory, and the MERGE ALGEBRA ops that make its carry a
    mergeable, serializable fold state —
    ``merge_states(fold(A), fold(B)).finish() == fold(A++B).finish()``
    byte-identically, and ``restore_state(serialize_state(fold))``
    resumes a mid-scan carry to the same bytes. graftlint --merge
    (analysis/merge.py) proves both properties mechanically every
    round; the multi-host NB merge (tests/test_multihost.py) and the
    incremental/resumable-scan work build on the same ops.

    ``kind``: "dataset" folds consume schema-parsed Dataset chunks;
    "bytes" folds consume raw byte blocks (sequence-shaped corpora).
    ``factory(cfg, inputs, schema)`` builds the sink; ``merge_states``
    folds one sink's carry into another (default: ``a.merge(b)``)."""

    kind: str
    factory: Callable
    merge_states: Callable = _merge_folds

    def serialize_state(self, fold) -> bytes:
        """Checkpoint a fold's carry: an npz of the fold's
        ``state_dict()`` — numpy arrays plus one JSON ``meta`` entry,
        no pickle (a checkpoint must be loadable by a DIFFERENT process
        with no trust in the writer)."""
        buf = io.BytesIO()
        np.savez(buf, **fold.state_dict())
        return buf.getvalue()

    def restore_state(self, cfg: JobConfig, inputs: Sequence[str],
                      blob: bytes, schema=None):
        """Rebuild a fold sink from a checkpoint: a FRESH factory sink
        (same config surface a resumed process would construct) with
        the serialized carry loaded into it, ready to consume the
        remaining chunks."""
        if schema is None and self.kind == "dataset":
            schema = _schema(cfg)
        fold = self.factory(cfg, list(inputs), schema)
        with np.load(io.BytesIO(blob), allow_pickle=False) as z:
            state = {k: z[k] for k in z.files}
        fold.load_state(state)
        return fold


#: canonical job name -> StreamFoldOps (see the dataclass above)
_STREAM_FOLDS: Dict[str, StreamFoldOps] = {
    "bayesianDistr": StreamFoldOps("dataset", _NBDistrFold),
    "mutualInformation": StreamFoldOps("dataset", _MutualInfoFold),
    "fisherDiscriminant": StreamFoldOps("dataset", _FisherFold),
    "markovStateTransitionModel": StreamFoldOps("bytes",
                                                _MarkovPerClassFold),
    "frequentItemsApriori": StreamFoldOps("bytes", _apriori_fold),
    "candidateGenerationWithSelfJoin": StreamFoldOps("bytes", _gsp_fold),
}


def stream_fold_names() -> List[str]:
    """Jobs the scan-sharing executor can fuse."""
    return sorted(_STREAM_FOLDS)


def stream_fold_ops(job: str) -> StreamFoldOps:
    """The registered fold-sink ops of a streamed job (accepts
    aliases) — the public handle the merge auditor, the multi-host
    merge path and the incremental delta-scan driver
    (:func:`run_incremental`) all share."""
    canonical = _REGISTRY[job][0] if job in _REGISTRY else job
    if canonical not in _STREAM_FOLDS:
        raise KeyError(
            f"job {job!r} has no registered stream fold; streamed jobs: "
            f"{', '.join(stream_fold_names())}")
    return _STREAM_FOLDS[canonical]


def run_shared(specs: Sequence[Tuple[str, object, str]],
               inputs: Sequence[str],
               fold_hook: Optional[Callable] = None) -> Dict[str, JobResult]:
    """Run N registered jobs over the SAME inputs with ONE scan.

    `specs` is a sequence of (job name, conf, output path); every job
    must be shared-scan capable (stream_fold_names()) and they must
    agree on scan kind, stream block size and (for Dataset folds) the
    schema file + delimiter — one read, one parse, N folds. Each job
    still reads its own prefixed config and writes its own outputs;
    results come back keyed by canonical job name, byte-identical to
    running the jobs one scan each (the existing run_job path stays as
    the fallback and as the equivalence oracle).

    `fold_hook(canonical, fold)`, when given, is called with each fold
    sink right after construction — the job server's warm-state tap
    (e.g. setting a miner fold's ``keep_sources`` so the server can pin
    its encoded-block cache after the run). Purely observational: it
    must not consume chunks."""
    from avenir_tpu.core.schema import FeatureSchema as _FS
    from avenir_tpu.core.stream import (SharedScan, stream_job_byte_blocks,
                                        stream_job_inputs)

    if not specs:
        return {}
    built = []
    for name, conf, output in specs:
        canonical, _prefix, cfg = _job_cfg(name, conf)
        if canonical not in _STREAM_FOLDS:
            raise ValueError(
                f"job {name!r} is not shared-scan capable; fusable jobs: "
                f"{', '.join(stream_fold_names())}")
        ops = _STREAM_FOLDS[canonical]
        kind, factory = ops.kind, ops.factory
        if any(canonical == b[0] for b in built):
            raise ValueError(
                f"job {canonical!r} appears twice in one shared scan")
        built.append((canonical, kind, cfg, factory, output))
    # autotune overlay BEFORE the compatibility checks: one knob set
    # (the fused group's profile) lands on every member config, so the
    # block-size/delimiter agreement below judges the tuned values
    session = _autotune_begin([b[0] for b in built],
                              [b[2] for b in built], inputs)
    rss0 = _rss_now()
    try:
        kinds = {k for _, k, _, _, _ in built}
        if len(kinds) != 1:
            raise ValueError(
                f"cannot fuse jobs of mixed scan kinds {kinds}")
        kind = kinds.pop()
        blocks = {cfg.get_float("stream.block.size.mb", 64.0)
                  for _, _, cfg, _, _ in built}
        if len(blocks) != 1:
            raise ValueError(
                f"fused jobs disagree on stream.block.size.mb: {blocks}")
        delims = {cfg.field_delim_regex for _, _, cfg, _, _ in built}
        if len(delims) != 1:
            raise ValueError(
                f"fused jobs disagree on field delimiter: {delims}")
        cfg0 = built[0][2]
        schema = None
        if kind == "dataset":
            spaths = {cfg.assert_get("feature.schema.file.path")
                      for _, _, cfg, _, _ in built}
            if len(spaths) != 1:
                raise ValueError(
                    f"fused jobs disagree on the schema file: {spaths}")
            schema = _FS.from_file(spaths.pop())
            chunks = stream_job_inputs(cfg0, list(inputs), schema)
        else:
            # bytes-kind folds all dispatch on SidecarBytesBlock, so the
            # shared feed opts into the bytes sidecar when the fused
            # configs agree on the meta skip count (they must: the
            # packed format is skip-specific); disagreement keeps the
            # raw feed
            skips = {cfg.get_int("skip.field.count", 1)
                     for _, _, cfg, _, _ in built}
            chunks = stream_job_byte_blocks(
                cfg0, list(inputs),
                sidecar_skip=skips.pop() if len(skips) == 1 else None)
        sc0 = _sidecar_counters()
        scan = SharedScan(chunks)
        folds = []
        for canonical, _kind, cfg, factory, output in built:
            fold = factory(cfg, list(inputs), schema)
            if fold_hook is not None:
                fold_hook(canonical, fold)
            folds.append((canonical, fold, output))
            scan.add_sink(fold, label=canonical)
        t0 = _obs.now()
        chunks_scanned = scan.run()
        _obs.record("job.dispatch", t0, mode="shared",
                    chunks=chunks_scanned,
                    jobs=",".join(c for c, _f, _o in folds))
        results: Dict[str, JobResult] = {}
        for canonical, fold, output in folds:
            if output:
                parent = os.path.dirname(os.path.abspath(output))
                os.makedirs(parent, exist_ok=True)
            results[canonical] = _finish_fold(fold, output, canonical)
            _note_sidecar_counters(canonical, results[canonical], sc0)
            _add_mem_counters(canonical, next(
                cfg for c, _k, cfg, _f, _o in built if c == canonical),
                inputs, results[canonical], rss0=rss0)
    except BaseException:
        if session is not None:
            session.close()   # a leaked session would contaminate
        raise                 # every later one in this process
    if session is not None:
        session.finish(results)
    return results


def run_warm_miner(name: str, conf, inputs: Sequence[str], output: str,
                   src) -> JobResult:
    """Serve a multi-pass miner from a WARM, already-scanned streaming
    source: pass 1 is already folded (``scan_items``/``scan`` memoize
    the discovery counts) and every per-k pass replays the source's
    committed encoded-block cache, so an unchanged corpus serves with
    ZERO CSV parses — the job server's pinned-cache fast path.

    The caller owns ``src`` and its validity (the server checks the
    cache's per-block content gate, ``SpillScanMixin.cache_ready``,
    before routing here); this function never closes it. Mining
    parameters come from the REQUEST's conf — pass 1 does not depend on
    them, so one warm source serves any thresholds. Output files are
    byte-identical to the cold runner path: same miner, same per-k
    device folds, same writers (the warm path only skips re-deriving
    state the source already memoizes); throughput counters price the
    mining wall time alone, which is the point."""
    canonical, _prefix, cfg = _job_cfg(name, conf)
    if canonical not in ("frequentItemsApriori",
                         "candidateGenerationWithSelfJoin"):
        raise ValueError(
            f"job {name!r} has no warm-source path; warm-servable jobs: "
            f"frequentItemsApriori, candidateGenerationWithSelfJoin")
    t0 = time.perf_counter()
    miner = _build_miner(canonical, cfg)
    levels = miner.mine_stream(src)
    n_rows = (src.n_trans if canonical == "frequentItemsApriori"
              else src.n_rows)
    res = finish_miner_levels(canonical, cfg, levels, n_rows,
                              time.perf_counter() - t0, output,
                              extra_counters=_cache_counters(src))
    _add_mem_counters(canonical, cfg, inputs, res)
    return res


# ====================================================== incremental driver
def _incremental_state_dir(cfg: JobConfig, canonical: str,
                           inputs: Sequence[str]) -> str:
    """Where a job's delta-scan state (block fingerprints + fold-carry
    checkpoints) lives across runs: `stream.incremental.state.dir` when
    configured, else a `.avenir_incremental/<job>_<corpus digest>`
    directory next to the first input — deterministic per (job, input
    set), so a rerun of the same job over the same corpus finds its own
    state and two jobs over one corpus never collide."""
    from avenir_tpu.core import keys as _keys

    explicit = cfg.get("stream.incremental.state.dir")
    if explicit:
        return explicit
    digest = _keys.state_digest(canonical, inputs)
    base = os.path.dirname(os.path.abspath(inputs[0]))
    return os.path.join(base, ".avenir_incremental",
                        f"{canonical}_{digest}")


def _conf_digest(cfg: JobConfig) -> str:
    """Content digest of the configuration a checkpoint's carry was
    folded under — the canonical recipe lives in
    :func:`avenir_tpu.core.keys.conf_digest` (view-neutral keys are
    declared in ``core.keys.VIEW_NEUTRAL_KEYS``, verified by
    ``graftlint --keys``); this name survives for its importers."""
    from avenir_tpu.core import keys as _keys

    return _keys.conf_digest(cfg)


class _IncrementalPlan:
    """One job's restore plan + delta-fold state — the per-job half of
    an incremental run, shared by the solo driver (:func:`run_incremental`)
    and the fused one (:func:`run_incremental_shared`) so the two can
    never disagree on restore gating or checkpoint layout."""

    def __init__(self, canonical: str, cfg: JobConfig, ops: StreamFoldOps,
                 inputs: List[str], output: str, schema, store,
                 conf_digest: str):
        self.canonical = canonical
        self.cfg = cfg
        self.ops = ops
        self.inputs = inputs
        self.abs_inputs = [os.path.abspath(p) for p in inputs]
        self.output = output
        self.schema = schema
        self.store = store
        self.conf_digest = conf_digest
        self.block = int(cfg.get_float("stream.block.size.mb", 64.0)
                         * (1 << 20))
        self.interval = int(
            cfg.get_float("stream.checkpoint.interval.mb", 256.0)
            * (1 << 20))
        self.delim = cfg.field_delim_regex
        self.fold = None
        self.watermarks = [0] * len(inputs)
        self.fps: List[list] = [[] for _ in inputs]
        self.hit_blocks = 0
        self.skipped = 0
        self.seq = 0
        self.delta_blocks = 0
        self.since_ckpt = 0
        self.predicted: Optional[int] = None
        self.rss0 = _rss_now()


def _prepare_incremental(canonical: str, cfg: JobConfig, inputs: List[str],
                         output: str, state_dir: Optional[str],
                         schema=None) -> _IncrementalPlan:
    """Build one job's restore plan: load the newest checkpoint, verify
    its recorded fingerprints against the current files, and restore
    the carry when — and only when — the covered prefix still content-
    matches; anything else (torn/truncated checkpoint, in-place edit,
    changed job/conf/inputs, mid-line watermark on a grown file,
    unloadable carry) leaves a fresh cold fold. `schema` lets the fused
    driver hand every plan ONE schema object (the run_shared contract);
    the solo driver loads the job's own."""
    from avenir_tpu.core import incremental as incr

    ops = stream_fold_ops(canonical)
    if schema is None and ops.kind == "dataset":
        schema = _schema(cfg)
    conf_digest = _conf_digest(cfg)
    store = incr.CheckpointStore(
        state_dir or _incremental_state_dir(cfg, canonical, inputs))
    plan = _IncrementalPlan(canonical, cfg, ops, inputs, output, schema,
                            store, conf_digest)

    t_restore = _obs.now()
    loaded = store.load()
    if loaded is not None:
        meta, blob = loaded
        plan.seq = int(meta.get("seq", 0))
        old_inputs = [str(p) for p in meta.get("inputs", [])]
        # the recorded input list must be a PREFIX of the current one
        # (append-only at the corpus level too: new source files fold
        # wholly, like appended bytes); any other change — including a
        # conf or schema-content change, which would parse the delta
        # under a different view than the restored prefix — is a cold
        # scan
        usable = (meta.get("format") == 1
                  and meta.get("format_version", 1) == 1
                  and meta.get("job") == canonical
                  and meta.get("conf_digest") == conf_digest
                  and old_inputs == plan.abs_inputs[:len(old_inputs)])
        fold = None
        if usable:
            wm, kept = [], []
            for path, src_fps in zip(inputs, meta.get("fingerprints", [])):
                n, covered = incr.verified_prefix(path, src_fps)
                if n != len(src_fps):
                    usable = False      # stale: an in-place edit — cold
                    break
                if covered < os.path.getsize(path) \
                        and not incr.ends_at_newline(path, covered):
                    # the corpus' last line had no terminator, so the
                    # appended bytes EXTEND the already-folded row —
                    # resuming would skip its continuation: cold scan
                    usable = False
                    break
                wm.append(covered)
                kept.append(list(src_fps))
            if usable:
                try:
                    fold = ops.restore_state(cfg, inputs, blob,
                                             schema=schema)
                except Exception:
                    fold = None         # unloadable carry: cold scan
            if fold is not None:
                plan.fold = fold
                plan.watermarks[:len(wm)] = wm
                plan.fps[:len(kept)] = kept
                plan.hit_blocks = sum(len(x) for x in kept)
                plan.skipped = sum(wm)
    restored = plan.fold is not None
    if plan.fold is None:
        plan.watermarks = [0] * len(inputs)
        plan.fps = [[] for _ in inputs]
        plan.hit_blocks = 0
        plan.skipped = 0
        plan.fold = ops.factory(cfg, inputs, schema)
    _obs.record("job.restore", t_restore, job=canonical,
                restored=restored, skipped_bytes=plan.skipped)

    # the checkpoint footprint is priced against the graftlint-mem
    # analytic model (advisory: the oracle the job-server admission
    # layer consumes; a failure to predict never fails the scan)
    try:
        from avenir_tpu.analysis.mem import corpus_stats, footprint_model
        from avenir_tpu.core.stream import prefetch_depth

        stats = corpus_stats([p for p in inputs if os.path.exists(p)],
                             delim=plan.delim)
        plan.predicted = int(footprint_model(
            canonical, plan.block, schema, stats,
            prefetch_depth=prefetch_depth(cfg)).total_bytes)
    except Exception:
        pass
    return plan


def _plan_checkpoint(plan: _IncrementalPlan, complete: bool) -> None:
    """Commit one atomic checkpoint of a plan's carry + fingerprints."""
    from avenir_tpu.core import incremental as incr

    t0 = _obs.now()
    plan.seq += 1
    blob = plan.ops.serialize_state(plan.fold)
    meta = {"format": 1, "format_version": 1,
            "job": plan.canonical, "seq": plan.seq,
            "conf_digest": plan.conf_digest,
            "inputs": plan.abs_inputs, "block_bytes": plan.block,
            "watermarks": list(plan.watermarks),
            "fingerprints": plan.fps,
            "complete": complete,
            "predicted_peak_bytes": plan.predicted}
    saved = plan.store.save(meta, blob)
    _obs.record("job.checkpoint", t0, job=plan.canonical, seq=plan.seq,
                complete=complete, nbytes=len(blob))
    hook = incr._checkpoint_hook
    if hook is not None:
        hook(saved)


def _plan_finish(plan: _IncrementalPlan,
                 checkpoint: bool = True) -> JobResult:
    """Final (complete) checkpoint — written BEFORE finish() so the
    carry never reflects a finished/sealed fold — then the artifact and
    the delta-accounting counters. ``checkpoint=False`` (the sharded
    refresh's missing-worker-fingerprints fallback) emits the artifact
    without touching the store: the PREVIOUS checkpoint stays the
    newest — its carry and fingerprints are still mutually consistent,
    whereas stamping this carry with partial fingerprints would make
    the next refresh re-fold bytes the carry already covers."""
    if checkpoint:
        _plan_checkpoint(plan, complete=True)
    if plan.output:
        parent = os.path.dirname(os.path.abspath(plan.output))
        os.makedirs(parent, exist_ok=True)
    res = _finish_fold(plan.fold, plan.output, plan.canonical)
    res.counters["Cache:HitBlocks"] = float(plan.hit_blocks)
    res.counters["Cache:DeltaBlocks"] = float(plan.delta_blocks)
    res.counters["Resume:SkippedBytes"] = float(plan.skipped)
    if plan.predicted is not None:
        res.counters["Mem:PredictedPeakBytes"] = float(plan.predicted)
    _add_mem_counters(plan.canonical, plan.cfg, plan.inputs, res,
                      rss0=plan.rss0)
    return res


def _cold_delta_feed(plan: _IncrementalPlan, path: str, start: int,
                     size: int):
    """The historical delta loop body as a (offset, length, hash,
    payload) tuple feed: raw blocks of [start, size), blanks as payload
    None, dataset-kind blocks parsed under the stream.parse span."""
    from avenir_tpu.core import incremental as incr
    from avenir_tpu.core.stream import (is_blank_block, iter_byte_blocks,
                                        prefetched)

    feed = prefetched(iter_byte_blocks(path, plan.block,
                                       byte_range=(start, size),
                                       with_offsets=True), depth=1)
    try:
        for off, data in feed:
            fp = incr.block_fingerprint(off, data)
            if is_blank_block(data):
                yield off, len(data), fp["hash"], None
                continue
            if plan.ops.kind == "dataset":
                t0 = _obs.now()
                payload = Dataset.from_csv(data, plan.schema,
                                           delim=plan.delim)
                _obs.record("stream.parse", t0, path=path,
                            nbytes=len(data), rows=len(payload))
            else:
                payload = data
            yield off, len(data), fp["hash"], payload
    finally:
        feed.close()


def _delta_feed(plan: _IncrementalPlan, path: str, start: int, size: int):
    """One source's delta range as a tuple feed, preferring the columnar
    sidecar: a refresh whose delta bytes were already packed (by a
    plain run, or by the previous refresh's extension) replays them
    parse-free, the genuinely new tail parses cold AND extends the
    sidecar. Any doubt — no manifest, boundary mismatch with the
    checkpoint watermark, content drift — falls back to the cold loop,
    byte-identically."""
    feed = None
    try:
        from avenir_tpu.native import sidecar

        opts = sidecar.opts_from_cfg(plan.cfg)
        if plan.ops.kind == "dataset":
            feed = sidecar.dataset_blocks(
                opts, path, plan.schema, plan.delim, plan.block,
                byte_range=(start, size))
        else:
            feed = sidecar.byte_blocks(
                opts, path, plan.delim,
                plan.cfg.get_int("skip.field.count", 1), plan.block,
                byte_range=(start, size))
    except Exception:
        feed = None
    return feed if feed is not None \
        else _cold_delta_feed(plan, path, start, size)


def run_incremental(name: str, conf, inputs: Sequence[str],
                    output: str = "",
                    state_dir: Optional[str] = None) -> JobResult:
    """Run a streamed job INCREMENTALLY: restore the last serialized
    fold carry, fold only the byte blocks past its watermark, and
    re-emit the artifact — O(delta) instead of O(corpus) for an
    append-mostly corpus, byte-identical to a cold full scan by the
    proven fold-state merge algebra (graftlint --merge re-proves it
    every round).

    Mechanism: a per-(job, corpus) CheckpointStore
    (core.incremental, see `state_dir` / the
    `stream.incremental.state.dir` key) holds the newest carry
    (StreamFoldOps.serialize_state npz) plus the content fingerprints
    (offset + length + hash) of every block it covers. On entry the
    recorded fingerprints are re-verified against the current files:
    a verified prefix restores the carry and skips its bytes; anything
    else — a torn/truncated checkpoint, an in-place edit, a different
    input list — falls back to a cold scan (never to a wrong artifact).
    While scanning, the carry is re-checkpointed every
    `stream.checkpoint.interval.mb` (atomic write; a torn checkpoint
    never commits), so a killed scan resumes mid-corpus from its last
    watermark instead of byte 0. The final checkpoint (complete=True)
    is what the next append-refresh restores.

    The result carries the delta accounting next to the usual stream
    counters: Cache:HitBlocks (restored, fingerprint-verified blocks),
    Cache:DeltaBlocks (blocks folded this run) and Resume:SkippedBytes
    (bytes the restored carry covered)."""
    canonical, _prefix, cfg = _job_cfg(name, conf)
    inputs = [str(p) for p in inputs]
    # autotune overlay BEFORE the restore plan: the knobs land in the
    # conf digest, so a knob CHANGE re-scans cold (the documented
    # conservative gate for any conf change) and the next refresh under
    # the same knobs restores warm. This is also the only path that
    # emits job.checkpoint spans — the checkpoint-interval rule's
    # signal lives here.
    session = _autotune_begin([canonical], [cfg], inputs)
    try:
        plan = _prepare_incremental(canonical, cfg, inputs, output,
                                    state_dir)
        sc0 = _sidecar_counters()

        # --------------------------------------------------- delta fold
        for si, path in enumerate(inputs):
            size = os.path.getsize(path)
            start = plan.watermarks[si]
            if start >= size:
                continue
            feed = _delta_feed(plan, path, start, size)
            try:
                for off, length, fp_hash, payload in feed:
                    if payload is not None:
                        t0 = _obs.now()
                        plan.fold.consume(payload)
                        _obs.record("stream.fold", t0,
                                    sink=plan.canonical)
                    plan.fps[si].append({"offset": int(off),
                                         "length": int(length),
                                         "hash": fp_hash})
                    plan.watermarks[si] = off + length
                    plan.delta_blocks += 1
                    plan.since_ckpt += length
                    if plan.since_ckpt >= plan.interval:
                        _plan_checkpoint(plan, complete=False)
                        plan.since_ckpt = 0
            finally:
                feed.close()
        res = _plan_finish(plan)
        _note_sidecar_counters(canonical, res, sc0)
    except BaseException:
        if session is not None:
            session.close()   # a leaked session would contaminate
        raise                 # every later one in this process
    if session is not None:
        session.finish({canonical: res})
    return res


def run_incremental_shared(specs: Sequence[Tuple[str, object, str]],
                           inputs: Sequence[str],
                           state_dirs: Optional[Dict[str, str]] = None
                           ) -> Dict[str, JobResult]:
    """Refresh N streamed jobs over the SAME appended corpus with ONE
    delta scan: each job restores its own checkpointed carry
    (:func:`_prepare_incremental`, the exact solo restore gate), and
    jobs whose verified watermarks agree fold the appended blocks
    through one ``SharedScan`` pass — N refreshes, one disk read + one
    parse of the delta. Jobs whose watermarks differ (one was seeded at
    a different corpus size, one fell back to a cold scan) group
    separately and still run, so fusion is an optimization, never a
    correctness gate. Results are byte-identical to running
    :func:`run_incremental` per job — the merge auditor's
    fused-incremental leg re-proves this every round.

    `specs` is (job name, conf, output) like :func:`run_shared`, with
    the same compatibility contract (one scan kind, one block size, one
    delimiter, one schema file); `state_dirs` optionally maps canonical
    job names to checkpoint dirs (the job server's managed store) —
    unmapped jobs use their per-(job, corpus) default."""
    from avenir_tpu.core.stream import SharedScan

    if not specs:
        return {}
    inputs = [str(p) for p in inputs]
    built = []
    for name, conf, output in specs:
        canonical, _prefix, cfg = _job_cfg(name, conf)
        ops = stream_fold_ops(canonical)
        if any(canonical == b[0] for b in built):
            raise ValueError(
                f"job {canonical!r} appears twice in one shared refresh")
        built.append((canonical, cfg, ops, output))
    kinds = {ops.kind for _c, _cfg, ops, _o in built}
    if len(kinds) != 1:
        raise ValueError(f"cannot fuse refreshes of mixed scan kinds "
                         f"{kinds}")
    kind = kinds.pop()
    blocks = {cfg.get_float("stream.block.size.mb", 64.0)
              for _c, cfg, _o2, _o in built}
    if len(blocks) != 1:
        raise ValueError(
            f"fused refreshes disagree on stream.block.size.mb: {blocks}")
    delims = {cfg.field_delim_regex for _c, cfg, _o2, _o in built}
    if len(delims) != 1:
        raise ValueError(
            f"fused refreshes disagree on field delimiter: {delims}")
    delim = delims.pop()
    schema = None
    if kind == "dataset":
        spaths = {cfg.assert_get("feature.schema.file.path")
                  for _c, cfg, _o2, _o in built}
        if len(spaths) != 1:
            raise ValueError(
                f"fused refreshes disagree on the schema file: {spaths}")
        schema = FeatureSchema.from_file(spaths.pop())

    plans = []
    for canonical, cfg, ops, output in built:
        sd = (state_dirs or {}).get(canonical)
        plans.append(_prepare_incremental(canonical, cfg, inputs, output,
                                          sd, schema=schema))
    block = plans[0].block

    # one SharedScan per watermark group: every plan restored to the
    # same coverage folds the same delta blocks from one read + parse
    groups: Dict[tuple, List[_IncrementalPlan]] = {}
    for plan in plans:
        groups.setdefault(tuple(plan.watermarks), []).append(plan)

    def delta_feed(group: List[_IncrementalPlan]):
        """(source index, offset, length, hash, parsed-once payload)
        past the group's common watermark; payload is None for blank
        blocks (folds skip them, fingerprints still cover them). Routes
        through the columnar sidecar (_delta_feed) unless the group's
        bytes-kind configs disagree on the meta skip count the packed
        format is keyed to."""
        sidecar_ok = kind == "dataset" or len(
            {p.cfg.get_int("skip.field.count", 1) for p in group}) == 1
        for si, path in enumerate(inputs):
            size = os.path.getsize(path)
            start = group[0].watermarks[si]
            if start >= size:
                continue
            feed = (_delta_feed(group[0], path, start, size)
                    if sidecar_ok
                    else _cold_delta_feed(group[0], path, start, size))
            try:
                for off, length, fp_hash, payload in feed:
                    yield si, off, length, fp_hash, payload
            finally:
                feed.close()

    def fold_sink(plan: _IncrementalPlan):
        def consume(item) -> None:
            payload = item[4]
            if payload is not None:
                plan.fold.consume(payload)
        return consume

    def bookkeeper(group: List[_IncrementalPlan]):
        # runs AFTER the folds (sink order), so an interval checkpoint
        # serializes carries that already folded the current block —
        # the solo driver's exact ordering
        def consume(item) -> None:
            si, off, length, fp_hash, _payload = item
            for plan in group:
                plan.fps[si].append({"offset": int(off),
                                     "length": int(length),
                                     "hash": fp_hash})
                plan.watermarks[si] = off + length
                plan.delta_blocks += 1
                plan.since_ckpt += length
                if plan.since_ckpt >= plan.interval:
                    _plan_checkpoint(plan, complete=False)
                    plan.since_ckpt = 0
        return consume

    sc0 = _sidecar_counters()
    for group in groups.values():
        scan = SharedScan(delta_feed(group))
        for plan in group:
            scan.add_sink(fold_sink(plan), label=plan.canonical)
        scan.add_sink(bookkeeper(group), label="bookkeeper")
        t0 = _obs.now()
        chunks_scanned = scan.run()
        _obs.record("job.dispatch", t0, mode="incremental_shared",
                    chunks=chunks_scanned,
                    jobs=",".join(p.canonical for p in group))

    results: Dict[str, JobResult] = {}
    for plan in plans:
        res = _plan_finish(plan)
        _note_sidecar_counters(plan.canonical, res, sc0)
        results[plan.canonical] = res
    return results


# =================================================================== bayesian
@job("bayesianDistr", "bad", "org.avenir.bayesian.BayesianDistribution")
def bayesian_distribution(cfg: JobConfig, inputs: List[str], output: str) -> JobResult:
    """NB sufficient-stats training -> CSV model file (SURVEY §3.1).
    `bad.tabular.input=false` switches to the free-text mode: rows are
    `text,classVal`, each token contributes a (classVal, token) count
    (BayesianDistribution.mapText, :186-195)."""
    out = _out_file(output)
    if not cfg.get_bool("tabular.input", True):
        from avenir_tpu.models.text import TextNaiveBayes

        # token counts fold per streamed line block: the free-text mode
        # streams like the tabular one (mapText's per-line contract)
        from avenir_tpu.core.stream import iter_line_blocks, prefetched

        tmodel = TextNaiveBayes()
        rows = 0
        block = int(cfg.get_float("stream.block.size.mb", 64.0) * (1 << 20))
        for path in inputs:
            lineno = 0
            for lines in prefetched(iter_line_blocks(path, block)):
                texts, labels = [], []
                for ln in lines:
                    lineno += 1
                    text, sep, cls = ln.rpartition(cfg.field_delim_regex)
                    if not sep:
                        raise ValueError(
                            f"{path}:{lineno}: text-mode row has no "
                            f"{cfg.field_delim_regex!r} delimiter "
                            f"(want text,classVal)")
                    texts.append(text)
                    labels.append(cls.strip())
                tmodel.accumulate(texts, labels)
                rows += len(texts)
        tmodel.finish()
        tmodel.save(out, delim=cfg.field_delim)
        return JobResult("bayesianDistr",
                         {"Distribution Data:Records": rows},
                         [out], tmodel)

    from avenir_tpu.core.stream import stream_job_inputs

    # block streaming keeps host RSS O(block) however large the input —
    # the mapper's one-line-at-a-time contract at block granularity
    # (BayesianDistribution.java:137); counts are additive so chunking
    # cannot change the model. The fold sink IS the shared-scan sink
    # (_NBDistrFold): one-job-one-scan is the single-sink special case,
    # driven through SharedScan so the per-chunk fold spans come from
    # the same instrumentation point as the fused path.
    schema = _schema(cfg)
    fold = _NBDistrFold(cfg, inputs, schema)
    _drive_fold(fold, stream_job_inputs(cfg, inputs, schema),
                "bayesianDistr")
    return _finish_fold(fold, output, "bayesianDistr")


@job("bayesianPredictor", "bap", "org.avenir.bayesian.BayesianPredictor")
def bayesian_predictor(cfg: JobConfig, inputs: List[str], output: str) -> JobResult:
    """Map-only NB posterior prediction (SURVEY §3.2). With
    `bap.output.feature.prob.only=true` emits per-row feature posterior
    P(features|actual class) — the quantity the KNN class-conditional
    pipeline joins in (BayesianPredictor.java:262-286)."""
    from avenir_tpu.models.naive_bayes import NaiveBayesModel, NaiveBayesPredictor

    from avenir_tpu.utils.metrics import CostBasedArbitrator

    schema = _schema(cfg)
    model = NaiveBayesModel.load(cfg.assert_get("bayesian.model.file.path"),
                                 schema, delim=cfg.field_delim)
    # cost-based arbitration (BayesianPredictor.java:140-144):
    # bap.predict.class.cost = falseNegCost,falsePosCost with
    # bap.predict.class = negClass,posClass (cardinality order fallback)
    arbitrator = None
    costs = cfg.get_list("predict.class.cost", delim=cfg.field_delim)
    if costs:
        classes = cfg.get_list("predict.class",
                               delim=cfg.field_delim) or schema.class_values()
        arbitrator = CostBasedArbitrator(classes[0], classes[1],
                                         int(costs[0]), int(costs[1]))
    pred = NaiveBayesPredictor(model, arbitrator=arbitrator)
    prob_only = cfg.get_bool("output.feature.prob.only", False)
    validate = cfg.get_bool("validation.mode", False)
    delim = cfg.field_delim
    out = _out_file(output)
    counters: Dict[str, float] = {}
    cls_vals = schema.class_values()
    # validation folds a ConfusionMatrix PER CHUNK (its count matrix is
    # additive), instead of collecting per-chunk label/code arrays and
    # concatenating at the end — that carry grew with rows seen, the
    # exact mem-unbounded-carry shape graftlint --mem flags
    cm: Optional[ConfusionMatrix] = None
    # map-only job: test rows stream in blocks (host RSS O(block))
    from avenir_tpu.core.stream import stream_job_inputs

    with open(out, "w") as fh:
        for ds in stream_job_inputs(cfg, inputs, schema, keep_raw=True):
            if prob_only:
                probs = pred.feature_prob(ds)
                for rid, p in zip(ds.ids(), probs):
                    fh.write(f"{rid}{delim}{p:.6g}\n")
            else:
                codes, post = pred.predict(ds)
                for raw, c, row_post in zip(ds.raw_rows, codes, post):
                    # row_post is the reference's int-percent-scaled
                    # unnormalized posterior; normalize across classes for
                    # the appended confidence field
                    tot = float(np.sum(row_post)) or 1.0
                    prob = int(np.rint(100.0 * row_post[int(c)] / tot))
                    fh.write(delim.join(raw + [cls_vals[int(c)], str(prob)]) + "\n")
                if validate:
                    if cm is None:
                        pos = cfg.get("positive.class.value")
                        cm = ConfusionMatrix(
                            cls_vals,
                            pos_class=cls_vals.index(pos) if pos else 1)
                    cm.add(ds.labels(), codes)
    if cm is not None:
        counters = cm.counters()
    return JobResult("bayesianPredictor", counters, [out])


# ======================================================================== knn
@job("nearestNeighbor", "nen", "org.avenir.knn.NearestNeighbor")
def nearest_neighbor(cfg: JobConfig, inputs: List[str], output: str) -> JobResult:
    """Fused KNN: inputs = [train CSV, test CSV]. Replaces stages (1)-(5)
    of resource/knn.sh — all-pairs distance, NB feature-posterior weighting
    and the secondary-sorted top-k vote run as one device program
    (SURVEY §3.3). Key names follow knn.properties (incl. the reference's
    `class.condtion.weighted` spelling, NearestNeighbor.java:92)."""
    from avenir_tpu.models.knn import NearestNeighborClassifier

    from avenir_tpu.core.stream import stream_job_inputs

    train_path, test_path = inputs[0], inputs[-1]
    schema = _schema(cfg)
    delim = cfg.field_delim_regex
    train = Dataset.from_csv(train_path, schema, delim=delim)
    clf = NearestNeighborClassifier(
        train,
        top_match_count=cfg.get_int("top.match.count", 5),
        kernel_function=cfg.get("kernel.function", "none"),
        kernel_param=cfg.get_float("kernel.param", 1.0),
        class_cond_weighted=cfg.get_bool("class.condtion.weighted", False)
        or cfg.get_bool("class.condition.weighted", False),
        inverse_distance_weighted=cfg.get_bool("inverse.distance.weighted", False),
        decision_threshold=cfg.get_float("decision.threshold", -1.0),
        positive_class=cfg.get("positive.class.value"),
        # framework-specific fast-path toggles (no reference analog): the
        # lane-resident packed top-k kernel and the in-kernel fused vote
        packed=cfg.get_bool("device.packed.kernel", False),
        fused=cfg.get_bool("device.fused.vote", False),
    )
    out = _out_file(output)
    out_delim = cfg.field_delim
    cls_vals = schema.class_values()
    with_distr = cfg.get_bool("output.class.distr", False)
    validate = cfg.get_bool("validation.mode", False)
    # cost-based arbitration (NearestNeighbor.java:264-277, :383-387):
    # nen.misclassification.cost = falsePosCost,falseNegCost with
    # nen.class.attribute.values = posClass,negClass
    arbitrator = pos_i = neg_i = None
    if cfg.get_bool("use.cost.based.classifier", False):
        from avenir_tpu.utils.metrics import CostBasedArbitrator

        cav = cfg.get_list("class.attribute.values") or [
            cls_vals[1], cls_vals[0]]
        pos_v, neg_v = cav[0], cav[1]
        costs = cfg.assert_list("misclassification.cost")
        fp_cost, fn_cost = int(costs[0]), int(costs[1])
        arbitrator = CostBasedArbitrator(neg_v, pos_v, fn_cost, fp_cost)
        pos_i, neg_i = cls_vals.index(pos_v), cls_vals.index(neg_v)
        clf.positive_class = pos_i
    # queries stream in blocks against the resident train index — test-set
    # size never bounds host RSS (the model is the index, not the
    # queries); validation folds the additive ConfusionMatrix per chunk
    # instead of carrying every chunk's labels to the end
    cm: Optional[ConfusionMatrix] = None
    with open(out, "w") as fh:
        for test in stream_job_inputs(cfg, [test_path], schema):
            codes, scores = clf.predict(test)
            if arbitrator is not None:
                # getClassProb int-percent scale (Neighborhood.java:319-334)
                tot = np.maximum(scores.sum(axis=1), 1e-9)
                pos_prob = np.floor(100.0 * scores[:, pos_i] / tot)
                codes = np.where(arbitrator.classify(pos_prob),
                                 pos_i, neg_i).astype(np.int32)
            for i, (rid, c) in enumerate(zip(test.ids(), codes)):
                fields = [str(rid), cls_vals[int(c)]]
                if with_distr:
                    tot = float(np.sum(scores[i])) or 1.0
                    fields += [f"{cls_vals[j]}:{scores[i][j] / tot:.3f}"
                               for j in range(len(cls_vals))]
                fh.write(out_delim.join(fields) + "\n")
            if validate:
                if cm is None:
                    cm = ConfusionMatrix(cls_vals,
                                         pos_class=clf.positive_class)
                cm.add(test.labels(), codes)
    counters: Dict[str, float] = cm.counters() if cm is not None else {}
    return JobResult("nearestNeighbor", counters, [out])


# ================================================================= similarity
def _similarity_schema(cfg: JobConfig) -> FeatureSchema:
    """Accept any of the three reference key spellings for the schema:
    sifarish `sts.same.schema.file.path`, spark `rich.attr.schema.path`,
    or the framework-wide `feature.schema.file.path`."""
    for key in ("feature.schema.file.path", "same.schema.file.path",
                "rich.attr.schema.path"):
        path = cfg.get(key)
        if path:
            return FeatureSchema.from_file(path)
    raise MissingConfigError(
        f"missing schema config param: {cfg.prefix}.feature.schema.file.path")


@job("recordSimilarity", "sts", "sameTypeSimilarity",
     "org.avenir.spark.similarity.RecordSimilarity")
def record_similarity_job(cfg: JobConfig, inputs: List[str], output: str) -> JobResult:
    """All-pairs record distance file (the sifarish SameTypeSimilarity stage
    of resource/knn.sh:44-57 / RecordSimilarity.scala:34). One input =
    intra-set i<j pairs; two inputs (or sts.inter.set.matching=true) =
    cross-set pairs. Output rows: id1,id2,scaled-int-distance."""
    from avenir_tpu.models.similarity import RecordSimilarity

    schema = _similarity_schema(cfg)
    delim = cfg.field_delim_regex
    sim = RecordSimilarity(
        metric=cfg.get("distance.metric", "manhattan"),
        scale=cfg.get_int("distance.scale", 1000),
        num_weights=cfg.get_float_list("num.attribute.weights"),
        cat_weights=cfg.get_float_list("cat.attribute.weights"),
    )
    out = _out_file(output)
    inter = cfg.get_bool("inter.set.matching", len(inputs) > 1)
    if inter:
        base = Dataset.from_csv(inputs[0], schema, delim=delim)
        other = Dataset.from_csv(inputs[-1], schema, delim=delim)
        n = sim.save(sim.inter(base, other), out, delim=cfg.field_delim,
                     id_first=cfg.get_bool("output.id.first", True))
    else:
        ds = Dataset.from_csv(inputs[0], schema, delim=delim)
        n = sim.save(sim.intra(ds), out, delim=cfg.field_delim,
                     id_first=cfg.get_bool("output.id.first", True))
    return JobResult("recordSimilarity", {"Similarity:Pairs": n}, [out])


@job("groupedRecordSimilarity", "grs",
     "org.avenir.spark.similarity.GroupedRecordSimilarity")
def grouped_similarity_job(cfg: JobConfig, inputs: List[str], output: str) -> JobResult:
    from avenir_tpu.models.similarity import GroupedRecordSimilarity

    schema = _similarity_schema(cfg)
    ds = Dataset.from_csv(inputs[0], schema, delim=cfg.field_delim_regex)
    sim = GroupedRecordSimilarity(
        [int(o) for o in cfg.assert_list("group.field.ordinals")],
        metric=cfg.get("distance.metric", "manhattan"),
        scale=cfg.get_int("distance.scale", 1000),
    )
    out = _out_file(output)
    delim = cfg.field_delim
    n = 0
    with open(out, "w") as fh:
        for key, id1, id2, d in sim.grouped_intra(ds):
            sd = int(round(d * sim.scale))
            fh.write(delim.join([*key, id1, id2, str(sd)]) + "\n")
            n += 1
    return JobResult("groupedRecordSimilarity", {"Similarity:Pairs": n}, [out])


@job("featureCondProbJoiner", "fcb", "org.avenir.knn.FeatureCondProbJoiner")
def feature_cond_prob_joiner(cfg: JobConfig, inputs: List[str], output: str
                             ) -> JobResult:
    """Stage (4) of the 5-job KNN pipeline: join the pairwise-distance
    file (recordSimilarity output, `id1,id2,dist` tail fields) with the
    per-train-entity feature posterior file (bayesianPredictor
    bap.output.feature.prob.only output, `id,prob` rows) on the train
    entity. The fused nearestNeighbor job computes this weighting
    in-process; this job keeps the stage individually addressable for
    drop-in pipeline parity (FeatureCondProbJoiner.java:46; input split
    detection by filename prefix, :97-98 — here via
    fcb.feature.cond.prob.split.prefix, falling back to treating the LAST
    input as the probability file). Output rows:
    testId,trainId,distance,trainFeaturePostProb."""
    # both inputs are sibling-job OUTPUTS: split with the output delim
    # (field_delim_regex is the user-input delimiter and may differ)
    delim = cfg.field_delim
    prefix = cfg.get("feature.cond.prob.split.prefix", "condProb")
    prob_files = [p for p in inputs
                  if os.path.basename(p).startswith(prefix)]
    dist_files = [p for p in inputs if p not in prob_files]
    if not prob_files:
        prob_files, dist_files = [inputs[-1]], inputs[:-1]
    probs: Dict[str, str] = {}
    for p in prob_files:
        for ln in _read_lines(p):
            toks = [t.strip() for t in ln.split(delim)]
            probs[toks[0]] = toks[-1]
    # the distance file's column order follows the sts job's own key
    id_first = cfg.scoped("sts").get_bool("output.id.first", True)
    out = _out_file(output)
    od = cfg.field_delim
    n = 0
    with open(out, "w") as fh:
        for p in dist_files:
            for ln in _read_lines(p):
                toks = [t.strip() for t in ln.split(delim)]
                if id_first:
                    id1, id2, dist = toks[-3], toks[-2], toks[-1]
                else:
                    dist, id1, id2 = toks[-3], toks[-2], toks[-1]
                pr = probs.get(id2)
                if pr is None and id1 in probs:
                    # distance rows carry (test, train) in either slot
                    id1, id2 = id2, id1
                    pr = probs[id2]
                if pr is None:
                    continue
                fh.write(od.join([id1, id2, dist, pr]) + "\n")
                n += 1
    return JobResult("featureCondProbJoiner", {"Join:Pairs": n}, [out])


# ======================================================================= tree
def _tree_builder(cfg: JobConfig, schema: FeatureSchema):
    from avenir_tpu.models.tree import DecisionTreeBuilder

    strategy = cfg.get("path.stopping.strategy", "maxDepth")
    return DecisionTreeBuilder(
        schema,
        split_algorithm=cfg.get("split.algorithm", "entropy"),
        max_depth=cfg.get_int("max.depth.limit", 3),
        min_info_gain=cfg.get_float("min.info.gain.limit", -1.0),
        min_population=cfg.get_int("min.population.limit", -1),
        stopping_strategy=strategy,
        attr_selection_strategy=cfg.get("split.attribute.selection.strategy",
                                        "notUsedYet"),
    )


@job("decTree", "dtb", "org.avenir.tree.DecisionTreeBuilder", "decisionTree")
def decision_tree(cfg: JobConfig, inputs: List[str], output: str) -> JobResult:
    """Decision-tree build; the reference's per-level MR iteration with
    decPathIn/decPathOut file rotation (resource/detr.sh:34-54) runs as an
    internal device loop, but the DecisionPathList JSON still lands at
    `dtb.decision.file.path.out` for checkpoint parity."""
    ds = _dataset(inputs[0], cfg)
    # build against the dataset's OWN schema object: parsing may have
    # discovered vocabularies (e.g. an undeclared class cardinality in
    # the reference's call_hangup.json) that a fresh load lacks
    builder = _tree_builder(cfg, ds.schema)
    paths = builder.fit(ds)
    out = cfg.get("decision.file.path.out") or _out_file(output, "decPathOut.txt")
    paths.save(out)
    return JobResult("decTree", {"Tree:Paths": len(paths.paths)}, [out], paths)


@job("randomForest", "dtb", "org.avenir.tree.RandomForestBuilder")
def random_forest(cfg: JobConfig, inputs: List[str], output: str) -> JobResult:
    from avenir_tpu.models.tree import RandomForestBuilder

    ds = _dataset(inputs[0], cfg)
    forest = RandomForestBuilder(
        ds.schema,
        num_trees=cfg.get_int("num.trees", 10),
        sampling=cfg.get("sub.sampling.strategy", "withReplace"),
        sample_rate=cfg.get_float("sub.sampling.rate", 0.7),
        split_algorithm=cfg.get("split.algorithm", "entropy"),
        max_depth=cfg.get_int("max.depth.limit", 3),
        stopping_strategy=cfg.get("path.stopping.strategy", "maxDepth"),
    ).fit(ds)
    outs = []
    if output:
        os.makedirs(output, exist_ok=True)
        for t, tree in enumerate(forest.trees):
            p = os.path.join(output, f"tree-{t:03d}.json")
            tree.save(p)
            outs.append(p)
    return JobResult("randomForest", {"Tree:Trees": len(forest.trees)},
                     outs, forest)


@job("classPartitionGenerator", "cpg",
     "org.avenir.explore.ClassPartitionGenerator",
     "splitGenerator", "org.avenir.tree.SplitGenerator")
def class_partition_job(cfg: JobConfig, inputs: List[str], output: str) -> JobResult:
    """Candidate-split class-histogram stats (cpg.* keys; the reference's
    two-job tree flow stage, ClassPartitionGenerator.java:61).

    Also answers to org.avenir.tree.SplitGenerator — the tree package's
    candidate-split stats base job (DecisionTreeBuilder extends it, which
    is how it slipped the original implements-Tool addressability scan:
    the Tool surface is inherited, not spelled in the subclass source)."""
    from avenir_tpu.models.explore import ClassPartitionGenerator

    ds = _dataset(inputs[0], cfg)
    attrs = cfg.get_int_list("split.attributes")
    cpg = ClassPartitionGenerator(
        ds, attributes=attrs,
        algorithm=cfg.get("split.algorithm", cfg.get("algorithm", "giniIndex")),
    )
    out = _out_file(output)
    delim = cfg.field_delim
    with open(out, "w") as fh:
        for s, stat in cpg.split_stats():
            fh.write(f"{s.attribute}{delim}{s.split_id}{delim}{stat:.6f}\n")
    return JobResult("classPartitionGenerator",
                     {"Splits:Candidates": len(cpg.splits)}, [out], cpg)


@job("dataPartitioner", "dap", "org.avenir.tree.DataPartitioner")
def data_partitioner_job(cfg: JobConfig, inputs: List[str], output: str) -> JobResult:
    from avenir_tpu.models.tree import DataPartitioner

    # keep_raw: partition output must pass rows through byte-identical
    # (reconstruction would reformat numerics and break on missing values)
    ds = _dataset(inputs[0], cfg, keep_raw=True)
    dp = DataPartitioner(
        ds.schema,
        algorithm=cfg.get("split.algorithm", "giniIndex"),
        split_attribute=cfg.get_int("split.attribute"),
    )
    base = cfg.get("project.base.path") or output
    paths = dp.partition(ds, base, delim=cfg.field_delim)
    return JobResult("dataPartitioner", {"Partition:Segments": len(paths)},
                     paths)


@job("contTimeStateTransitionStats", "cts",
     "org.avenir.spark.markov.ContTimeStateTransitionStats")
def ctmc_stats_job(cfg: JobConfig, inputs: List[str], output: str) -> JobResult:
    """CTMC statistics by uniformization (ContTimeStateTransitionStats.scala:34).
    `cts.state.trans.file.path` holds the rate matrix rows; input rows are
    `id,initState[,endState]`; `cts.state.trans.stat` picks stateDwellTime
    (target = cts.target.states[0]) or StateTransitionCount (targets[0:2]).

    Output-compat deviation vs the Scala job (documented on the model class
    too): the transition-count inner loop bound and the conditional
    normalization differ, so stats for identical inputs are close but not
    byte-identical to the reference's."""
    from avenir_tpu.models.markov import ContTimeStateTransitionStats

    states = cfg.assert_list("state.values")
    horizon = cfg.assert_float("time.horizon")
    rate_path = cfg.assert_get("state.trans.file.path")
    # two accepted rate-file shapes (the Scala job's cts.key.field.len
    # contract): a plain S x S numeric matrix, or stateTransitionRate's
    # per-entity output (`key,state,r0,...,rS-1` rows) — the supplier-
    # fulfillment flow (sup.sh transRate -> rateStat) hands the second
    # straight through, and stats are then looked up by the input row's
    # entity key
    per_entity: Dict[str, np.ndarray] = {}
    # shape sniffing by STRUCTURE, not parse failure (numeric entity ids
    # and state labels would make a per-entity file loadtxt-able): a
    # plain matrix row has S tokens; a per-entity row has S + 2 with the
    # second token being a state label
    first = next(iter(_read_lines(rate_path)), "")
    ftoks = [t.strip() for t in first.split(cfg.field_delim_regex)]
    if len(ftoks) == len(states) + 2 and ftoks[1] in states:
        rows: Dict[str, Dict[str, List[float]]] = {}
        for ln in _read_lines(rate_path):
            toks = [t.strip() for t in ln.split(cfg.field_delim_regex)]
            key, state, vals = toks[0], toks[1], [float(v) for v in toks[2:]]
            if state not in states or len(vals) != len(states):
                raise ValueError(
                    f"rate file row for {key!r} does not match "
                    f"state.values {states}")
            rows.setdefault(key, {})[state] = vals
        for key, by_state in rows.items():
            missing = [s for s in states if s not in by_state]
            if missing:
                raise ValueError(
                    f"entity {key!r} in {rate_path} has no rate row for "
                    f"state(s) {missing}")
            per_entity[key] = np.array([by_state[s] for s in states])
        rates = None
    else:
        rates = np.loadtxt(rate_path, delimiter=cfg.field_delim_regex,
                           ndmin=2)
        if rates.shape != (len(states), len(states)):
            raise ValueError(
                f"rate matrix in {rate_path} has shape {rates.shape}; "
                f"expected {(len(states), len(states))} for state.values "
                f"{states} (or stateTransitionRate per-entity rows)")

    stats_cache: Dict[str, ContTimeStateTransitionStats] = {}

    def stats_for(rid: str) -> ContTimeStateTransitionStats:
        if rates is not None:
            key = ""
        else:
            if rid not in per_entity:
                raise KeyError(f"no rate matrix for entity {rid!r} in "
                               f"{rate_path}")
            key = rid
        if key not in stats_cache:
            q = rates if rates is not None else per_entity[key]
            stats_cache[key] = ContTimeStateTransitionStats(
                q, states, horizon)
        return stats_cache[key]

    stat_kind = cfg.get("state.trans.stat", "stateDwellTime")
    targets = cfg.assert_list("target.states")
    out = _out_file(output)
    delim = cfg.field_delim
    with open(out, "w") as fh:
        for path in inputs:
            for ln in _read_lines(path):
                toks = [t.strip() for t in ln.split(cfg.field_delim_regex)]
                rid, init = toks[0], toks[1]
                end = toks[2] if len(toks) > 2 else None
                st = stats_for(rid)
                if stat_kind == "stateDwellTime":
                    v = st.dwell_time(init, targets[0], end)
                else:
                    v = st.transition_count(init, targets[0], targets[1], end)
                fh.write(f"{rid}{delim}{v:.6f}\n")
    return JobResult("contTimeStateTransitionStats", {},
                     [out], stats_cache)


@job("stateTransitionRate", "str",
     "org.avenir.spark.markov.StateTransitionRate")
def state_transition_rate_job(cfg: JobConfig, inputs: List[str],
                              output: str) -> JobResult:
    """Per-entity CTMC transition-rate matrices from timestamped state
    rows (StateTransitionRate.scala:30): group by str.key.field.ordinals,
    sort by the epoch-time field, rate(i->j) = count(i->j) / dwell(i)
    with dwell scaled to str.rate.time.unit (hour/day/week) and diagonal
    set to -sum(off-diagonal row) as the Scala job does. Input timestamps
    are ms, sec, or s-since-epoch per str.input.time.unit."""
    from avenir_tpu.models.markov import StateTransitionRate

    key_ords = cfg.get_int_list("key.field.ordinals", [0])
    time_ord = cfg.assert_int("time.field.ordinal")
    state_ord = cfg.assert_int("state.field.ordinal")
    states = cfg.assert_list("state.values")
    in_unit = cfg.get("input.time.unit", "ms")
    try:
        to_ms = {"ms": 1.0, "sec": 1000.0, "s": 1000.0}[in_unit]
    except KeyError:
        raise ValueError(f"invalid input time unit {in_unit!r}")
    rate_unit = cfg.get("rate.time.unit", "hour")
    try:
        unit_ms = {"hour": 3.6e6, "day": 8.64e7, "week": 6.048e8}[rate_unit]
    except KeyError:
        raise ValueError(f"invalid rate time unit {rate_unit!r}")
    prec = cfg.get_int("trans.rate.output.precision", 6)

    by_key: Dict[str, List[Tuple[float, str]]] = {}
    for p in inputs:
        for ln in _read_lines(p):
            toks = [t.strip() for t in ln.split(cfg.field_delim_regex)]
            key = cfg.field_delim.join(toks[o] for o in key_ords)
            by_key.setdefault(key, []).append(
                (float(toks[time_ord]) * to_ms, toks[state_ord]))
    out = _out_file(output)
    delim = cfg.field_delim
    models: Dict[str, StateTransitionRate] = {}
    with open(out, "w") as fh:
        for key, events in sorted(by_key.items()):
            events.sort(key=lambda e: e[0])
            seq = [(s, t / unit_ms) for t, s in events]
            model = StateTransitionRate(states).fit([seq])
            models[key] = model
            q = model.rates()
            q = q - np.diag(q.sum(axis=1))
            for i, s in enumerate(states):
                row = delim.join(f"{v:.{prec}f}" for v in q[i])
                fh.write(f"{key}{delim}{s}{delim}{row}\n")
    return JobResult("stateTransitionRate",
                     {"Basic:Entities": len(by_key)}, [out], models)


# ==================================================================== explore
@job("mutualInformation", "mut", "org.avenir.explore.MutualInformation")
def mutual_information_job(cfg: JobConfig, inputs: List[str], output: str) -> JobResult:
    from avenir_tpu.core.stream import stream_job_inputs

    # block streaming: MI's count tables fold additively per chunk, so
    # host RSS stays O(block) at any input size (the mapper contract of
    # MutualInformation.java:138-216); the fold sink doubles as the
    # shared-scan sink (_MutualInfoFold)
    fold = _MutualInfoFold(cfg, inputs, None)
    _drive_fold(fold, stream_job_inputs(cfg, inputs, _schema(cfg)),
                "mutualInformation")
    return _finish_fold(fold, output, "mutualInformation")


@job("ruleEvaluator", "rue", "org.avenir.explore.RuleEvaluator")
def rule_evaluator(cfg: JobConfig, inputs: List[str], output: str) -> JobResult:
    """rue.rule.<name> definitions `cond1 & cond2 => cons` evaluated for
    support/confidence (RuleEvaluator.java:48)."""
    from avenir_tpu.core.stream import stream_job_inputs
    from avenir_tpu.models.explore import Rule

    names = cfg.assert_list("rule.names")
    cond_delim = cfg.get("cond.delim", "&")
    rules = {}
    for name in names:
        expr = cfg.assert_get(f"rule.{name}")
        if expr.count("=>") != 1:
            raise ValueError(
                f"{cfg.prefix}.rule.{name} must contain exactly one '=>' "
                f"(cond => cons), got: {expr!r}")
        cond_part, cons_part = expr.split("=>")
        rules[name] = Rule(
            [c.strip() for c in cond_part.split(cond_delim) if c.strip()],
            [c.strip() for c in cons_part.split(cond_delim) if c.strip()],
        )
    # all rules fold their (rows, cond, both) counts per streamed chunk
    totals = {name: [0, 0, 0] for name in names}
    rows_seen = 0
    for chunk in stream_job_inputs(cfg, inputs, _schema(cfg)):
        rows_seen += len(chunk)
        for name, rule in rules.items():
            for i, v in enumerate(rule.counts(chunk)):
                totals[name][i] += v
    if rows_seen == 0:
        raise ValueError(f"ruleEvaluator: empty input "
                         f"(no records in {inputs})")
    out = _out_file(output)
    delim = cfg.field_delim
    results = {}
    with open(out, "w") as fh:
        for name in names:
            res = Rule.finalize(*totals[name])
            results[name] = res
            fh.write(f"{name}{delim}{res['support']:.6f}{delim}"
                     f"{res['confidence']:.6f}\n")
    return JobResult("ruleEvaluator", {"Basic:Records": rows_seen},
                     [out], results)


@job("cramerCorrelation", "crc", "org.avenir.explore.CramerCorrelation")
@job("categoricalCorrelation", "cac",
     "org.avenir.explore.CategoricalCorrelation")
def cramer_job(cfg: JobConfig, inputs: List[str], output: str) -> JobResult:
    """Cramér-index categorical<->class correlation (crc.*); the cac.* job
    computes the same contingency-table stat (CramerCorrelation.java:54)."""
    from avenir_tpu.core.stream import stream_job_inputs
    from avenir_tpu.models.explore import ContingencyAccumulator

    name = cfg.props.get("__job_name__", "cramerCorrelation")
    acc = ContingencyAccumulator()
    for chunk in stream_job_inputs(cfg, inputs, _schema(cfg)):
        acc.add(chunk)
    if acc.n == 0:
        raise ValueError(f"{name}: empty input (no records in {inputs})")
    corr = acc.cramer()
    out = _out_file(output)
    delim = cfg.field_delim
    with open(out, "w") as fh:
        for ordinal, v in sorted(corr.items()):
            fh.write(f"{ordinal}{delim}{v:.6f}\n")
    return JobResult(name, {"Basic:Records": acc.n}, [out], corr)


@job("heterogeneityReduction", "hrc",
     "org.avenir.explore.HeterogeneityReductionCorrelation")
def heterogeneity_job(cfg: JobConfig, inputs: List[str], output: str) -> JobResult:
    from avenir_tpu.core.stream import stream_job_inputs
    from avenir_tpu.models.explore import ContingencyAccumulator

    acc = ContingencyAccumulator()
    for chunk in stream_job_inputs(cfg, inputs, _schema(cfg)):
        acc.add(chunk)
    if acc.n == 0:
        raise ValueError(f"heterogeneityReduction: empty input "
                         f"(no records in {inputs})")
    corr = acc.heterogeneity(cfg.get("heterogeneity.algorithm", "entropy"))
    out = _out_file(output)
    delim = cfg.field_delim
    with open(out, "w") as fh:
        for ordinal, v in sorted(corr.items()):
            fh.write(f"{ordinal}{delim}{v:.6f}\n")
    return JobResult("heterogeneityReduction",
                     {"Basic:Records": acc.n}, [out], corr)


@job("numericalCorrelation", "nuc",
     "org.avenir.explore.NumericalCorrelation")
def numerical_corr_job(cfg: JobConfig, inputs: List[str], output: str) -> JobResult:
    from avenir_tpu.core.stream import stream_job_inputs
    from avenir_tpu.models.explore import NumericMomentAccumulator

    schema = _schema(cfg)
    acc = NumericMomentAccumulator()
    for chunk in stream_job_inputs(cfg, inputs, schema):
        acc.add(chunk)
    if acc.n == 0:
        raise ValueError(f"numericalCorrelation: empty input "
                         f"(no records in {inputs})")
    corr = acc.correlation()           # [D+1, D+1]: class is the last column
    fields = [f.ordinal for f in schema.feature_fields if f.is_numeric]
    out = _out_file(output)
    delim = cfg.field_delim
    with open(out, "w") as fh:
        for i, oi in enumerate(fields):
            for j, oj in enumerate(fields):
                if j > i:
                    fh.write(f"{oi}{delim}{oj}{delim}{corr[i, j]:.6f}\n")
            # feature-vs-class correlation: the relevance signal this
            # family of jobs exists to emit
            fh.write(f"{oi}{delim}class{delim}{corr[i, -1]:.6f}\n")
    return JobResult("numericalCorrelation",
                     {"Basic:Records": acc.n}, [out], corr)


@job("reliefFeatureRelevance", "ffr",
     "org.avenir.explore.ReliefFeatureRelevance")
def relief_job(cfg: JobConfig, inputs: List[str], output: str) -> JobResult:
    from avenir_tpu.models.explore import relief_relevance

    ds = _dataset(inputs[0], cfg)
    rel = relief_relevance(ds, sample_size=cfg.get_int("sample.size"))
    out = _out_file(output)
    delim = cfg.field_delim
    with open(out, "w") as fh:
        for ordinal, v in sorted(rel.items()):
            fh.write(f"{ordinal}{delim}{v:.6f}\n")
    return JobResult("reliefFeatureRelevance", {}, [out], rel)


@job("categoricalClassAffinity", "cca",
     "org.avenir.explore.CategoricalClassAffinity")
def class_affinity_job(cfg: JobConfig, inputs: List[str], output: str) -> JobResult:
    from avenir_tpu.core.stream import stream_job_inputs
    from avenir_tpu.models.explore import (ContingencyAccumulator,
                                           class_affinity_from_table)

    schema = _schema(cfg)
    acc = ContingencyAccumulator()
    for chunk in stream_job_inputs(cfg, inputs, schema):
        acc.add(chunk)
    if acc.n == 0:
        raise ValueError(f"categoricalClassAffinity: empty input "
                         f"(no records in {inputs})")
    top_n = cfg.get_int("top.count", 3)
    out = _out_file(output)
    delim = cfg.field_delim
    payload = {}
    with open(out, "w") as fh:
        for fld in schema.feature_fields:
            if not fld.is_categorical or fld.ordinal not in acc.tables:
                continue
            aff = class_affinity_from_table(
                acc.tables[fld.ordinal], fld, schema.class_values(), top_n)
            payload[fld.ordinal] = aff
            for cv, pairs in aff.items():
                for val, score in pairs:
                    fh.write(f"{fld.ordinal}{delim}{cv}{delim}{val}"
                             f"{delim}{score:.6f}\n")
    return JobResult("categoricalClassAffinity",
                     {"Basic:Records": acc.n}, [out], payload)


@job("categoricalContinuousEncoding", "coe",
     "org.avenir.explore.CategoricalContinuousEncoding")
def supervised_encoding_job(cfg: JobConfig, inputs: List[str], output: str) -> JobResult:
    from avenir_tpu.core.stream import stream_job_inputs
    from avenir_tpu.models.explore import (ContingencyAccumulator,
                                           supervised_encoding_from_table)

    schema = _schema(cfg)
    acc = ContingencyAccumulator()
    for chunk in stream_job_inputs(cfg, inputs, schema):
        acc.add(chunk)
    if acc.n == 0:
        raise ValueError(f"categoricalContinuousEncoding: empty input "
                         f"(no records in {inputs})")
    strategy = cfg.get("encoding.strategy", "supervisedRatio")
    pos = cfg.get("pos.class.attr.value")
    out = _out_file(output)
    delim = cfg.field_delim
    payload = {}
    with open(out, "w") as fh:
        for fld in schema.feature_fields:
            if not fld.is_categorical or fld.ordinal not in acc.tables:
                continue
            enc = supervised_encoding_from_table(
                acc.tables[fld.ordinal], fld, schema.class_values(),
                strategy=strategy, pos_class=pos)
            payload[fld.ordinal] = enc
            for val, code in enc.items():
                fh.write(f"{fld.ordinal}{delim}{val}{delim}{code:.6f}\n")
    return JobResult("categoricalContinuousEncoding",
                     {"Basic:Records": acc.n}, [out], payload)


@job("topMatchesByClass", "tmc", "org.avenir.explore.TopMatchesByClass")
def top_matches_job(cfg: JobConfig, inputs: List[str], output: str) -> JobResult:
    from avenir_tpu.models.explore import top_matches_by_class

    ds = _dataset(inputs[0], cfg)
    matches = top_matches_by_class(ds, k=cfg.get_int("top.match.count", 3))
    out = _out_file(output)
    delim = cfg.field_delim
    ids = ds.ids()
    y = ds.labels()
    cls_vals = ds.schema.class_values()
    n = 0
    with open(out, "w") as fh:
        for cv, (dist, idx) in matches.items():
            rows = np.flatnonzero(y == cls_vals.index(cv))
            for r in range(dist.shape[0]):
                # entity ids on both sides so rows join back to the data
                row = [cv, str(ids[rows[r]])] + [
                    f"{ids[idx[r, j]]}:{dist[r, j]:.4f}"
                    for j in range(dist.shape[1])]
                fh.write(delim.join(row) + "\n")
                n += 1
    return JobResult("topMatchesByClass", {"Basic:Records": n}, [out], matches)


@job("underSamplingBalancer", "usb",
     "org.avenir.explore.UnderSamplingBalancer")
@job("baggingSampler", "bas", "org.avenir.explore.BaggingSampler")
def sampler_job(cfg: JobConfig, inputs: List[str], output: str) -> JobResult:
    """Map-only row samplers: class rebalancing by undersampling (usb.*)
    or bootstrap sampling (bas.*); rows pass through byte-identical."""
    from avenir_tpu.models.explore import bagging_sample, undersample_balance

    name = cfg.props.get("__job_name__", "underSamplingBalancer")
    ds = _dataset(inputs[0], cfg, keep_raw=True)
    if name == "baggingSampler":
        sampled = bagging_sample(ds, rate=cfg.get_float("sample.rate", 1.0),
                                 seed=cfg.get_int("seed", 0))
    else:
        sampled = undersample_balance(ds, seed=cfg.get_int("seed", 0))
    out = _out_file(output)
    with open(out, "w") as fh:
        fh.write(sampled.to_csv(cfg.field_delim) if len(sampled) else "")
    return JobResult(name, {"Basic:Records": len(sampled)}, [out])


# ==================================================================== cluster
@job("agglomerativeGraphical", "agg",
     "org.avenir.cluster.AgglomerativeGraphical")
def agglomerative_job(cfg: JobConfig, inputs: List[str], output: str) -> JobResult:
    """Greedy agglomerative clustering over a pairwise-distance file (the
    EntityDistanceMapFileAccessor input, AgglomerativeGraphical.java:108)."""
    from avenir_tpu.models.cluster import AgglomerativeGraphical
    from avenir_tpu.models.similarity import (distance_matrix_from_file,
                                              read_distance_file)

    dist_path = cfg.get("distance.file.path") or inputs[0]
    pairs = read_distance_file(dist_path, delim=cfg.field_delim_regex,
                               scale=cfg.get_int("distance.scale", 1000))
    ids = sorted({a for a, _ in pairs})
    m = distance_matrix_from_file(dist_path, ids, pairs=pairs)
    model = AgglomerativeGraphical(
        num_clusters=cfg.get_int("num.clusters", 2),
        max_avg_distance=cfg.get_float("max.avg.distance"),
    ).fit(m)
    out = _out_file(output)
    delim = cfg.field_delim
    with open(out, "w") as fh:
        for i, rid in enumerate(ids):
            fh.write(f"{rid}{delim}{int(model.labels_[i])}\n")
    return JobResult("agglomerativeGraphical",
                     {"Cluster:Count": len(set(model.labels_.tolist()))},
                     [out], model)


@job("clusterTrain", "train", "kmeansCluster")
def cluster_train_job(cfg: JobConfig, inputs: List[str], output: str) -> JobResult:
    """The python-layer cluster.py surface (train.* jprops keys,
    unsupv/cluster.py:24-60): kmeans / dbscan over the schema's numeric
    features, with cohesion model selection output."""
    from avenir_tpu.models.cluster import DBSCAN, KMeans, cohesion

    ds = _dataset(inputs[0], cfg)
    x = ds.feature_matrix()
    algo = cfg.get("algo", "kmeans")
    if algo == "kmeans":
        model = KMeans(k=cfg.get_int("num.clusters", 3),
                       iters=cfg.get_int("num.iters", 100)).fit(x)
        labels = model.labels_          # fit already assigned the train rows
    elif algo == "dbscan":
        from avenir_tpu.models.cluster import dataset_distance_matrix

        model = DBSCAN(eps=cfg.get_float("eps", 0.5),
                       min_samples=cfg.get_int("min.samples", 4))
        model.fit(dataset_distance_matrix(ds))
        labels = model.labels_
    else:
        raise ValueError(f"unknown cluster algo {algo!r}")
    out = _out_file(output)
    delim = cfg.field_delim
    with open(out, "w") as fh:
        for rid, lab in zip(ds.ids(), labels):
            fh.write(f"{rid}{delim}{int(lab)}\n")
    coh = float(cohesion(x, np.asarray(labels))) if len(set(labels)) > 1 else 0.0
    return JobResult("clusterTrain", {"Cluster:Cohesion": coh}, [out], model)


# =================================================================== sequence
@job("candidateGenerationWithSelfJoin", "cgs",
     "org.avenir.sequence.CandidateGenerationWithSelfJoin", "gspMiner")
def gsp_job(cfg: JobConfig, inputs: List[str], output: str) -> JobResult:
    """GSP frequent-sequence mining; the reference's per-k self-join rounds
    (CandidateGenerationWithSelfJoin.java:44-49) run internally up to
    cgs.item.set.length, with per-k output files."""
    from avenir_tpu.models.sequence import (GSPMiner, SequenceSet,
                                            StreamingSequenceSource)

    skip = cfg.get_int("skip.field.count", 1)
    miner = GSPMiner(
        support_threshold=cfg.assert_float("support.threshold"),
        max_length=cfg.get_int("item.set.length", 3),
    )
    total_bytes = sum(os.path.getsize(p) for p in inputs
                      if os.path.exists(p))
    in_ram = (cfg.get("stream.block.size.mb") is None
              and total_bytes < (256 << 20))
    # timer starts BEFORE the in-RAM probe reads the file: RowsPerSec
    # must price the whole job's I/O identically on both paths, or the
    # tripwire mis-alarms when a corpus crosses the in-RAM gate
    t0 = time.perf_counter()
    if in_ram:
        rows = [[t.strip(" \t\r") for t in ln.split(cfg.field_delim_regex)]
                for p in inputs for ln in _read_lines(p)]
        # the in-RAM cost is the padded [N, T] matrix: one anomalously
        # long row must not blow it up — gate on the footprint
        t_max = max((len(r) - skip for r in rows), default=1)
        in_ram = len(rows) * max(t_max, 1) * 4 < (2 << 30)
    if in_ram:
        # in-RAM: one [N, T] upload, device-resident across k rounds
        levels = miner.mine(SequenceSet.from_token_rows(
            rows, skip_field_count=skip))
        n_rows = len(rows)
    else:
        # beyond-RAM (or explicitly chunked): one streamed scan per k,
        # per-k re-scans replaying the pass-1 encoded-block cache
        src = StreamingSequenceSource(
            inputs, delim=cfg.field_delim_regex, skip_field_count=skip,
            block_bytes=int(cfg.get_float("stream.block.size.mb", 64.0)
                            * (1 << 20)),
            spill_cache=cfg.get_bool("stream.encoded.cache", True),
            cache_budget_bytes=_cache_budget(cfg))
        _attach_sidecar_opts(src, cfg)
        levels = miner.mine_stream(src)
        n_rows = src.n_rows
        cache_counters = _cache_counters(src)
        src.close()
    counters = {"GSP:MaxLength": max(levels) if levels else 0,
                **throughput_counters(n_rows, time.perf_counter() - t0),
                **(cache_counters if not in_ram else {})}
    outs = _write_gsp_outputs(cfg, output, levels)
    return JobResult("candidateGenerationWithSelfJoin", counters,
                     outs, levels)


@job("sequencePositionalCluster", "spc",
     "org.avenir.sequence.SequencePositionalCluster")
def positional_cluster_job(cfg: JobConfig, inputs: List[str], output: str) -> JobResult:
    from avenir_tpu.models.sequence import EventLocalityAnalyzer, positional_cluster

    analyzer = EventLocalityAnalyzer(
        window_time_span=cfg.assert_float("window.time.span"),
        time_step=cfg.get_float("window.time.step", 1.0),
        score_threshold=cfg.get_float("score.threshold", 0.5),
        min_occurence=cfg.get_int("min.occurence", 2),
    )
    rows = [[t.strip() for t in ln.split(cfg.field_delim_regex)]
            for p in inputs for ln in _read_lines(p)]
    quant_ord = cfg.get_int("quant.field.ordinal", 2)
    seq_ord = cfg.get_int("seq.num.field.ordinal", 1)
    thresh = cfg.get_float("quant.threshold")
    cond = (lambda v: v >= thresh) if thresh is not None else (lambda v: True)
    clusters = positional_cluster(rows, analyzer, quant_ord, seq_ord, cond)
    out = _out_file(output)
    delim = cfg.field_delim
    with open(out, "w") as fh:
        for pos, score in clusters:
            fh.write(f"{pos:.4f}{delim}{score:.6f}\n")
    return JobResult("sequencePositionalCluster",
                     {"Windows:Found": len(clusters)}, [out], clusters)


@job("eventTimeDistribution", "etd",
     "org.avenir.spark.sequence.EventTimeDistribution")
def event_time_job(cfg: JobConfig, inputs: List[str], output: str) -> JobResult:
    """Inter-arrival time histogram (EventTimeDistribution.scala:27):
    rows are id,timestamp... grouped by id."""
    from avenir_tpu.models.markov import event_time_distribution

    ts_ord = cfg.get_int("time.stamp.field.ordinal", 1)
    by_id: Dict[str, List[float]] = {}
    for p in inputs:
        for ln in _read_lines(p):
            toks = [t.strip() for t in ln.split(cfg.field_delim_regex)]
            by_id.setdefault(toks[0], []).append(float(toks[ts_ord]))
    seqs = [sorted(v) for v in by_id.values()]
    hist = event_time_distribution(
        seqs, num_buckets=cfg.get_int("num.buckets", 24),
        bucket_width=cfg.get_float("bucket.width", 3600.0))
    out = _out_file(output)
    delim = cfg.field_delim
    with open(out, "w") as fh:
        for b, c in enumerate(hist):
            fh.write(f"{b}{delim}{int(c)}\n")
    return JobResult("eventTimeDistribution",
                     {"Basic:Entities": len(by_id)}, [out], hist)


@job("sequenceGenerator", "seg",
     "org.avenir.spark.sequence.SequenceGenerator")
def sequence_generator_job(cfg: JobConfig, inputs: List[str],
                           output: str) -> JobResult:
    """Sequence formation from event rows (SequenceGenerator.scala:31):
    group rows by seg.id.field.ordinals, project seg.val.field.ordinals,
    sort each group's value records by seg.seq.field (an index INTO the
    projected value record, matching the Scala withSortFields contract),
    emit one line per entity: key fields then the sorted value records
    flattened."""
    key_ords = cfg.get_int_list("id.field.ordinals", [0])
    val_ords = cfg.assert_list("val.field.ordinals")
    val_ords = [int(v) for v in val_ords]
    seq_field = cfg.assert_int("seq.field")

    def sort_key(rec: List[str]) -> Tuple[float, str]:
        v = rec[seq_field]
        try:
            f = float(v)
            # NaN sort keys would silently scramble the group order
            if math.isnan(f):
                return (float("inf"), v)
            return (f, "")
        except ValueError:
            return (float("inf"), v)

    by_key: Dict[str, List[List[str]]] = {}
    for p in inputs:
        for ln in _read_lines(p):
            toks = [t.strip() for t in ln.split(cfg.field_delim_regex)]
            key = cfg.field_delim.join(toks[o] for o in key_ords)
            by_key.setdefault(key, []).append([toks[o] for o in val_ords])
    out = _out_file(output)
    delim = cfg.field_delim
    with open(out, "w") as fh:
        for key, recs in sorted(by_key.items()):
            recs.sort(key=sort_key)
            flat = delim.join(tok for rec in recs for tok in rec)
            fh.write(f"{key}{delim}{flat}\n")
    return JobResult("sequenceGenerator",
                     {"Basic:Entities": len(by_key)}, [out], by_key)


# ================================================================ association
@job("frequentItemsApriori", "fia",
     "org.avenir.association.FrequentItemsApriori", "apriori")
def apriori_job(cfg: JobConfig, inputs: List[str], output: str) -> JobResult:
    """All k-rounds internal; per-k itemset files written like the
    reference's per-round outputs (FrequentItemsApriori.java:123-126)."""
    from avenir_tpu.models.association import (FrequentItemsApriori,
                                               StreamingTransactionSource,
                                               TransactionSet)

    miner = FrequentItemsApriori(
        support_threshold=cfg.assert_float("support.threshold"),
        max_length=cfg.get_int("item.set.length", 3),
        emit_trans_id=cfg.get_bool("emit.trans.id", False),
    )
    trans_id_ord = cfg.get_int("tans.id.ord", 0)
    skip = cfg.get_int("skip.field.count", 1)
    marker = cfg.get("infreq.item.marker")
    total_bytes = sum(os.path.getsize(p) for p in inputs
                      if os.path.exists(p))
    in_ram = (cfg.get("stream.block.size.mb") is None
              and total_bytes < (256 << 20))
    # timer before the in-RAM probe's file read: RowsPerSec must price
    # both paths' I/O identically (see gsp_job)
    t0 = time.perf_counter()
    if in_ram:
        # space/tab/CR trim: both apriori entry points and the native
        # counting pass must agree on token identity
        rows = [[t.strip(" \t\r") for t in ln.split(cfg.field_delim_regex)]
                for path in inputs for ln in _read_lines(path)]
        # the in-RAM cost is the [N, V] multi-hot matrix, which can dwarf
        # the file bytes for a wide item catalog — gate on its footprint
        vocab = {tok for row in rows for tok in row[skip:]
                 if tok and tok != marker}
        in_ram = len(rows) * max(len(vocab), 1) < (2 << 30)
    if in_ram:
        # in-RAM input: one upload, device-resident across all k rounds
        # (_contain_counts_resident — one dispatch per k, not per block)
        levels = miner.mine(TransactionSet.from_rows(
            rows, trans_id_ord=trans_id_ord, skip_field_count=skip,
            marker=marker))
        n_rows = len(rows)
    else:
        # beyond-RAM (or explicitly chunked): one streamed scan per
        # itemset length — the reference's per-k MR jobs over the same
        # HDFS input, bit-packed over the frequent vocabulary after k=1,
        # and per-k re-scans replay the pass-1 encoded-block cache
        # instead of re-parsing CSV; host RSS stays O(block) at any size
        src = StreamingTransactionSource(
            inputs, delim=cfg.field_delim_regex,
            trans_id_ord=trans_id_ord, skip_field_count=skip, marker=marker,
            block_bytes=int(cfg.get_float("stream.block.size.mb", 64.0)
                            * (1 << 20)),
            spill_cache=cfg.get_bool("stream.encoded.cache", True),
            cache_budget_bytes=_cache_budget(cfg))
        _attach_sidecar_opts(src, cfg)
        levels = miner.mine_stream(src)
        n_rows = src.n_trans
        cache_counters = _cache_counters(src)
        src.close()
    counters = {"Apriori:MaxLength": len(levels),
                **throughput_counters(n_rows, time.perf_counter() - t0),
                **(cache_counters if not in_ram else {})}
    outs = _write_apriori_outputs(cfg, output, levels)
    return JobResult("frequentItemsApriori", counters, outs, levels)


@job("associationRuleMiner", "arm",
     "org.avenir.association.AssociationRuleMiner")
def rule_miner_job(cfg: JobConfig, inputs: List[str], output: str) -> JobResult:
    from avenir_tpu.models.association import AssociationRuleMiner, ItemSetList

    miner = AssociationRuleMiner(
        conf_threshold=cfg.assert_float("conf.threshold"),
        max_ante_size=cfg.get_int("max.ante.size", 3),
    )
    levels = []
    for k, path in enumerate(inputs, start=1):
        levels.append(ItemSetList.load(path, k, delim=cfg.field_delim))
    rules = miner.mine(levels)
    out = _out_file(output)
    delim = cfg.field_delim
    with open(out, "w") as fh:
        for r in rules:
            fh.write(f"{':'.join(r.antecedent)}{delim}{':'.join(r.consequent)}"
                     f"{delim}{r.confidence:.6f}{delim}{r.support:.6f}\n")
    return JobResult("associationRuleMiner", {"Rules:Count": len(rules)},
                     [out], rules)


@job("infrequentItemMarker", "iim",
     "org.avenir.association.InfrequentItemMarker")
def infrequent_item_marker_job(cfg: JobConfig, inputs: List[str],
                               output: str) -> JobResult:
    """Map-only pass replacing items not in the frequent-1-itemset file
    with a marker token (InfrequentItemMarker.java:41-46, run after the
    k=1 Apriori round to shrink later scans). Reads iim.item.set.file.path
    (must hold length-1 itemsets), iim.infreq.item.marker (default '*'),
    iim.skip.field.count (default 1)."""
    from avenir_tpu.models.association import InfrequentItemMarker, ItemSetList

    length = cfg.get_int("item.set.length", 1)
    if length != 1:
        raise ValueError("expecting item set of length 1")
    isl = ItemSetList.load(
        cfg.assert_get("item.set.file.path"), length,
        with_trans_ids=cfg.get_bool("contains.trans.id", True),
        delim=cfg.get("itemset.delim", ","))
    marker = InfrequentItemMarker(
        frequent_items=(s.items[0] for s in isl.item_sets),
        marker=cfg.get("infreq.item.marker", "*"),
        skip_field_count=cfg.get_int("skip.field.count", 1))
    out = _out_file(output)
    delim = cfg.field_delim
    n = marked = 0
    with open(out, "w") as fh:
        for path in inputs:
            for ln in _read_lines(path):
                row = [t.strip() for t in ln.split(cfg.field_delim_regex)]
                marked_row = marker.mark_row(row)
                marked += sum(a != b for a, b in zip(row, marked_row))
                n += 1
                fh.write(delim.join(marked_row) + "\n")
    return JobResult("infrequentItemMarker",
                     {"Basic:Records": n, "Marker:Replaced": marked}, [out])


# ===================================================================== markov
@job("markovStateTransitionModel", "mst",
     "org.avenir.markov.MarkovStateTransitionModel",
     "org.avenir.spark.sequence.MarkovStateTransitionModel")
def markov_model_job(cfg: JobConfig, inputs: List[str], output: str) -> JobResult:
    """Per-class matrices via mst.* keys (the Hadoop job). With
    `id.field.ordinals` set (the Spark surface's HOCON key,
    MarkovStateTransitionModel.scala:51-52), builds one matrix PER ENTITY
    key — the multi-tenant mode — with `seq.start.ordinal` marking where
    the state sequence begins and optional `class.attr.ordinal` splitting
    each entity's matrix by class; sections are emitted as `entity:<key>`."""
    from avenir_tpu.core.stream import stream_job_lines
    from avenir_tpu.models.markov import MarkovStateTransitionModel

    states = cfg.get_list("model.states") or cfg.assert_list("state.list")
    scale = cfg.get_int("trans.prob.scale", 1000)
    id_ords = cfg.get_int_list("id.field.ordinals")
    out = _out_file(output)
    # bigram counts are additive, so both modes fold streamed line blocks
    # (the mapper's one-line-at-a-time contract,
    # MarkovStateTransitionModel.java:116-133) at O(block) host RSS
    if id_ords is not None:
        class_ord = cfg.get_int("class.attr.ordinal")
        # mandatory in the Spark reference (getMandatoryIntParam, :54);
        # the convenience default must skip the class column too
        key_ords = list(id_ords) + ([class_ord]
                                    if class_ord is not None else [])
        seq_start = cfg.get_int(
            "seq.start.ordinal",
            max(key_ords) + 1 if key_ords else 0)
        delim = cfg.field_delim_regex
        model = MarkovStateTransitionModel(states, scale=scale)
        from avenir_tpu.native.ingest import (extract_column_native,
                                              native_seq_ready,
                                              seq_encode_native)

        if native_seq_ready(delim):
            # native path: states CSR-encode natively; only the (open-
            # vocabulary) entity key columns materialize as strings
            from avenir_tpu.core.stream import stream_job_byte_blocks

            model.class_labels = []
            model.counts = np.zeros((0,) + model.counts.shape[1:],
                                    np.float64)
            index: Dict[str, int] = {}
            for data in stream_job_byte_blocks(cfg, inputs):
                enc = seq_encode_native(data, delim, states)
                lens = np.diff(enc[1])
                if key_ords:
                    # rows too short to carry every key column are a
                    # crisp error on BOTH engines
                    short = lens <= max(key_ords)
                    if short.any():
                        raise ValueError(
                            f"row {int(np.argmax(short))} has no "
                            f"id/class field (ordinal {max(key_ords)})")
                    cols = [extract_column_native(data, delim, o)
                            for o in key_ords]
                    keys = cols[0]
                    for col in cols[1:]:
                        keys = np.char.add(np.char.add(keys, ","), col)
                else:
                    # degenerate config (no id/class columns): one key
                    keys = np.full(lens.shape[0], "")
                # first-seen entity order, vectorized: unique keys
                # ordered by first occurrence, then row indices
                uniq, first, inv = np.unique(
                    keys, return_index=True, return_inverse=True)
                gidx = np.empty(uniq.shape[0], np.int64)
                for u in np.argsort(first):
                    key = str(uniq[u])
                    gi = index.get(key)
                    if gi is None:
                        gi = len(index)
                        index[key] = gi
                        model.class_labels.append(key)
                    gidx[u] = gi
                if len(index) > model.counts.shape[0]:
                    model.counts = np.pad(
                        model.counts,
                        ((0, len(index) - model.counts.shape[0]),
                         (0, 0), (0, 0)))
                model.fit_csr(enc[0], enc[1], skip=seq_start, y=gidx[inv])
        else:
            for lines in stream_job_lines(cfg, inputs):
                seqs: List[List[str]] = []
                entity_of_row: List[str] = []
                for ln in lines:
                    toks = [t.strip(" \t\r") for t in ln.split(delim)]
                    if key_ords and len(toks) <= max(key_ords):
                        raise ValueError(
                            f"row {len(entity_of_row)} has no id/class "
                            f"field (ordinal {max(key_ords)})")
                    key = ",".join(toks[o] for o in id_ords)
                    if class_ord is not None:
                        key += f",{toks[class_ord]}"
                    entity_of_row.append(key)
                    seqs.append(toks[seq_start:])
                model.fit_entities(seqs, entity_of_row)
        entities = model.class_labels or []
        if not entities:
            raise ValueError(
                f"markovStateTransitionModel: empty input "
                f"(no records in {inputs})")
        model.save(out, delim=cfg.field_delim, marker="entity")
        return JobResult("markovStateTransitionModel",
                         {"Entities:Count": len(entities)}, [out], model)

    # per-class mode: the fold sink doubles as the shared-scan sink
    # (_MarkovPerClassFold) — native CSR encode per raw byte block when
    # the C encoder is built, line decode + fit otherwise
    from avenir_tpu.core.stream import stream_job_byte_blocks

    fold = _MarkovPerClassFold(cfg, inputs)
    # the fold dispatches on SidecarBytesBlock (consume_encoded), so the
    # feed opts into the bytes-kind sidecar at this job's skip count —
    # a verified repeat scan fits from packed codes without a tokenizer
    _drive_fold(fold,
                stream_job_byte_blocks(cfg, inputs,
                                       sidecar_skip=fold.skip
                                       if fold.native else None),
                "markovStateTransitionModel")
    return _finish_fold(fold, output, "markovStateTransitionModel")


@job("markovModelClassifier", "mmc",
     "org.avenir.markov.MarkovModelClassifier",
     "org.avenir.spark.sequence.MarkovModelClassifier")
def markov_classifier_job(cfg: JobConfig, inputs: List[str], output: str) -> JobResult:
    from avenir_tpu.models.markov import (MarkovModelClassifier,
                                          MarkovStateTransitionModel)

    model = MarkovStateTransitionModel.load(
        cfg.assert_get("mm.model.path"), delim=cfg.field_delim)
    pos, neg = cfg.assert_list("class.labels")
    clf = MarkovModelClassifier(
        model, pos, neg,
        threshold=cfg.get_float("log.odds.threshold", 0.0))
    skip = cfg.get_int("skip.field.count", 1)
    class_ord = cfg.get_int("class.label.field.ord") \
        if cfg.get_bool("validation.mode", False) else None
    from avenir_tpu.core.stream import stream_job_lines

    out = _out_file(output)
    delim = cfg.field_delim
    counters: Dict[str, float] = {}
    actual, predicted = [], []
    with open(out, "w") as fh:
        # map-only row transform at O(block): classify per line block
        for lines in stream_job_lines(cfg, inputs):
            ids, seqs, labels = _parse_sequences(
                lines, cfg.field_delim_regex, skip, class_ord)
            cls, scores = clf.predict(seqs)
            for rid, c, s in zip(ids, cls, scores):
                fh.write(f"{rid}{delim}{c}{delim}{s:.6f}\n")
            if class_ord is not None:
                actual += labels
                predicted += list(cls)
    if actual:
        lab = [pos, neg]
        counters = _validate(
            lab, np.array([lab.index(a) for a in actual]),
            np.array([lab.index(p) for p in predicted]), 0)
    return JobResult("markovModelClassifier", counters, [out])


@job("hiddenMarkovModelBuilder", "hmmb",
     "org.avenir.markov.HiddenMarkovModelBuilder")
def hmm_builder_job(cfg: JobConfig, inputs: List[str], output: str) -> JobResult:
    """Fully-tagged input: `obs<sub.field.delim>state` tokens after the skip
    fields (HiddenMarkovModelBuilder.java:136-153). With
    `hmmb.partially.tagged=true`, tokens are bare observations except the
    ones matching hmmb.model.states, and `hmmb.window.function` spreads the
    state->obs counts around each tagged position (:174-259)."""
    from avenir_tpu.core.stream import stream_job_lines
    from avenir_tpu.models.markov import HiddenMarkovModelBuilder

    states = cfg.assert_list("model.states")
    obs = cfg.assert_list("model.observations")
    sub = cfg.get("sub.field.delim", ":")
    skip = cfg.get_int("skip.field.count", 1)
    builder = HiddenMarkovModelBuilder(states, obs)
    # per-sequence count accumulation over streamed line blocks (the
    # mapper contract, HiddenMarkovModelBuilder.java:136-153)
    if cfg.get_bool("partially.tagged", False):
        wf = [int(v) for v in cfg.assert_list("window.function")]
        for lines in stream_job_lines(cfg, inputs):
            _, seqs, _ = _parse_sequences(lines, cfg.field_delim_regex, skip)
            for seq in seqs:
                builder.add_partially_tagged(seq, wf)
    else:
        delim = cfg.field_delim_regex
        from avenir_tpu.native.ingest import (native_seq_ready,
                                              seq_encode_native)

        if native_seq_ready(delim):
            # native path: encode whole `obs:state` pair tokens against
            # the state-major pair vocabulary straight from byte blocks
            from avenir_tpu.core.stream import stream_job_byte_blocks

            vocab = [f"{ov}{sub}{sv}" for sv in states for ov in obs]
            for data in stream_job_byte_blocks(cfg, inputs):
                # cannot be None: availability + delim pre-checked
                enc = seq_encode_native(data, delim, vocab)
                builder.add_csr(*enc, skip=skip)
        else:
            for lines in stream_job_lines(cfg, inputs):
                _, seqs, _ = _parse_sequences(lines, delim, skip)
                for seq in seqs:
                    pairs = [tok.split(sub) for tok in seq]
                    builder.add([p[1] for p in pairs], [p[0] for p in pairs])
    hmm = builder.finish()
    out = _out_file(output)
    hmm.save(out, delim=cfg.field_delim)
    return JobResult("hiddenMarkovModelBuilder", {}, [out], hmm)


@job("viterbiStatePredictor", "vsp",
     "org.avenir.markov.ViterbiStatePredictor")
def viterbi_job(cfg: JobConfig, inputs: List[str], output: str) -> JobResult:
    from avenir_tpu.models.markov import HiddenMarkovModel, ViterbiDecoder

    hmm = HiddenMarkovModel.load(cfg.assert_get("hmm.model.path"),
                                 delim=cfg.field_delim)
    decoder = ViterbiDecoder(hmm)
    skip = 1 if cfg.get_int("id.field.ordinal", 0) >= 0 else 0
    out = _out_file(output)
    delim = cfg.field_delim
    with open(out, "w") as fh:
        for path in inputs:
            ids, seqs, _ = _read_sequences(path, cfg.field_delim_regex, skip)
            decoded = decoder.decode(seqs)
            for rid, states in zip(ids, decoded):
                fh.write(delim.join([rid] + list(states)) + "\n")
    return JobResult("viterbiStatePredictor", {}, [out])


@job("probabilisticSuffixTree", "pstg",
     "org.avenir.markov.ProbabilisticSuffixTreeGenerator")
def pst_job(cfg: JobConfig, inputs: List[str], output: str) -> JobResult:
    from avenir_tpu.models.markov import ProbabilisticSuffixTree

    skip = cfg.get_int("skip.field.count", 1)
    seqs = []
    for path in inputs:
        _, ss, _ = _read_sequences(path, cfg.field_delim_regex, skip)
        seqs += ss
    symbols = sorted({s for seq in seqs for s in seq})
    pst = ProbabilisticSuffixTree(
        symbols, max_depth=cfg.get_int("max.seq.length", 3)).fit(seqs)
    out = _out_file(output)
    delim = cfg.field_delim
    with open(out, "w") as fh:
        for ctx in sorted(pst.counts):
            counts = pst.counts[ctx]
            total = float(counts.sum()) or 1.0
            for si, sym in enumerate(pst.symbols):
                if counts[si] > 0:
                    fh.write(f"{''.join(ctx) or '$'}{delim}{sym}{delim}"
                             f"{counts[si] / total:.6f}\n")
    return JobResult("probabilisticSuffixTree", {}, [out], pst)


# ============================================================ regress / discr
@job("logisticRegression", "lrj",
     "org.avenir.regress.LogisticRegressionJob")
def logistic_regression_job(cfg: JobConfig, inputs: List[str], output: str) -> JobResult:
    """In-process epochs replace the driver loop of SURVEY §3.6; the
    coefficient history still appends to `coeff.file.path` and the result
    counters carry the reference's CONVERGED(100)/NOT_CONVERGED(101) exit
    status (LogisticRegressionJob.java:95-119)."""
    from avenir_tpu.models.regress import LogisticRegression

    ds = _dataset(inputs[0], cfg)
    lr = LogisticRegression(
        iteration_limit=cfg.get_int("iteration.limit", 10),
        convergence_criteria=cfg.get("convergence.criteria", "iterLimit"),
        convergence_threshold=cfg.get_float("convergence.threshold", 5.0),
        pos_class=cfg.get("positive.class.value"),
    ).fit(ds)
    coeff_path = cfg.get("coeff.file.path") or _out_file(output, "coeff.txt")
    lr.save_coeff_history(coeff_path, delim=cfg.field_delim)
    return JobResult(
        "logisticRegression",
        {"Regression:ExitStatus": lr.check_convergence()}, [coeff_path], lr)


@job("fisherDiscriminant", "fid",
     "org.avenir.discriminant.FisherDiscriminant")
def fisher_job(cfg: JobConfig, inputs: List[str], output: str) -> JobResult:
    from avenir_tpu.core.stream import stream_job_inputs

    # the fold sink doubles as the shared-scan sink (_FisherFold)
    fold = _FisherFold(cfg, inputs, None)
    _drive_fold(fold, stream_job_inputs(cfg, inputs, _schema(cfg)),
                "fisherDiscriminant")
    return _finish_fold(fold, output, "fisherDiscriminant")


# ======================================================================= text
@job("wordCounter", "wco", "org.avenir.text.WordCounter",
     "org.avenir.sanity.WordCount")
def word_counter_job(cfg: JobConfig, inputs: List[str], output: str) -> JobResult:
    from avenir_tpu.models.text import WordCounter

    from avenir_tpu.core.stream import stream_job_lines

    wc = WordCounter(
        text_field_ordinal=cfg.get_int("text.field.ordinal", -1),
        delim=cfg.field_delim_regex,
    )
    # token counts fold per streamed line block: host RSS is O(block +
    # vocabulary), never O(file) (WordCounter's mapper contract)
    counts: Dict[str, int] = {}
    for lines in stream_job_lines(cfg, inputs):
        for word, c in wc.count(lines):
            counts[word] = counts.get(word, 0) + c
    out = _out_file(output)
    delim = cfg.field_delim
    with open(out, "w") as fh:
        for word in sorted(counts):
            fh.write(f"{word}{delim}{counts[word]}\n")
    return JobResult("wordCounter", {"Words:Unique": len(counts)}, [out])


# ==================================================================== bandits
@job("greedyRandomBandit", "grb", "org.avenir.reinforce.GreedyRandomBandit")
@job("auerDeterministic", "aue", "org.avenir.reinforce.AuerDeterministic")
@job("randomFirstGreedyBandit", "rfg",
     "org.avenir.reinforce.RandomFirstGreedyBandit")
@job("softMaxBandit", "smb", "org.avenir.reinforce.SoftMaxBandit")
def bandit_job(cfg: JobConfig, inputs: List[str], output: str) -> JobResult:
    """One decision round of a batch bandit: input = group item stats rows
    `group,item,count,reward` (chombo RunningAggregator output the tutorial
    loops back, resource/price_optimize_tutorial.txt:55-82); output = the
    selected items per group for the round."""
    from avenir_tpu.models.bandits import GroupBanditData, make_bandit_job

    # job name = the registry key the caller used (one impl serves all four)
    name = cfg.props.get("__job_name__", "greedyRandomBandit")
    batch = cfg.get_int("global.batch.size", 1)
    kw = {}
    if name == "greedyRandomBandit":
        kw = {
            "random_selection_prob": cfg.get_float("random.selection.prob", 0.1),
            "prob_reduction_algorithm": cfg.get("prob.reduction.algorithm",
                                                "linear"),
            "prob_reduction_constant": cfg.get_float("prob.reduction.constant",
                                                     1.0),
            "auer_greedy_constant": cfg.get_float("auer.greedy.constant", 1.0),
            "selection_unique": cfg.get_bool("selection.unique", False),
        }
    elif name == "softMaxBandit":
        kw = {"temp_constant": cfg.get_float("temp.constant", 1.0)}
    round_num = cfg.get_int("current.round.num", 1)
    data = GroupBanditData.from_rows(
        [[t.strip() for t in ln.split(cfg.field_delim_regex)]
         for p in inputs for ln in _read_lines(p)],
        count_ord=cfg.get_int("count.ordinal", 2),
        reward_ord=cfg.get_int("reward.ordinal", 3),
    )
    bj = make_bandit_job(name, batch, **kw)
    sel = bj.select(data, round_num)
    out = _out_file(output)
    with open(out, "w") as fh:
        data.write_selections(
            sel, fh, cfg.field_delim,
            output_decision_count=cfg.get_bool("output.decision.count",
                                               False))
    return JobResult(name, {"Bandit:Groups": len(data.group_ids)}, [out], sel)


# =================================================================== pipeline
@dataclass
class Stage:
    name: str
    job: str
    inputs: List[str]
    output: str
    conf_overrides: Dict[str, str] = field(default_factory=dict)


class Pipeline:
    """Replaces the resource/*.sh case-statement drivers: ordered named
    stages over one shared properties file; stage outputs feed later stage
    inputs by path (e.g. the knn.sh 5-stage flow, SURVEY §3.3). Run all
    stages or a single named one — the same way the shell scripts were
    invoked per-stage by hand.

    Failure handling (SURVEY §5): the reference delegates retry to Hadoop
    (`mapreduce.map.maxattempts=2`, knn.properties:5-6) and relies on jobs
    being re-runnable because all state is files. The same two properties
    hold here: a failed stage re-runs up to `mapreduce.map.maxattempts`
    times (every job rewrites its outputs from its inputs, so a retry is
    exactly a Hadoop task re-attempt), and `on_retry` is the observability
    hook (attempt log / fault-injection point in tests)."""

    def __init__(self, conf, stages: Sequence[Stage], on_retry=None):
        self.props = (load_properties(conf) if isinstance(conf, str)
                      else dict(conf))
        self.stages = list(stages)
        self.results: Dict[str, JobResult] = {}
        self.max_attempts = max(
            int(self.props.get("mapreduce.map.maxattempts", "2")), 1)
        self.on_retry = on_retry
        self.attempts: Dict[str, int] = {}

    def _stage_props(self, st: Stage) -> Dict[str, str]:
        props = dict(self.props)
        props.update(st.conf_overrides)
        return props

    def _run_stage(self, st: Stage) -> None:
        for attempt in range(1, self.max_attempts + 1):
            self.attempts[st.name] = attempt
            try:
                self.results[st.name] = run_job(
                    st.job, self._stage_props(st), st.inputs, st.output)
                break
            except Exception as exc:
                if attempt >= self.max_attempts:
                    raise
                if self.on_retry is not None:
                    self.on_retry(st.name, attempt, exc)

    def _fusable(self, st: Stage) -> bool:
        key = _REGISTRY.get(st.job)
        return key is not None and key[0] in _STREAM_FOLDS

    def run(self, only: Optional[str] = None,
            fuse: bool = False) -> Dict[str, JobResult]:
        """Run the stages. With fuse=True, maximal runs of CONSECUTIVE
        stages that read the same inputs and are shared-scan capable
        (stream_fold_names()) execute as ONE SharedScan pass via
        run_shared() — N jobs, one disk read + parse of the corpus. Any
        fused-group failure falls back to the existing one-job-one-scan
        per-stage path (with its usual retry semantics), so fusion is a
        pure optimization, never a new failure mode."""
        stages = [st for st in self.stages
                  if only is None or st.name == only]
        i = 0
        while i < len(stages):
            group = [stages[i]]
            if fuse and self._fusable(stages[i]):
                seen = {_REGISTRY[stages[i].job][0]}
                j = i + 1
                while (j < len(stages) and self._fusable(stages[j])
                       and stages[j].inputs == stages[i].inputs
                       and _REGISTRY[stages[j].job][0] not in seen):
                    group.append(stages[j])
                    seen.add(_REGISTRY[stages[j].job][0])
                    j += 1
            if len(group) >= 2:
                specs = [(st.job, self._stage_props(st), st.output)
                         for st in group]
                try:
                    shared = run_shared(specs, group[0].inputs)
                    for st in group:
                        # keyed lookup, not positional zip: immune to any
                        # future reordering of run_shared's result dict
                        self.results[st.name] = shared[_REGISTRY[st.job][0]]
                        self.attempts[st.name] = 1
                    i += len(group)
                    continue
                except Exception as exc:
                    # fused attempt failed (mixed configs, a job error,
                    # ...): the one-job-one-scan path is the fallback
                    if self.on_retry is not None:
                        self.on_retry(
                            "+".join(st.name for st in group), 1, exc)
            for st in group:
                self._run_stage(st)
            i += len(group)
        return self.results


def run_from_cli(argv: Sequence[str]) -> JobResult:
    """`python -m avenir_tpu <jobName> --conf <props> IN... OUT` — the
    `hadoop jar avenir.jar <class> -Dconf.path=<props> IN OUT` surface.

    `python -m avenir_tpu serve ...` instead starts the resident
    multi-tenant job server — over a stdin/filesystem request spool
    (avenir_tpu.server.spool — batched shared scans, warm caches,
    byte-budget admission; no network dependency) or, with
    `--listen HOST:PORT`, behind the JSON-over-HTTP edge
    (avenir_tpu.net.listener — 429 backpressure wired to the admission
    model). `python -m avenir_tpu fleet --root DIR --hosts N` runs N
    server processes behind the affinity router (avenir_tpu.net.fleet),
    and `python -m avenir_tpu stats <paths...>` renders one server's
    live metrics.json — or a fleet's, merged through the additive
    histogram algebra (avenir_tpu.obs.report)."""
    import argparse

    if argv and argv[0] == "serve":
        from avenir_tpu.server.spool import serve_main

        rc = serve_main(list(argv[1:]))
        if rc:
            sys.exit(rc)
        return JobResult("serve")

    if argv and argv[0] == "fleet":
        from avenir_tpu.net.fleet import fleet_main

        rc = fleet_main(list(argv[1:]))
        if rc:
            sys.exit(rc)
        return JobResult("fleet")

    if argv and argv[0] == "stats":
        from avenir_tpu.obs.report import stats_main

        rc = stats_main(list(argv[1:]))
        if rc:
            sys.exit(rc)
        return JobResult("stats")

    if argv and argv[0] == "tune":
        from avenir_tpu.tune.report import tune_main

        rc = tune_main(list(argv[1:]))
        if rc:
            sys.exit(rc)
        return JobResult("tune")

    ap = argparse.ArgumentParser(prog="avenir_tpu")
    ap.add_argument("jobname", help="job name or reference Tool class")
    ap.add_argument("--conf", required=False, default=None,
                    help="properties file (the -Dconf.path analog)")
    ap.add_argument("--incremental", action="store_true",
                    help="delta-scan a streamed job: restore the last "
                         "fold-state checkpoint and fold only appended "
                         "blocks (run_incremental)")
    ap.add_argument("--shard", type=int, default=0, metavar="N",
                    help="run a streamed job's scan across N worker "
                         "processes: over-partitioned byte-range blocks "
                         "claimed through the first-commit-wins block "
                         "ledger, merged via the registered fold-state "
                         "algebra (avenir_tpu.dist.run_sharded); "
                         "byte-identical to the solo scan")
    ap.add_argument("--autotune", action="store_true",
                    help="close the telemetry loop: apply the profile "
                         "store's tuned knobs to this run and record its "
                         "signals for the next (sets stream.autotune)")
    ap.add_argument("paths", nargs="*", help="input paths... output path")
    # intermixed: `jobname --conf props IN OUT` splits the positionals
    # around the optional, which plain parse_args cannot reassemble
    args = ap.parse_intermixed_args(argv)
    if not args.paths:
        ap.error("expected IN... OUT paths (at least an output path)")
    # a down accelerator tunnel hangs backend init in-process with no
    # exception; probe + degrade to CPU so CLI jobs survive an outage
    from avenir_tpu.utils.devices import ensure_usable_backend

    degraded = ensure_usable_backend()
    if degraded:
        print(f"WARNING: accelerator unavailable ({degraded}); "
              "running on CPU", file=sys.stderr)
    # a .conf path routes through the HOCON block loader in run_job
    props = args.conf if args.conf else {}
    if args.autotune:
        # splice the opt-in key into the properties; HOCON confs carry
        # per-block keys, so the flag cannot reach inside one — set
        # stream.autotune in the job's block instead
        if isinstance(props, str):
            if props.endswith(".conf"):
                ap.error("--autotune cannot rewrite a HOCON .conf; set "
                         "stream.autotune = true in the job's block")
            props = dict(load_properties(props))
        else:
            props = dict(props)
        props["stream.autotune"] = "true"
    short = args.jobname.rsplit(".", 1)[-1]
    name = args.jobname if args.jobname in _REGISTRY else short[0].lower() + short[1:]
    inputs, output = args.paths[:-1], args.paths[-1]
    if args.shard and args.incremental and (
            _REGISTRY[name][0] if name in _REGISTRY else name) in (
            "frequentItemsApriori", "candidateGenerationWithSelfJoin"):
        # every other family composes the two drivers (run_sharded_refresh);
        # the miners' per-k rounds re-scan the whole corpus per candidate
        # length, so their 'incremental refresh' would be a hidden full
        # re-mine — loud over silent
        ap.error("--shard and --incremental cannot compose for the "
                 "miners: per-k candidate rounds re-scan the whole "
                 "corpus; run --shard (full re-mine) or --incremental "
                 "alone")
    if args.shard and args.autotune:
        # the sharded driver does not consult the profile store yet;
        # accepting the flag would silently tune nothing — the same
        # loud-over-silent contract the knob guard holds everywhere
        ap.error("--shard does not support --autotune yet; the sharded "
                 "driver applies no tuned knobs")
    if args.shard and args.incremental:
        from avenir_tpu.dist.driver import run_sharded_refresh

        res = run_sharded_refresh(name, props, inputs, output,
                                  procs=args.shard)
    elif args.shard:
        from avenir_tpu.dist import run_sharded

        res = run_sharded(name, props, inputs, output,
                          procs=args.shard)
    else:
        runner = run_incremental if args.incremental else run_job
        res = runner(name, props, inputs, output)
    print(json.dumps({"job": res.name, "counters": res.counters,
                      "outputs": res.outputs}))
    return res


if __name__ == "__main__":           # `python -m avenir_tpu.runner ...`
    run_from_cli(sys.argv[1:])       # same surface as `python -m avenir_tpu`
