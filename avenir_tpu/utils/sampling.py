"""Distribution samplers + histogram for synthetic data generation.

Reference (python/lib/sampler.py + stats.py, SURVEY §2.10): rejection
samplers (Gaussian over mean±3σ, non-parametric over a binned histogram), a
Metropolis-Hastings sampler with a Gaussian random-walk proposal (optionally
a local/global mixture), and a Histogram container — the machinery behind
every `resource/*.py` synthetic data generator.

TPU-first design: samplers are vectorized — `sample(n)` draws n values in
one shot from numpy Generator primitives (inverse-CDF for the histogram
instead of scalar accept/reject loops); the Metropolis chain is a
`lax.scan` so long chains run as one compiled program.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp


class Histogram:
    """Binned distribution over [xmin, xmin + bin_width*(n-1)]
    (stats.py Histogram)."""

    def __init__(self, xmin: float, bin_width: float,
                 values: Optional[Sequence[float]] = None,
                 xmax: Optional[float] = None):
        self.xmin = float(xmin)
        self.bin_width = float(bin_width)
        if values is not None:
            self.bins = np.asarray(values, np.float64)
            self.xmax = self.xmin + self.bin_width * (len(self.bins) - 1)
        else:
            self.xmax = float(xmax)
            n = int((self.xmax - self.xmin) / self.bin_width) + 1
            self.bins = np.zeros(n, np.float64)

    @classmethod
    def initialized(cls, xmin, bin_width, values) -> "Histogram":
        return cls(xmin, bin_width, values=values)

    @classmethod
    def uninitialized(cls, xmin, xmax, bin_width) -> "Histogram":
        return cls(xmin, bin_width, xmax=xmax)

    def _bin_index(self, x) -> np.ndarray:
        return np.clip(((np.asarray(x) - self.xmin) // self.bin_width)
                       .astype(np.int64), 0, len(self.bins) - 1)

    def add(self, x: np.ndarray) -> None:
        np.add.at(self.bins, self._bin_index(x), 1.0)

    def value(self, x) -> np.ndarray:
        return self.bins[self._bin_index(x)]

    def bounded(self, x):
        return np.clip(x, self.xmin, self.xmax)

    def min_max(self) -> Tuple[float, float]:
        return self.xmin, self.xmax

    def normalized(self) -> np.ndarray:
        s = self.bins.sum()
        return self.bins / s if s > 0 else self.bins

    def cum_distr(self) -> np.ndarray:
        """Cumulative distribution over bins (stats.py cumDistr)."""
        return np.cumsum(self.normalized())

    def percentile(self, percent: float) -> float:
        """Value at the given percentile (stats.py percentile)."""
        if not 0 <= percent <= 100:
            raise ValueError("percent must be in [0, 100]")
        cum = self.cum_distr()
        idx = int(np.searchsorted(cum, percent / 100.0))
        idx = min(idx, len(self.bins) - 1)
        return self.xmin + idx * self.bin_width

    def cum_value(self, x) -> np.ndarray:
        """Cumulative probability at value x (stats.py cumValue)."""
        return self.cum_distr()[self._bin_index(x)]


@dataclass
class GaussianSampler:
    """Gaussian sampler truncated to mean±3σ (GaussianRejectSampler,
    sampler.py:25 — same distribution, drawn by redraw instead of a scalar
    accept/reject loop)."""

    mean: float
    std_dev: float
    rng: np.random.Generator = field(
        default_factory=lambda: np.random.default_rng())

    def sample(self, n: Optional[int] = None):
        shape = (n,) if n is not None else (1,)
        lo, hi = self.mean - 3 * self.std_dev, self.mean + 3 * self.std_dev
        out = self.rng.normal(self.mean, self.std_dev, shape)
        bad = (out < lo) | (out > hi)
        while bad.any():
            out[bad] = self.rng.normal(self.mean, self.std_dev, bad.sum())
            bad = (out < lo) | (out > hi)
        return out if n is not None else float(out[0])


@dataclass
class NonParamSampler:
    """Sampler over an arbitrary binned distribution (NonParamRejectSampler,
    sampler.py:50) via inverse CDF on the histogram weights."""

    xmin: float
    bin_width: float
    values: Sequence[float]
    rng: np.random.Generator = field(
        default_factory=lambda: np.random.default_rng())

    def sample(self, n: Optional[int] = None):
        p = np.asarray(self.values, np.float64)
        p = p / p.sum()
        k = self.rng.choice(len(p), size=n if n is not None else 1, p=p)
        out = self.xmin + k * self.bin_width
        return out if n is not None else float(out[0])


class MetropolisSampler:
    """Metropolis chain over a histogram target (MetropolitanSampler,
    sampler.py:78): Gaussian random-walk proposal, optional local/global
    mixture, thinning via `skip`. The whole chain is one `lax.scan`."""

    def __init__(self, proposal_std: float, xmin: float, bin_width: float,
                 values: Sequence[float], seed: int = 0,
                 global_proposal_std: Optional[float] = None,
                 mixture_threshold: float = 0.5):
        self.target = Histogram.initialized(xmin, bin_width, values)
        self.proposal_std = float(proposal_std)
        self.global_proposal_std = global_proposal_std
        self.mixture_threshold = float(mixture_threshold)
        self.key = jax.random.key(seed)
        self.cur = float(np.random.default_rng(seed).uniform(
            self.target.xmin, self.target.xmax))
        self.trans_count = 0

    def set_mixture_proposal(self, global_std: float, threshold: float):
        self.global_proposal_std = float(global_std)
        self.mixture_threshold = float(threshold)

    def sample(self, n: int = 1, skip: int = 1) -> np.ndarray:
        """Draw n samples, advancing `skip` proposals per draw."""
        bins = jnp.asarray(self.target.bins)
        xmin, xmax = self.target.xmin, self.target.xmax
        bw = self.target.bin_width
        pstd = self.proposal_std
        gstd = self.global_proposal_std
        thr = self.mixture_threshold

        def value(x):
            idx = jnp.clip(((x - xmin) // bw).astype(jnp.int32),
                           0, bins.shape[0] - 1)
            return bins[idx]

        def propose(key, x):
            if gstd is None:
                return x + pstd * jax.random.normal(key)
            ku, kn = jax.random.split(key)
            std = jnp.where(jax.random.uniform(ku) < thr, pstd, gstd)
            return x + std * jax.random.normal(kn)

        def one_step(carry, key):
            x, fx, acc = carry
            kp, ka = jax.random.split(key)
            nxt = jnp.clip(propose(kp, x), xmin, xmax)
            fn = value(nxt)
            take = jax.random.uniform(ka) < fn / jnp.maximum(fx, 1e-30)
            x2 = jnp.where(take, nxt, x)
            return (x2, jnp.where(take, fn, fx), acc + take.astype(jnp.int32)), x2

        keys = jax.random.split(self.key, n * skip + 1)
        self.key = keys[0]
        fx0 = jnp.maximum(value(jnp.asarray(self.cur)), 1e-30)
        (x, _, acc), chain = jax.lax.scan(
            one_step, (jnp.asarray(self.cur), fx0, jnp.asarray(0)), keys[1:])
        self.cur = float(x)
        self.trans_count += int(acc)
        return np.asarray(chain)[skip - 1::skip][:n]
