"""Utility layer: metrics, counters, model math shared across algorithms."""
