"""Tracing / profiling / running stats.

The reference has no tracing or profiling at all — only log4j debug flags
and Hadoop counters (SURVEY §5: "New framework: jax.profiler traces +
per-phase wall clock; this is green-field"). This module is that
green-field piece:

- PhaseTimer: named per-phase wall-clock accounting for multi-stage jobs
  (the timing analog of the reference's per-job Hadoop counter groups).
- trace(): context manager around jax.profiler for TensorBoard-readable
  device traces of a region.
- RunningStats: mergeable count/mean/variance/min/max accumulator (the
  chombo SimpleStat role, SURVEY §0 dependency table) — moments add, so
  shard results combine exactly like the device psum path.
"""

from __future__ import annotations

import contextlib
import math
import threading
import time
from dataclasses import dataclass
from typing import Dict, Iterator, List


class PhaseTimer:
    """Accumulated wall clock per named phase.

    with timer.phase("ingest"): ...
    with timer.phase("train"): ...
    timer.report() -> {"ingest": seconds, ...}

    Thread-safe: phase exits mutate the accumulators under a lock, so
    one timer can be shared across server worker threads (phases that
    OVERLAP in time still sum their full durations — per-worker timers
    aggregated through :meth:`merge` are the per-thread view)."""

    def __init__(self):
        self.totals: Dict[str, float] = {}
        self.counts: Dict[str, int] = {}
        self._order: List[str] = []
        self._lock = threading.Lock()

    @contextlib.contextmanager
    def phase(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            with self._lock:
                if name not in self.totals:
                    self._order.append(name)
                    self.totals[name] = 0.0
                    self.counts[name] = 0
                self.totals[name] += dt
                self.counts[name] += 1

    def _snapshot(self) -> Dict[str, tuple]:
        with self._lock:
            return {name: (self.totals[name], self.counts[name])
                    for name in self._order}

    def merge(self, other: "PhaseTimer") -> "PhaseTimer":
        """Fold another timer's accumulators into this one (additive,
        like every fold-state merge in the repo) — how per-worker
        timers aggregate into one report. Snapshot-then-apply: the two
        locks are never held together, so ``a.merge(b)`` can never
        deadlock against a concurrent ``b.merge(a)``."""
        for name, (total, count) in other._snapshot().items():
            with self._lock:
                if name not in self.totals:
                    self._order.append(name)
                    self.totals[name] = 0.0
                    self.counts[name] = 0
                self.totals[name] += total
                self.counts[name] += count
        return self

    def report(self) -> Dict[str, float]:
        with self._lock:
            return {name: self.totals[name] for name in self._order}

    def summary(self) -> str:
        with self._lock:
            total = sum(self.totals.values()) or 1.0
            lines = []
            for name in self._order:
                t = self.totals[name]
                lines.append(
                    f"{name:>20s}  {t:9.3f}s  {100 * t / total:5.1f}%  "
                    f"x{self.counts[name]}")
        return "\n".join(lines)


@contextlib.contextmanager
def trace(log_dir: str) -> Iterator[None]:
    """jax.profiler device trace of the enclosed region, written for
    TensorBoard / xprof. No-ops cleanly if the profiler can't start (e.g.
    an already-active trace).

    The region also records into the avenir-trace span recorder
    (``jax.profiler.trace`` span with the device trace dir and whether
    the profiler actually started as attrs), so a host-side Chrome
    trace links each device-trace capture to the phase that took it."""
    import jax

    from avenir_tpu import obs

    started = False
    try:
        jax.profiler.start_trace(log_dir)
        started = True
    except Exception:
        pass
    t0 = obs.now()
    try:
        yield
    finally:
        if started:
            try:
                jax.profiler.stop_trace()
            except Exception:
                pass
        obs.record("jax.profiler.trace", t0, log_dir=log_dir,
                   started=started)


@dataclass
class RunningStats:
    """Mergeable first/second-moment accumulator (chombo SimpleStat role)."""

    count: float = 0.0
    total: float = 0.0
    total_sq: float = 0.0
    min_val: float = math.inf
    max_val: float = -math.inf

    def add(self, *values: float) -> "RunningStats":
        for v in values:
            self.count += 1
            self.total += v
            self.total_sq += v * v
            self.min_val = min(self.min_val, v)
            self.max_val = max(self.max_val, v)
        return self

    def add_array(self, arr) -> "RunningStats":
        import numpy as np

        a = np.asarray(arr, np.float64).ravel()
        if a.size:
            self.count += a.size
            self.total += float(a.sum())
            self.total_sq += float((a * a).sum())
            self.min_val = min(self.min_val, float(a.min()))
            self.max_val = max(self.max_val, float(a.max()))
        return self

    def merge(self, other: "RunningStats") -> "RunningStats":
        """Moments are additive — the host-side analog of psum-merging
        per-shard stats."""
        self.count += other.count
        self.total += other.total
        self.total_sq += other.total_sq
        self.min_val = min(self.min_val, other.min_val)
        self.max_val = max(self.max_val, other.max_val)
        return self

    @property
    def mean(self) -> float:
        return self.total / self.count if self.count else 0.0

    @property
    def variance(self) -> float:
        if self.count < 2:
            return 0.0
        m = self.mean
        return max((self.total_sq - self.count * m * m) / (self.count - 1), 0.0)

    @property
    def std(self) -> float:
        return math.sqrt(self.variance)


def enable_persistent_compilation_cache(
        cache_dir: str = "/tmp/jax_comp_cache",
        min_compile_secs: float = 1.0) -> bool:
    """Persistent XLA compilation cache, best-effort: cold compiles through
    a remote-chip tunnel cost tens of seconds per shape, and the bench /
    kernel-check programs are shape-stable across runs. Shared by every
    entry point so the cache location changes in one place. Returns
    whether the config was accepted (custom platforms may decline)."""
    import jax

    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          min_compile_secs)
        return True
    except Exception:
        return False
