"""Validation metrics: confusion matrix + counter groups.

The reference validates classifiers in-job by pushing TP/FN/TN/FP, accuracy,
recall and precision into Hadoop counters under a "Validation" group
(util/ConfusionMatrix.java, used at bayesian/BayesianPredictor.java:170-180
and knn/NearestNeighbor.java:300-312). Here the confusion matrix is computed
on device in one vectorized pass and surfaced as a plain dict of counters.
"""

from __future__ import annotations

from typing import Dict, Sequence

import numpy as np


class ConfusionMatrix:
    """Multi-class confusion matrix with the reference's binary counter names.

    `pos_class` marks which class index plays the "positive" role for the
    TP/FP/TN/FN counters (the reference takes the configured positive class
    value, e.g. bap.positive.class.value).
    """

    def __init__(self, class_values: Sequence[str], pos_class: int = 0):
        self.class_values = list(class_values)
        self.k = len(self.class_values)
        self.pos_class = pos_class
        self.matrix = np.zeros((self.k, self.k), dtype=np.int64)  # [actual, predicted]

    def add(self, actual: np.ndarray, predicted: np.ndarray) -> None:
        a = np.asarray(actual).astype(np.int64).ravel()
        p = np.asarray(predicted).astype(np.int64).ravel()
        np.add.at(self.matrix, (a, p), 1)

    # ------------------------------------------------------------- counters
    @property
    def total(self) -> int:
        return int(self.matrix.sum())

    @property
    def true_pos(self) -> int:
        c = self.pos_class
        return int(self.matrix[c, c])

    @property
    def false_neg(self) -> int:
        c = self.pos_class
        return int(self.matrix[c, :].sum() - self.matrix[c, c])

    @property
    def false_pos(self) -> int:
        c = self.pos_class
        return int(self.matrix[:, c].sum() - self.matrix[c, c])

    @property
    def true_neg(self) -> int:
        return self.total - self.true_pos - self.false_neg - self.false_pos

    def accuracy(self) -> float:
        t = self.total
        return float(np.trace(self.matrix)) / t if t else 0.0

    def recall(self) -> float:
        denom = self.true_pos + self.false_neg
        return self.true_pos / denom if denom else 0.0

    def precision(self) -> float:
        denom = self.true_pos + self.false_pos
        return self.true_pos / denom if denom else 0.0

    def counters(self) -> Dict[str, float]:
        """The reference's "Validation" counter group, percent-scaled like
        Hadoop counters (accuracy/recall/precision as int percent)."""
        return {
            "Validation:TruePositive": self.true_pos,
            "Validation:FalseNegative": self.false_neg,
            "Validation:TrueNegative": self.true_neg,
            "Validation:FalsePositive": self.false_pos,
            "Validation:Accuracy": int(100 * self.accuracy()),
            "Validation:Recall": int(100 * self.recall()),
            "Validation:Precision": int(100 * self.precision()),
        }

    def __repr__(self) -> str:
        return f"ConfusionMatrix(k={self.k}, total={self.total})"


class CostBasedArbitrator:
    """Misclassification-cost decision between two classes.

    Reference: util/CostBasedArbitrator.java, constructed as
    (negClass, posClass, falseNegCost, falsePosCost) and used by
    BayesianPredictor (:342-391, two-probability `arbitrate`) and
    NearestNeighbor (:383-387, positive-probability-threshold `classify`).
    Probabilities are int-percent scaled in the reference; both methods
    here are vectorized over numpy arrays and keep the reference's exact
    integer decision formulas."""

    def __init__(self, neg_class: str, pos_class: str,
                 false_neg_cost: float, false_pos_cost: float):
        self.neg_class = neg_class
        self.pos_class = pos_class
        self.false_neg_cost = false_neg_cost  # cost of missing a positive
        self.false_pos_cost = false_pos_cost  # cost of a false alarm

    def arbitrate(self, prob_neg: np.ndarray, prob_pos: np.ndarray) -> np.ndarray:
        """True -> positive class. CostBasedArbitrator.arbitrate:
        negCost = falseNegCost*posProb + negProb,
        posCost = falsePosCost*negProb + posProb, pick pos iff posCost<negCost."""
        pos, neg = np.asarray(prob_pos), np.asarray(prob_neg)
        neg_cost = self.false_neg_cost * pos + neg
        pos_cost = self.false_pos_cost * neg + pos
        return pos_cost < neg_cost

    def classify(self, prob_pos: np.ndarray) -> np.ndarray:
        """True -> positive class. CostBasedArbitrator.classify: positive
        iff posProb > falsePosCost*100 / (falsePosCost + falseNegCost)
        (integer division, as the reference computes it)."""
        thr = int(self.false_pos_cost * 100) // int(
            self.false_pos_cost + self.false_neg_cost)
        return np.asarray(prob_pos) > thr


def jit_cache_size(fn) -> int:
    """Number of compiled executables cached on a `jax.jit` callable, or
    -1 when the runtime doesn't expose it.

    Growth across calls == compile-cache misses == recompiles. This is
    the runtime cross-check for graftlint's `recompile-hazard` rule: the
    static analyzer promises a shape-stable fold never recompiles, and
    bench_scaling.py asserts this counter stays at the shape-bucket bound
    (pow2-quantized block/candidate axes → logarithmically many entries)
    instead of growing per block. If the two ever disagree, trust this
    counter and tighten the rule."""
    try:
        return int(fn._cache_size())
    except (AttributeError, TypeError):
        return -1


def throughput_counters(records: int, seconds: float) -> Dict[str, float]:
    """The regression-tripwire pair every streamed job should report:
    the Hadoop-style Basic:Records plus a derived Basic:RowsPerSec, so
    scale harnesses (tools/stream_scale_check.py, bench_scaling.py) get a
    non-null rows figure AND a rate to alarm on without re-deriving
    either. A non-positive wall clock (mocked timers) yields rate 0
    rather than inf/ZeroDivision."""
    rate = records / seconds if seconds > 0 else 0.0
    return {"Basic:Records": int(records),
            "Basic:RowsPerSec": round(rate, 1)}


class Counters:
    """A flat stand-in for Hadoop counter groups: "Group:Name" -> value."""

    def __init__(self) -> None:
        self.values: Dict[str, float] = {}

    def incr(self, key: str, amount: float = 1) -> None:
        self.values[key] = self.values.get(key, 0) + amount

    def set(self, key: str, value: float) -> None:
        self.values[key] = value

    def update(self, other: Dict[str, float]) -> None:
        self.values.update(other)

    def get(self, key: str, default: float = 0) -> float:
        return self.values.get(key, default)

    def __repr__(self) -> str:
        return f"Counters({self.values})"
