"""Small tabular model/stat containers from the reference util package.

TPU note: these are host-side model-file and bookkeeping objects — the
heavy counting that fills them runs in the device kernels (segment_sum /
cross_count); these classes only hold, normalize, and serialize results,
mirroring the reference's util classes (SURVEY §2.8).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Dict, Optional, Sequence

import numpy as np


class StateTransitionProbability:
    """Row-normalized scaled transition matrix
    (util/StateTransitionProbability.java:29, extends chombo TabularData):
    counts in, int-scaled (or float-precision) probabilities out."""

    def __init__(self, row_labels: Sequence[str], col_labels: Optional[Sequence[str]] = None,
                 scale: int = 100, float_precision: int = 3):
        self.row_labels = list(row_labels)
        self.col_labels = list(col_labels) if col_labels is not None else list(row_labels)
        self.scale = scale
        self.float_precision = float_precision
        self.table = np.zeros((len(self.row_labels), len(self.col_labels)), np.float64)

    def add(self, row: str, col: str, count: float = 1.0) -> None:
        self.table[self.row_labels.index(row), self.col_labels.index(col)] += count

    def set(self, row: str, col: str, value: float) -> None:
        self.table[self.row_labels.index(row), self.col_labels.index(col)] = value

    def normalize_rows(self) -> np.ndarray:
        """Probabilities scaled by `scale` and rounded (normalizeRows):
        integer matrix when scale > 1, rounded floats at scale 1."""
        prob = self.table / np.maximum(self.table.sum(axis=1, keepdims=True), 1e-12)
        scaled = prob * self.scale
        if self.scale > 1:
            return np.rint(scaled).astype(np.int64)
        return np.round(scaled, self.float_precision)

    def prob(self, row: str, col: str) -> float:
        r = self.table[self.row_labels.index(row)]
        tot = r.sum()
        return float(r[self.col_labels.index(col)] / tot) if tot > 0 else 0.0

    def serialize(self, delim: str = ",") -> str:
        rows = self.normalize_rows()
        return "\n".join(delim.join(str(v) for v in row) for row in rows)


class ContingencyMatrix:
    """Categorical x categorical contingency table with the Cramér index
    (util/ContingencyMatrix.java:28, consumed by CramerCorrelation)."""

    def __init__(self, num_rows: int, num_cols: int):
        self.table = np.zeros((num_rows, num_cols), np.float64)

    def add(self, row: int, col: int, count: float = 1.0) -> None:
        self.table[row, col] += count

    def accumulate(self, other: "ContingencyMatrix") -> None:
        self.table += other.table

    def total(self) -> float:
        return float(self.table.sum())

    def chi_squared(self) -> float:
        n = self.table.sum()
        if n <= 0:
            return 0.0
        expected = np.outer(self.table.sum(axis=1), self.table.sum(axis=0)) / n
        mask = expected > 0
        return float(((self.table - expected)[mask] ** 2 / expected[mask]).sum())

    def cramer_index(self) -> float:
        n = self.table.sum()
        if n <= 0:
            return 0.0
        k = min(self.table.shape) - 1
        if k <= 0:
            return 0.0
        return float(self.chi_squared() / (n * k))

    def serialize(self, delim: str = ",") -> str:
        return delim.join(str(int(v)) for v in self.table.ravel())

    @classmethod
    def deserialize(cls, text: str, num_rows: int, num_cols: int,
                    delim: str = ",") -> "ContingencyMatrix":
        m = cls(num_rows, num_cols)
        vals = [float(t) for t in text.strip().split(delim)]
        m.table = np.asarray(vals, np.float64).reshape(num_rows, num_cols)
        return m


@dataclass
class CostAttribute:
    """Attribute-change cost entry (util/CostAttribute.java:30): numeric
    cost per unit change, or categorical from,to -> cost map."""

    ordinal: int
    num_attr_cost: float = 0.0
    cat_attr_cost: Dict[str, float] = field(default_factory=dict)

    @classmethod
    def from_json(cls, obj: Dict) -> "CostAttribute":
        return cls(
            ordinal=int(obj["ordinal"]),
            num_attr_cost=float(obj.get("numAttrCost", 0.0)),
            cat_attr_cost={str(k): float(v)
                           for k, v in obj.get("catAttrCost", {}).items()},
        )


class CostSchema:
    """Attribute-change cost schema (util/CostSchema.java:27): the cost of
    moving an entity's attribute value, used for cost-based actionability
    analysis of model outputs."""

    def __init__(self, attributes: Sequence[CostAttribute]):
        self.attributes = {a.ordinal: a for a in attributes}

    @classmethod
    def from_json(cls, obj: Dict) -> "CostSchema":
        return cls([CostAttribute.from_json(a) for a in obj["attributes"]])

    @classmethod
    def from_file(cls, path: str) -> "CostSchema":
        with open(path) as fh:
            return cls.from_json(json.load(fh))

    def find_cost(self, ordinal: int, *args) -> float:
        """find_cost(ord, value_change) for numeric attributes;
        find_cost(ord, from_value, to_value) for categorical (missing
        pairs cost 0, CostSchema.java:59-71)."""
        attr = self.attributes.get(ordinal)
        if attr is None:
            raise ValueError(f"invalid attribute ordinal {ordinal}")
        if len(args) == 1:
            return attr.num_attr_cost * float(args[0])
        return attr.cat_attr_cost.get(f"{args[0]},{args[1]}", 0.0)


@dataclass
class ClassAttributeCounter:
    """Pos/neg class count pair (util/ClassAttributeCounter.java:25)."""

    pos_count: int = 0
    neg_count: int = 0

    def add(self, pos: int, neg: int) -> None:
        self.pos_count += pos
        self.neg_count += neg

    def update(self, pos: int, neg: int) -> None:
        self.pos_count = pos
        self.neg_count = neg

    @property
    def total(self) -> int:
        return self.pos_count + self.neg_count
