"""Accelerator availability probing: degrade to CPU during outages.

When the accelerator tunnel is down, any backend init (jax.devices(), the
first jit dispatch) hangs in-process indefinitely — there is no exception
to catch. The only reliable detection is a subprocess probe with a hard
timeout; the only reliable degrade is pinning the CPU platform BEFORE any
backend init in this process. The CLI runner uses this so every job keeps
working (slower, correct) through an outage instead of hanging silently —
the same degrade contract as bench.py and __graft_entry__.
"""

from __future__ import annotations

import os
import subprocess
import sys
from typing import Tuple

_PROBE_RESULT = None        # process-lifetime cache


def probe_accelerator(timeout_s: float = 60.0) -> Tuple[bool, str]:
    """(reachable, reason), probed in a subprocess with a hard timeout.
    The reason string separates a HANG (tunnel outage) from a CRASH
    (broken install) so operators debug the right thing."""
    try:
        proc = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices(); print('ok')"],
            capture_output=True, timeout=timeout_s, text=True)
    except subprocess.TimeoutExpired:
        return False, (f"device probe hung >{timeout_s:.0f}s "
                       "(transient tunnel outage)")
    if proc.returncode == 0 and "ok" in proc.stdout:
        return True, "ok"
    tail = (proc.stderr or proc.stdout).strip().splitlines()
    return False, ("backend probe crashed (broken jax/plugin install?): "
                   + (tail[-1] if tail else f"exit {proc.returncode}"))


def ensure_usable_backend(timeout_s: float = None) -> str:
    """Probe once per process; on an unreachable accelerator, pin the CPU
    platform so subsequent compute degrades instead of hanging. Returns
    the degrade reason, or "" when the accelerator is fine.

    Opt-outs: AVENIR_SKIP_DEVICE_PROBE=1 skips the probe entirely (e.g.
    when the caller already pinned a platform). A JAX_PLATFORMS env var
    leading with "cpu" is already hang-proof — no probe needed; any other
    value (the infra sets JAX_PLATFORMS=<accelerator> by default) still
    gets probed, because that is exactly the process that hangs."""
    global _PROBE_RESULT
    if os.environ.get("AVENIR_SKIP_DEVICE_PROBE"):
        return ""
    env_platforms = os.environ.get("JAX_PLATFORMS", "")
    if env_platforms.split(",")[0].strip() == "cpu":
        # the accelerator plugin's sitecustomize overrides the env var at
        # backend init (observed: JAX_PLATFORMS=cpu still hangs on a dead
        # tunnel); enforce the operator's choice via the config knob,
        # which the plugin cannot override
        import jax

        jax.config.update("jax_platforms", env_platforms)
        return ""
    if timeout_s is None:
        timeout_s = float(os.environ.get("AVENIR_DEVICE_PROBE_TIMEOUT", 60))
    if _PROBE_RESULT is None:
        _PROBE_RESULT = probe_accelerator(timeout_s)
    ok, reason = _PROBE_RESULT
    if ok:
        return ""
    import jax

    jax.config.update("jax_platforms", "cpu")
    return reason
