"""MCMC convergence diagnostics: Geweke and Raftery-Lewis.

Reference (python/lib/mcconverge.py, SURVEY §2.10): GewekeConvergence
computes a modified z-score comparing an early window (first 10% after
burn-in) against the last 50% for each candidate burn-in size
(mcconverge.py:13-37); RafteryLewisConvergence derives burn-in and sample
size from the 2-state (below/above a quantile threshold) chain's transition
matrix (:40-87 — the reference implementation has several typos; the
formulas here follow Raftery & Lewis 1992, which that code clearly
intends).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from math import erf, log, sqrt
from typing import List, Optional, Sequence, Tuple

import numpy as np


def _norm_ppf(p: float) -> float:
    """Inverse standard-normal CDF (Acklam's rational approximation)."""
    a = [-3.969683028665376e+01, 2.209460984245205e+02,
         -2.759285104469687e+02, 1.383577518672690e+02,
         -3.066479806614716e+01, 2.506628277459239e+00]
    b = [-5.447609879822406e+01, 1.615858368580409e+02,
         -1.556989798598866e+02, 6.680131188771972e+01,
         -1.328068155288572e+01]
    c = [-7.784894002430293e-03, -3.223964580411365e-01,
         -2.400758277161838e+00, -2.549732539343734e+00,
         4.374664141464968e+00, 2.938163982698783e+00]
    d = [7.784695709041462e-03, 3.224671290700398e-01,
         2.445134137142996e+00, 3.754408661907416e+00]
    plow, phigh = 0.02425, 1 - 0.02425
    if p < plow:
        q = sqrt(-2 * log(p))
        return (((((c[0] * q + c[1]) * q + c[2]) * q + c[3]) * q + c[4]) * q
                + c[5]) / ((((d[0] * q + d[1]) * q + d[2]) * q + d[3]) * q + 1)
    if p > phigh:
        return -_norm_ppf(1 - p)
    q = p - 0.5
    r = q * q
    return (((((a[0] * r + a[1]) * r + a[2]) * r + a[3]) * r + a[4]) * r
            + a[5]) * q / (((((b[0] * r + b[1]) * r + b[2]) * r + b[3]) * r
                            + b[4]) * r + 1)


def _norm_cdf(x: float) -> float:
    return 0.5 * (1.0 + erf(x / sqrt(2.0)))


@dataclass
class GewekeConvergence:
    """Geweke z-scores for a list of candidate burn-in sizes.

    z = (mean(A) - mean(B)) / sqrt(var(A)/|A| + var(B)/|B|) with A the
    first `window_a` fraction after burn-in and B the last `window_b`
    fraction; |z| < ~2 indicates convergence."""

    burn_in_sizes: Sequence[int]
    window_a: float = 0.1
    window_b: float = 0.5
    zscores: List[Tuple[int, int, float]] = field(default_factory=list)

    def calculate_zscores(self, data: Sequence[float]
                          ) -> List[Tuple[int, int, float]]:
        self.zscores = []
        x = np.asarray(data, np.float64)
        n = len(x)
        for bi in self.burn_in_sizes:
            rem = n - bi
            if rem < 4:
                continue
            a = x[bi: bi + max(int(rem * self.window_a), 2)]
            b = x[n - max(int(rem * self.window_b), 2):]
            se = sqrt(a.var() / len(a) + b.var() / len(b))
            z = float((a.mean() - b.mean()) / se) if se > 0 else 0.0
            self.zscores.append((n, int(bi), z))
        return self.zscores

    def converged(self, threshold: float = 2.0) -> bool:
        return bool(self.zscores) and abs(self.zscores[-1][2]) < threshold


@dataclass
class RafteryLewisConvergence:
    """Raftery-Lewis burn-in / sample-size estimate.

    Parameters mirror the reference's (k, s, r, e): `thinning_interval` k,
    `quantile` the probability q whose estimate is wanted, accuracy `r`
    (half-width of the tolerated interval), confidence `s`, and
    `trans_prob_conf_limit` e for the burn-in criterion.
    """

    thinning_interval: int = 1
    quantile: float = 0.025
    accuracy: float = 0.005
    confidence: float = 0.95
    trans_prob_conf_limit: float = 0.001

    def find_sample_size(self, data: Sequence[float],
                         threshold: Optional[float] = None
                         ) -> Tuple[int, int]:
        """Returns (burn_in_size, sample_size) in original (unthinned)
        iterations. `threshold` defaults to the `quantile`-quantile of the
        chain (the reference picks a random chain value)."""
        x = np.asarray(data, np.float64)[::self.thinning_interval]
        u = (np.quantile(x, self.quantile) if threshold is None
             else float(threshold))
        z = (x < u).astype(np.int64)
        # 2-state transition counts
        tr = np.zeros((2, 2), np.float64)
        np.add.at(tr, (z[:-1], z[1:]), 1.0)
        row = tr.sum(axis=1)
        if row[0] == 0 or row[1] == 0:
            return 0, len(x) * self.thinning_interval
        alpha = tr[0, 1] / row[0]                 # P(0 -> 1)
        beta = tr[1, 0] / row[1]                  # P(1 -> 0)
        ab = alpha + beta
        if ab <= 0 or ab >= 2:
            return 0, len(x) * self.thinning_interval
        lam = 1.0 - ab
        # burn-in: m with lam^m * max(alpha,beta)/ab <= e
        if abs(lam) < 1e-12:
            burn_in = 0.0
        else:
            burn_in = (log(self.trans_prob_conf_limit * ab / max(alpha, beta))
                       / log(abs(lam)))
        burn_in = max(burn_in, 0.0) * self.thinning_interval
        # sample size: n = alpha*beta*(2-ab)/ab^3 * (phi/r)^2
        phi = _norm_ppf(0.5 * (1.0 + self.confidence))
        n = (alpha * beta * (2.0 - ab) / ab ** 3) * (phi / self.accuracy) ** 2
        n *= self.thinning_interval
        return int(np.ceil(burn_in)), int(np.ceil(n))

    def n_min(self) -> int:
        """Minimum sample size assuming independence."""
        phi = _norm_ppf(0.5 * (1.0 + self.confidence))
        q = self.quantile
        return int(np.ceil(q * (1 - q) * (phi / self.accuracy) ** 2))
