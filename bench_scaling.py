"""Scaling-efficiency bench: distributed NB + KNN over 1/2/4/8-device meshes.

Prints ONE JSON line:
  {"metric": "scaling_efficiency_nb_knn", "value": <geomean efficiency at
   max devices>, "unit": "fraction_of_linear", "table": [...]}

Runs on real chips when the host has them; otherwise bootstraps a virtual
CPU device pool (same mechanism as __graft_entry__.dryrun_multichip). See
avenir_tpu/parallel/scaling.py for what the virtual numbers do and don't
mean.
"""

import json
import sys


def main(n_devices: int = 8):
    from __graft_entry__ import _bootstrap_devices

    devices = _bootstrap_devices(n_devices)
    from avenir_tpu.parallel.scaling import measure_scaling

    result = measure_scaling(devices)
    eff = result["efficiency_at_max"]
    value = float((eff["nb"] * eff["knn"]) ** 0.5)
    platform = devices[0].platform
    print(f"# platform={platform} table={result['table']}", file=sys.stderr)
    line = {
        "metric": "scaling_efficiency_nb_knn",
        "value": round(value, 3),
        "unit": "fraction_of_linear",
        "devices": eff["devices"],
        "platform": platform,
        "table": result["table"],
    }
    if result.get("virtual_devices"):
        line["virtual_devices"] = True
        line["note"] = result["note"]
    print(json.dumps(line))


if __name__ == "__main__":
    main(int(sys.argv[1]) if len(sys.argv) > 1 else 8)
