"""Scaling-efficiency bench: distributed NB + KNN over 1/2/4/8-device meshes.

Prints ONE JSON line:
  {"metric": "scaling_efficiency_nb_knn", "value": <geomean efficiency at
   max devices>, "unit": "fraction_of_linear", "table": [...]}

Runs on real chips when the host has them; otherwise bootstraps a virtual
CPU device pool (same mechanism as __graft_entry__.dryrun_multichip). See
avenir_tpu/parallel/scaling.py for what the virtual numbers do and don't
mean.
"""

import json
import sys


def main(n_devices: int = 8, quick: bool = False):
    from __graft_entry__ import _bootstrap_devices

    devices = _bootstrap_devices(n_devices)
    from avenir_tpu.parallel.scaling import measure_scaling

    # --quick: smoke-scale workloads (single-core hosts; CI)
    kw = dict(nb_rows_per_device=4_096, knn_queries_per_device=64,
              knn_train=1_024, iters=2) if quick else {}
    result = measure_scaling(devices, **kw)
    eff = result["efficiency_at_max"]
    value = float((eff["nb"] * eff["knn"]) ** 0.5)
    platform = devices[0].platform
    print(f"# platform={platform} table={result['table']}", file=sys.stderr)
    line = {
        "metric": "scaling_efficiency_nb_knn",
        "value": round(value, 3),
        "unit": "fraction_of_linear",
        "devices": eff["devices"],
        "platform": platform,
        "table": result["table"],
    }
    # HLO-validated collective-payload model + pod-scale projection
    for key in ("nb_hlo_allreduce_payload_bytes", "nb_analytic_payload_bytes",
                "payload_model_validated", "projection_8_to_256"):
        line[key] = result[key]
    if result.get("virtual_devices"):
        line["virtual_devices"] = True
        line["note"] = result["note"]
    print(json.dumps(line))


if __name__ == "__main__":
    args = [a for a in sys.argv[1:] if a != "--quick"]
    main(int(args[0]) if args else 8, quick="--quick" in sys.argv[1:])
